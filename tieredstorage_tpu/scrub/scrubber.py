"""Scrubber engine: one incremental integrity pass over the object store.

A pass has three stages:

1. **Enumerate** — `storage.list_objects(prefix)` builds the inventory; every
   `.rsm-manifest` key anchors a segment triple (`.log`, `.indexes`,
   manifest). Keys claimed by no manifest are orphans (left behind by a
   crashed upload whose rollback never ran, or by manual meddling).
2. **Verify** — each manifest's chunk index is cross-checked against the
   store: the `.log` object is stream-fetched in contiguous chunk batches
   (storage IO throttled through a `TokenBucket` so scrubbing never starves
   foreground fetches), every batch is CRC32C-verified against the manifest's
   `chunkChecksums` through the batched MXU kernel (`ops/crc32c.crc32c_batch`,
   host-table fallback), and transformed segments additionally round-trip
   detransform (AES-GCM tag check / decompress) — byte-identical coverage to
   a real fetch, without a consumer in the loop. The detransform runs under
   the BACKGROUND work class (`transform/scheduler.py`): its device windows
   join the shared scheduler's background admission class rather than racing
   foreground fetch decrypts. Size drift is caught structurally: short reads
   inside the chunk walk, range probes past the expected end.
3. **Repair** — corrupt/missing objects are re-uploaded from a supplied
   local segment source (`repair_source`) when one is available, orphans are
   deleted, and every corrupt object is pushed through the chunk-manager
   quarantine hook so broker fetch storms can't hammer it meanwhile.

Everything observed lands in a `ScrubReport` findings ledger, `scrub.*`
spans, and `scrub-metrics` sensors.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import BinaryIO, Callable, Optional

from tieredstorage_tpu.manifest.segment_manifest import SegmentManifestV1, manifest_from_json
from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
)
from tieredstorage_tpu.utils.ratelimit import TokenBucket
from tieredstorage_tpu.utils.streams import read_exactly
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

log = logging.getLogger(__name__)

MANIFEST_SUFFIX = ".rsm-manifest"
LOG_SUFFIX = ".log"
INDEXES_SUFFIX = ".indexes"

#: Finding kinds (the ledger's vocabulary).
CORRUPT_CHUNK = "corrupt-chunk"
MISSING_OBJECT = "missing-object"
TRUNCATED_OBJECT = "truncated-object"
OVERSIZED_OBJECT = "oversized-object"
ORPHAN_OBJECT = "orphan-object"
MANIFEST_UNREADABLE = "manifest-unreadable"

#: Kinds a `repair_source` re-upload can heal.
_REUPLOADABLE = (CORRUPT_CHUNK, MISSING_OBJECT, TRUNCATED_OBJECT, OVERSIZED_OBJECT)


@dataclasses.dataclass
class ScrubFinding:
    kind: str
    key: str
    detail: str = ""
    chunk_id: Optional[int] = None
    repaired: bool = False

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ScrubReport:
    """Findings ledger + work accounting of one scrub pass."""

    started_at: float = 0.0
    duration_s: float = 0.0
    objects_listed: int = 0
    manifests: int = 0
    chunks_verified: int = 0
    bytes_scanned: int = 0
    findings: list[ScrubFinding] = dataclasses.field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings

    @property
    def repaired(self) -> int:
        return sum(1 for f in self.findings if f.repaired)

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.kind] = out.get(f.kind, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "started_at": self.started_at,
            "duration_s": self.duration_s,
            "objects_listed": self.objects_listed,
            "manifests": self.manifests,
            "chunks_verified": self.chunks_verified,
            "bytes_scanned": self.bytes_scanned,
            "clean": self.clean,
            "repaired": self.repaired,
            "counts": self.counts(),
            "findings": [f.to_json() for f in self.findings],
        }


class Scrubber:
    """Stateless per-pass engine; counters accumulate across passes for the
    `scrub-metrics` gauges."""

    def __init__(
        self,
        storage: StorageBackend,
        *,
        prefix: str = "",
        transform_backend=None,
        data_key_decoder: Optional[Callable[[str], bytes]] = None,
        rate_bucket: Optional[TokenBucket] = None,
        batch_chunks: int = 16,
        repair_enabled: bool = False,
        repair_source: Optional[Callable[[ObjectKey], Optional[BinaryIO]]] = None,
        quarantine: Optional[Callable[[ObjectKey, str], None]] = None,
        verify_transforms: bool = True,
        tracer=NOOP_TRACER,
        metrics=None,
    ) -> None:
        if batch_chunks < 1:
            raise ValueError("batch_chunks must be >= 1")
        self._storage = storage
        self.prefix = prefix
        self._transform_backend = transform_backend
        self._data_key_decoder = data_key_decoder
        self._rate_bucket = rate_bucket
        self._batch_chunks = batch_chunks
        self.repair_enabled = repair_enabled
        self.repair_source = repair_source
        self._quarantine = quarantine
        self._verify_transforms = verify_transforms
        self.tracer = tracer
        self.metrics = metrics
        #: Cumulative counters, exported as scrub-metrics gauges.
        self.passes = 0
        self.findings_total = 0
        self.corrupt_chunks_total = 0
        self.orphans_total = 0
        self.missing_objects_total = 0
        self.repairs_total = 0
        self.bytes_scanned_total = 0
        self.chunks_verified_total = 0
        self.last_report: Optional[ScrubReport] = None

    # ------------------------------------------------------------------ pass
    def scrub_once(self) -> ScrubReport:
        # Monotonic: started_at orders passes and feeds duration math; it is
        # an instant on the process clock, not a calendar timestamp.
        report = ScrubReport(started_at=time.monotonic())
        start = time.monotonic()
        with self.tracer.span("scrub.pass", prefix=self.prefix):
            inventory = [k.value for k in self._storage.list_objects(self.prefix)]
            report.objects_listed = len(inventory)
            present = set(inventory)
            claimed: set[str] = set()
            for manifest_key in (k for k in inventory if k.endswith(MANIFEST_SUFFIX)):
                report.manifests += 1
                stem = manifest_key[: -len(MANIFEST_SUFFIX)]
                log_key = stem + LOG_SUFFIX
                indexes_key = stem + INDEXES_SUFFIX
                claimed.update((manifest_key, log_key, indexes_key))
                with self.tracer.span("scrub.segment", key=stem):
                    manifest = self._load_manifest(manifest_key, report)
                    if manifest is None:
                        continue
                    self._verify_log(log_key, manifest, present, report)
                    self._verify_indexes(indexes_key, manifest, present, report)
            for key in inventory:
                if key not in claimed:
                    self._orphan(key, report)
        report.duration_s = time.monotonic() - start
        self._account(report)
        return report

    def _account(self, report: ScrubReport) -> None:
        self.passes += 1
        self.findings_total += len(report.findings)
        self.bytes_scanned_total += report.bytes_scanned
        self.chunks_verified_total += report.chunks_verified
        self.repairs_total += report.repaired
        for f in report.findings:
            if f.kind == CORRUPT_CHUNK:
                self.corrupt_chunks_total += 1
            elif f.kind == ORPHAN_OBJECT:
                self.orphans_total += 1
            elif f.kind == MISSING_OBJECT:
                self.missing_objects_total += 1
        self.last_report = report
        if self.metrics is not None:
            self.metrics.record_pass(report)
        if report.findings:
            log.warning(
                "Scrub pass found %d issue(s): %s (%d repaired)",
                len(report.findings), report.counts(), report.repaired,
            )
        self.tracer.event(
            "scrub.pass_complete", findings=len(report.findings),
            bytes=report.bytes_scanned, chunks=report.chunks_verified,
        )

    # ------------------------------------------------------------- manifests
    def _load_manifest(
        self, manifest_key: str, report: ScrubReport
    ) -> Optional[SegmentManifestV1]:
        try:
            with self._storage.fetch(ObjectKey(manifest_key)) as stream:
                text = stream.read()
            self._throttle(len(text))
            report.bytes_scanned += len(text)
            return manifest_from_json(text, data_key_decoder=self._data_key_decoder)
        except Exception as e:  # noqa: BLE001 — any unreadable manifest is a finding
            self._finding(
                report,
                ScrubFinding(MANIFEST_UNREADABLE, manifest_key, f"{type(e).__name__}: {e}"),
            )
            return None

    # ------------------------------------------------------------ log object
    def _verify_log(
        self,
        log_key: str,
        manifest: SegmentManifestV1,
        present: set[str],
        report: ScrubReport,
    ) -> None:
        index = manifest.chunk_index
        expected_size = index.total_transformed_size
        key = ObjectKey(log_key)
        if log_key not in present:
            self._finding(
                report,
                ScrubFinding(MISSING_OBJECT, log_key, "log object absent from inventory"),
                repair_key=key,
            )
            return
        findings_before = len(report.findings)
        if index.original_file_size > 0 and expected_size > 0:
            chunks = index.chunks()
            for i in range(0, len(chunks), self._batch_chunks):
                if not self._verify_batch(
                    key, manifest, chunks[i : i + self._batch_chunks], report
                ):
                    break
        # Structural size probe: one byte past the expected end must be
        # unsatisfiable; a successful read means the object grew.
        if self._object_extends_past(key, expected_size):
            self._finding(
                report,
                ScrubFinding(
                    OVERSIZED_OBJECT, log_key,
                    f"object extends past the manifest's {expected_size} bytes",
                ),
            )
        self._maybe_repair(key, report, findings_before)

    def _verify_batch(self, key, manifest, chunks, report: ScrubReport) -> bool:
        """Fetch + verify one contiguous chunk window; False stops the walk."""
        batch_bytes = sum(c.transformed_size for c in chunks)
        self._throttle(batch_bytes)
        with self.tracer.span(
            "scrub.verify_batch", key=key.value, chunks=len(chunks), bytes=batch_bytes,
        ):
            whole = BytesRange.of(
                chunks[0].transformed_position,
                chunks[-1].transformed_position + chunks[-1].transformed_size - 1,
            )
            stored: list[bytes] = []
            try:
                with self._storage.fetch(key, whole) as stream:
                    for c in chunks:
                        stored.append(read_exactly(stream, c.transformed_size))
            except KeyNotFoundException:
                self._finding(
                    report,
                    ScrubFinding(MISSING_OBJECT, key.value, "log object vanished mid-scrub"),
                )
                return False
            except (EOFError, InvalidRangeException) as e:
                got = sum(len(b) for b in stored)
                self._finding(
                    report,
                    ScrubFinding(
                        TRUNCATED_OBJECT, key.value,
                        f"short read in chunks {chunks[0].id}..{chunks[-1].id}: {e}",
                        chunk_id=chunks[len(stored)].id if len(stored) < len(chunks) else None,
                    ),
                    quarantine_reason="truncated object",
                )
                report.bytes_scanned += got
                return False
            report.bytes_scanned += batch_bytes
            report.chunks_verified += len(chunks)
            bad = self._verify_checksums(key, manifest, chunks, stored, report)
            self._verify_detransform(key, manifest, chunks, stored, bad, report)
        return True

    def _verify_checksums(
        self, key, manifest, chunks, stored, report: ScrubReport
    ) -> set[int]:
        """CRC32C every fetched chunk against the manifest's recorded values
        (batched through the MXU log-tree kernel); returns bad chunk ids."""
        recorded = manifest.chunk_checksums
        if not recorded:
            return set()
        from tieredstorage_tpu.ops.crc32c import crc32c_batch

        got = crc32c_batch(stored)
        bad: set[int] = set()
        for c, crc in zip(chunks, got):
            want = recorded[c.id] if c.id < len(recorded) else None
            if crc != want:
                bad.add(c.id)
                self._finding(
                    report,
                    ScrubFinding(
                        CORRUPT_CHUNK, key.value,
                        f"CRC32C mismatch: stored {crc:#010x}, manifest "
                        f"{'absent' if want is None else f'{want:#010x}'}",
                        chunk_id=c.id,
                    ),
                    quarantine_reason=f"CRC32C mismatch on chunk {c.id}",
                )
        return bad

    def _verify_detransform(
        self, key, manifest, chunks, stored, already_bad: set[int], report: ScrubReport
    ) -> None:
        """GCM-tag / decompress round-trip for transformed segments: the same
        failure a real fetch would hit, caught before any consumer does.
        The device work runs under the BACKGROUND work class: with
        cross-request batching enabled, verification windows join the
        scheduler's background admission class (paced by
        ``scrub.rate.bytes`` scheduler-side, bounded-age starvation
        watchdog) instead of racing foreground fetch decrypts for the
        device — and a device failure mid-scrub wakes background waiters
        only, never a latency-class fetch."""
        if (
            not self._verify_transforms
            or self._transform_backend is None
            or (not manifest.compression and manifest.encryption is None)
        ):
            return
        from tieredstorage_tpu.transform.api import DetransformOptions
        from tieredstorage_tpu.transform.scheduler import (
            BACKGROUND,
            work_class_scope,
        )

        opts = DetransformOptions.from_manifest(manifest)
        clean = [(c, b) for c, b in zip(chunks, stored) if c.id not in already_bad]
        if not clean:
            return
        try:
            with work_class_scope(BACKGROUND):
                self._transform_backend.detransform([b for _, b in clean], opts)
            return
        except Exception:  # noqa: BLE001 — isolate the culprit chunk below
            pass
        for c, b in clean:
            try:
                with work_class_scope(BACKGROUND):
                    self._transform_backend.detransform([b], opts)
            except Exception as e:  # noqa: BLE001 — per-chunk verdict
                self._finding(
                    report,
                    ScrubFinding(
                        CORRUPT_CHUNK, key.value,
                        f"detransform failed: {type(e).__name__}: {e}",
                        chunk_id=c.id,
                    ),
                    quarantine_reason=f"detransform failure on chunk {c.id}",
                )

    # --------------------------------------------------------------- indexes
    def _verify_indexes(
        self,
        indexes_key: str,
        manifest: SegmentManifestV1,
        present: set[str],
        report: ScrubReport,
    ) -> None:
        expected = manifest.segment_indexes.total_size
        key = ObjectKey(indexes_key)
        if indexes_key not in present:
            if expected == 0:
                return  # all indexes empty → no object is correct
            self._finding(
                report,
                ScrubFinding(MISSING_OBJECT, indexes_key, "indexes object absent"),
                repair_key=key,
            )
            return
        findings_before = len(report.findings)
        self._throttle(expected)
        try:
            with self._storage.fetch(key) as stream:
                blob = stream.read()
        except KeyNotFoundException:
            self._finding(
                report,
                ScrubFinding(MISSING_OBJECT, indexes_key, "indexes object vanished mid-scrub"),
            )
            return
        report.bytes_scanned += len(blob)
        if len(blob) != expected:
            kind = TRUNCATED_OBJECT if len(blob) < expected else OVERSIZED_OBJECT
            self._finding(
                report,
                ScrubFinding(
                    kind, indexes_key,
                    f"indexes object is {len(blob)} bytes, manifest says {expected}",
                ),
            )
        self._maybe_repair(key, report, findings_before)

    # --------------------------------------------------------------- orphans
    def _orphan(self, key: str, report: ScrubReport) -> None:
        finding = ScrubFinding(ORPHAN_OBJECT, key, "claimed by no manifest")
        if self.repair_enabled:
            try:
                self._storage.delete(ObjectKey(key))
                finding.repaired = True
            except StorageBackendException as e:
                finding.detail += f"; cleanup failed: {e}"
        self._finding(report, finding)

    # --------------------------------------------------------------- helpers
    def _finding(
        self,
        report: ScrubReport,
        finding: ScrubFinding,
        *,
        quarantine_reason: Optional[str] = None,
        repair_key: Optional[ObjectKey] = None,
    ) -> None:
        report.findings.append(finding)
        self.tracer.event(
            "scrub.finding", kind=finding.kind, key=finding.key,
            chunk_id=finding.chunk_id,
        )
        if quarantine_reason is not None and self._quarantine is not None:
            try:
                self._quarantine(ObjectKey(finding.key), f"scrub: {quarantine_reason}")
            except Exception:  # noqa: BLE001 — quarantine must not fail the pass
                log.warning("Quarantine hook failed for %s", finding.key, exc_info=True)
        if repair_key is not None:
            finding.repaired = self._reupload(repair_key)

    def _maybe_repair(self, key: ObjectKey, report: ScrubReport, findings_before: int) -> None:
        """Re-upload a damaged object once per pass; marks the findings that
        triggered it repaired on success."""
        damaged = [
            f for f in report.findings[findings_before:]
            if f.kind in _REUPLOADABLE and f.key == key.value
        ]
        if not damaged:
            return
        if self._reupload(key):
            for f in damaged:
                f.repaired = True

    def _reupload(self, key: ObjectKey) -> bool:
        if not self.repair_enabled or self.repair_source is None:
            return False
        try:
            source = self.repair_source(key)
        except Exception:  # noqa: BLE001 — a broken source must not fail the pass
            log.warning("Repair source failed for %s", key, exc_info=True)
            return False
        if source is None:
            return False
        try:
            with source:
                self._storage.upload(source, key)
            self.tracer.event("scrub.repair", key=key.value)
            log.info("Scrub repaired %s by re-upload", key)
            return True
        except StorageBackendException:
            log.warning("Scrub re-upload failed for %s", key, exc_info=True)
            return False

    def _object_extends_past(self, key: ObjectKey, size: int) -> bool:
        try:
            with self._storage.fetch(key, BytesRange.of(size, size)) as stream:
                return bool(stream.read(1))
        except (InvalidRangeException, KeyNotFoundException):
            return False
        except StorageBackendException:
            return False

    def _throttle(self, n_bytes: int) -> None:
        """Consume scrub STORAGE-IO budget (ranged fetches, index reads);
        batches larger than the bucket capacity are drained in
        capacity-sized slices so big windows still pace correctly
        (TokenBucket.consume clamps single requests at capacity). Device
        GCM work is NOT throttled here: with cross-request batching
        enabled, verification windows are paced by the device scheduler's
        background admission class instead (the rsm wiring maps
        ``scrub.rate.bytes`` onto both)."""
        bucket = self._rate_bucket
        if bucket is None:
            return
        remaining = n_bytes
        while remaining > 0:
            take = min(remaining, bucket.capacity)
            bucket.consume(take)
            remaining -= take
