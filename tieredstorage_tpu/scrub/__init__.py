"""Background integrity scrubbing: detect-verify-repair over the object store.

The paper's design trusts object storage blindly — once a segment is uploaded
nothing re-reads it until a fetch hits it, so bit-rot, truncation, or a lost
object surfaces as a user-facing read error months later. This subsystem is
the proactive third leg next to fault injection (faults/) and tracing/metrics
(utils/tracing.py, metrics/): enumerate (`StorageBackend.list_objects`),
cross-check manifests against the inventory, batch-verify chunk CRC32C
(ops/crc32c) and GCM/decompress round-trips, quarantine what is poisoned,
and heal what is repairable — the same shape Ceph deep-scrub, ZFS scrub, and
S3's internal auditors grew.
"""

from tieredstorage_tpu.scrub.metrics import SCRUB_METRIC_GROUP, ScrubMetrics
from tieredstorage_tpu.scrub.scheduler import ScrubScheduler
from tieredstorage_tpu.scrub.scrubber import (
    INDEXES_SUFFIX,
    LOG_SUFFIX,
    MANIFEST_SUFFIX,
    ScrubFinding,
    ScrubReport,
    Scrubber,
)
from tieredstorage_tpu.scrub.sweeper import (
    RecoverySweeper,
    SweeperInvariantError,
    SweepReport,
    SweepScheduler,
)

__all__ = [
    "INDEXES_SUFFIX",
    "LOG_SUFFIX",
    "MANIFEST_SUFFIX",
    "SCRUB_METRIC_GROUP",
    "RecoverySweeper",
    "ScrubFinding",
    "ScrubMetrics",
    "ScrubReport",
    "ScrubScheduler",
    "Scrubber",
    "SweepReport",
    "SweepScheduler",
    "SweeperInvariantError",
]
