"""Anti-entropy repair: converge the replicas of a ReplicatedStorageBackend.

Quorum writes and read failover keep the *service* available through a
replica outage, but they leave the replicas themselves divergent: a write
that met quorum at 2/3 never reached the third replica, and a replica
restored from a snapshot may hold stale bytes. This daemon is the repair
leg (Dynamo §4.7 anti-entropy; the scrubber's detect-verify-repair shape
applied *across* replicas instead of within one store):

1. **Diff** — enumerate every replica by prefix (the same
   `StorageBackend.list_objects` leg the scrubber uses) and fetch + hash
   the bytes of every key that any replica holds.
2. **Arbitrate** — when versions diverge, pick the canonical copy:
   a `.log` object is verified against its manifest's ``chunkChecksums``
   (the at-rest ground truth PR 3 records at upload, checked through
   `ops/crc32c.crc32c_batch`); otherwise the majority content wins, with
   replica health order breaking ties.
3. **Repair** — copy the canonical bytes to every replica that is missing
   the key or holds a divergent version, counting repairs and emitting
   ``replication.repair`` trace events.

A pass over converged replicas reports zero diffs — the failover demo's
convergence gate. Deletion semantics: `ReplicatedStorageBackend.delete`
raises unless every replica converged, precisely so this pass cannot
resurrect a half-deleted object; a key deliberately removed everywhere is
simply absent from every listing.

Byte-level hashing reads every replicated object once per pass, throttled
by the same `TokenBucket` budget the scrubber uses; deployments with very
large stores should scope passes with `prefix`.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import io
import json
import logging
import threading
import time
from typing import Optional

from tieredstorage_tpu.scrub.scrubber import LOG_SUFFIX, MANIFEST_SUFFIX
from tieredstorage_tpu.storage.core import KeyNotFoundException, ObjectKey
from tieredstorage_tpu.storage.replicated import ReplicatedStorageBackend, ReplicaState
from tieredstorage_tpu.utils.ratelimit import TokenBucket
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

log = logging.getLogger(__name__)


@dataclasses.dataclass
class AntiEntropyReport:
    """Work ledger of one anti-entropy pass."""

    started_at: float = 0.0
    duration_s: float = 0.0
    keys_checked: int = 0
    bytes_compared: int = 0
    missing_copies: int = 0
    divergent_keys: int = 0
    repairs: int = 0
    repair_failures: int = 0
    unreadable_replicas: int = 0

    @property
    def in_sync(self) -> bool:
        """True when the pass found zero differences (nothing to repair)."""
        return self.missing_copies == 0 and self.divergent_keys == 0

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["in_sync"] = self.in_sync
        return out


class AntiEntropyRepairer:
    """Stateless per-pass engine over a ReplicatedStorageBackend; cumulative
    counters feed the `replication-metrics` gauges."""

    def __init__(
        self,
        replicated: ReplicatedStorageBackend,
        *,
        prefix: str = "",
        rate_bucket: Optional[TokenBucket] = None,
        tracer=NOOP_TRACER,
    ) -> None:
        self._replicated = replicated
        self.prefix = prefix
        self._rate_bucket = rate_bucket
        self.tracer = tracer
        #: Cumulative counters, exported as replication-metrics gauges.
        self.passes = 0
        self.repairs_total = 0
        self.diffs_total = 0
        self.last_report: Optional[AntiEntropyReport] = None

    # ------------------------------------------------------------------ pass
    def run_once(self) -> AntiEntropyReport:
        from tieredstorage_tpu.transform.scheduler import (
            BACKGROUND,
            work_class_scope,
        )

        # Monotonic, like ScrubReport.started_at: an ordering instant on the
        # process clock, not a calendar timestamp.
        report = AntiEntropyReport(started_at=time.monotonic())
        start = time.monotonic()
        # The whole pass runs background-class: any device GCM work its
        # hashing/repair walk triggers joins the scheduler's background
        # admission class with the scrubber's, never a foreground bucket.
        with work_class_scope(BACKGROUND), self.tracer.span(
            "replication.antientropy", prefix=self.prefix
        ):
            replicas = self._replicated.replica_states
            listings = self._list_all(replicas, report)
            all_keys = sorted(set().union(*listings.values())) if listings else []
            for key in all_keys:
                self._converge_key(key, replicas, listings, report)
        report.duration_s = time.monotonic() - start
        self.passes += 1
        self.repairs_total += report.repairs
        self.diffs_total += report.missing_copies + report.divergent_keys
        self.last_report = report
        self.tracer.event(
            "replication.antientropy_complete", keys=report.keys_checked,
            repairs=report.repairs, in_sync=report.in_sync,
        )
        if not report.in_sync:
            log.warning(
                "Anti-entropy pass: %d missing cop(ies), %d divergent key(s), "
                "%d repaired", report.missing_copies, report.divergent_keys,
                report.repairs,
            )
        return report

    def _list_all(
        self, replicas: list[ReplicaState], report: AntiEntropyReport
    ) -> dict[str, set[str]]:
        listings: dict[str, set[str]] = {}
        for rep in replicas:
            try:
                listings[rep.name] = {
                    k.value for k in rep.backend.list_objects(self.prefix)
                }
            except Exception:  # noqa: BLE001 — a dark replica skips this pass
                report.unreadable_replicas += 1
                log.warning(
                    "Anti-entropy cannot list replica %s; skipping it this pass",
                    rep.name, exc_info=True,
                )
        return listings

    def _converge_key(
        self,
        key: str,
        replicas: list[ReplicaState],
        listings: dict[str, set[str]],
        report: AntiEntropyReport,
    ) -> None:
        report.keys_checked += 1
        # Health-ordered so the tie-break and the repair source prefer the
        # replica reads already trust most.
        ordered = [rep for rep in self._ordered(replicas) if rep.name in listings]
        versions: dict[bytes, list[ReplicaState]] = {}
        contents: dict[bytes, bytes] = {}
        missing: list[ReplicaState] = []
        for rep in ordered:
            if key not in listings[rep.name]:
                missing.append(rep)
                continue
            data = self._read(rep, key)
            if data is None:
                missing.append(rep)  # listed but unreadable → treat as absent
                continue
            report.bytes_compared += len(data)
            digest = hashlib.sha256(data).digest()
            versions.setdefault(digest, []).append(rep)
            contents[digest] = data
        if not versions:
            return
        if len(versions) > 1:
            report.divergent_keys += 1
        report.missing_copies += len(missing)
        canonical = self._arbitrate(key, versions, contents, ordered)
        data = contents[canonical]
        holders = {rep.name for rep in versions[canonical]}
        for rep in ordered:
            if rep.name in holders:
                continue
            reason = "missing" if rep in missing else "divergent"
            self._throttle(len(data))
            try:
                rep.backend.upload(io.BytesIO(data), ObjectKey(key))
            except Exception:  # noqa: BLE001 — one bad copy must not end the pass
                report.repair_failures += 1
                log.warning(
                    "Anti-entropy failed to repair %s on replica %s",
                    key, rep.name, exc_info=True,
                )
                continue
            report.repairs += 1
            self.tracer.event(
                "replication.repair", key=key, replica=rep.name, reason=reason,
                bytes=len(data),
            )

    def _ordered(self, replicas: list[ReplicaState]) -> list[ReplicaState]:
        return sorted(replicas, key=lambda rep: rep.health_score(), reverse=True)

    def _read(self, rep: ReplicaState, key: str) -> Optional[bytes]:
        try:
            with rep.backend.fetch(ObjectKey(key)) as stream:
                data = stream.read()
        except KeyNotFoundException:
            return None
        except Exception:  # noqa: BLE001 — unreadable copy = candidate for repair
            log.warning(
                "Anti-entropy cannot read %s from replica %s", key, rep.name,
                exc_info=True,
            )
            return None
        self._throttle(len(data))
        return data

    # ------------------------------------------------------------ arbitration
    def _arbitrate(
        self,
        key: str,
        versions: dict[bytes, list[ReplicaState]],
        contents: dict[bytes, bytes],
        ordered: list[ReplicaState],
    ) -> bytes:
        """Pick the canonical digest among divergent versions.

        `.log` objects have recorded ground truth: the manifest's
        ``chunkChecksums`` (PR 3) arbitrate exactly — a two-replica split
        is always a 1-1 majority tie, and checksums resolve it for the
        objects that carry the actual payload. Everything else falls back
        to majority content, then replica health order."""
        if len(versions) == 1:
            return next(iter(versions))
        if key.endswith(LOG_SUFFIX):
            checksums, chunks = self._recorded_checksums(key, ordered)
            if checksums is not None:
                verified = [
                    d for d, data in contents.items()
                    if self._matches_checksums(data, checksums, chunks)
                ]
                if len(verified) == 1:
                    self.tracer.event(
                        "replication.arbitrated", key=key, how="chunk-checksums",
                    )
                    return verified[0]
        by_rank: dict[bytes, int] = {}
        for rank, rep in enumerate(ordered):
            for digest, holders in versions.items():
                if rep in holders and digest not in by_rank:
                    by_rank[digest] = rank
        return max(
            versions,
            key=lambda d: (len(versions[d]), -by_rank.get(d, len(ordered))),
        )

    def _recorded_checksums(self, log_key: str, ordered: list[ReplicaState]):
        """(chunkChecksums, chunk list) from the segment's manifest on any
        replica, parsed without requiring the data-key decoder (checksums
        and chunk geometry are plaintext fields)."""
        from tieredstorage_tpu.manifest.chunk_index import chunk_index_from_json

        manifest_key = log_key[: -len(LOG_SUFFIX)] + MANIFEST_SUFFIX
        for rep in ordered:
            try:
                with rep.backend.fetch(ObjectKey(manifest_key)) as stream:
                    obj = json.loads(stream.read())
                raw = obj.get("chunkChecksums")
                if raw is None:
                    return None, None
                blob = base64.b64decode(raw)
                checksums = [
                    int.from_bytes(blob[i : i + 4], "big")
                    for i in range(0, len(blob), 4)
                ]
                return checksums, chunk_index_from_json(obj["chunkIndex"]).chunks()
            except KeyNotFoundException:
                continue
            except Exception:  # noqa: BLE001 — an unreadable manifest can't arbitrate
                log.warning(
                    "Anti-entropy cannot use manifest %s for arbitration",
                    manifest_key, exc_info=True,
                )
                return None, None
        return None, None

    @staticmethod
    def _matches_checksums(data: bytes, checksums: list[int], chunks) -> bool:
        from tieredstorage_tpu.ops.crc32c import crc32c_batch

        if chunks and len(data) != (
            chunks[-1].transformed_position + chunks[-1].transformed_size
        ):
            return False
        slices = [
            data[c.transformed_position : c.transformed_position + c.transformed_size]
            for c in chunks
        ]
        if len(slices) != len(checksums):
            return False
        return crc32c_batch(slices) == checksums

    def _throttle(self, n_bytes: int) -> None:
        bucket = self._rate_bucket
        if bucket is None or n_bytes <= 0:
            return
        remaining = n_bytes
        while remaining > 0:
            take = min(remaining, bucket.capacity)
            bucket.consume(take)
            remaining -= take


class AntiEntropyScheduler:
    """Daemon thread running anti-entropy passes on a fixed period (same
    survive-a-bad-pass contract as ScrubScheduler; the scrub scheduler is
    not reused because its status surface is scrubber-shaped)."""

    def __init__(self, repairer: AntiEntropyRepairer, *, interval_ms: int) -> None:
        if interval_ms < 1:
            raise ValueError("interval_ms must be >= 1")
        self.repairer = repairer
        self.interval_s = interval_ms / 1000.0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_error: Optional[str] = None

    def start(self) -> "AntiEntropyScheduler":
        if self._thread is not None:
            raise RuntimeError("AntiEntropyScheduler already started")
        self._thread = threading.Thread(
            target=self._run, name="anti-entropy", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def run_now(self) -> None:
        self._wake.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.repairer.run_once()
                self._last_error = None
            except Exception as e:  # noqa: BLE001 — the loop must survive a bad pass
                self._last_error = f"{type(e).__name__}: {e}"
                log.warning("Anti-entropy pass failed", exc_info=True)

    def status(self) -> dict:
        repairer = self.repairer
        out = {
            "interval_ms": int(self.interval_s * 1000),
            "passes": repairer.passes,
            "repairs_total": repairer.repairs_total,
            "diffs_total": repairer.diffs_total,
            "last_error": self._last_error,
        }
        if repairer.last_report is not None:
            out["last_pass"] = repairer.last_report.to_json()
        return out
