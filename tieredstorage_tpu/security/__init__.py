"""Envelope encryption (reference L5): AES-256-GCM data keys wrapped by RSA KEKs.

Reference: core/src/main/java/io/aiven/kafka/tieredstorage/security/.
"""

from tieredstorage_tpu.security.aes import AesEncryptionProvider, DataKeyAndAAD
from tieredstorage_tpu.security.keys import EncryptedDataKey
from tieredstorage_tpu.security.rsa import RsaEncryptionProvider, RsaKeyReader

__all__ = [
    "AesEncryptionProvider",
    "DataKeyAndAAD",
    "EncryptedDataKey",
    "RsaEncryptionProvider",
    "RsaKeyReader",
]
