"""AES-256-GCM data-key provider (host/CPU path).

Reference: core/.../security/AesEncryptionProvider.java — AES-256, GCM with
128-bit tag and 12-byte IV (constants :36-39), a fresh DEK + AAD pair per
segment from two independent key generations (:52-58; the reference comments
that deriving AAD from the DEK would be a security flaw), fresh random IV per
chunk with ciphertext layout `IV || ciphertext || tag` (the `cryptography`
AEAD API emits ciphertext||tag, matching JDK GCM output).

The TPU path (ops/aes.py + ops/ghash.py) produces identical bytes for the
same (key, iv, aad, plaintext); this module is the correctness oracle and the
non-TPU fallback.
"""

from __future__ import annotations

import dataclasses
import os

try:  # Optional dependency: only the encrypt/decrypt paths need it, so the
    # module (and everything importing DataKeyAndAAD) stays importable and
    # unencrypted pipelines keep working without `cryptography` installed.
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover - exercised only without cryptography
    AESGCM = None

    class InvalidTag(Exception):  # type: ignore[no-redef]
        """Stand-in so callers can catch aes.InvalidTag unconditionally."""


def _aesgcm(data_key: bytes) -> "AESGCM":
    if AESGCM is None:
        raise ModuleNotFoundError(
            "The 'cryptography' package is required for AES-GCM encryption "
            "(encryption.enabled) but is not installed"
        )
    return AESGCM(data_key)


KEY_SIZE = 32  # AES-256
IV_SIZE = 12
TAG_SIZE = 16
AAD_SIZE = 32


@dataclasses.dataclass(frozen=True)
class DataKeyAndAAD:
    data_key: bytes
    aad: bytes


class AesEncryptionProvider:
    @staticmethod
    def create_data_key_and_aad() -> DataKeyAndAAD:
        # Two independent random draws, like the reference's two generateKey()
        # calls (AesEncryptionProvider.java:52-58).
        return DataKeyAndAAD(data_key=os.urandom(KEY_SIZE), aad=os.urandom(AAD_SIZE))

    @staticmethod
    def encrypt_chunk(plaintext: bytes, data_key: bytes, aad: bytes, iv: bytes | None = None) -> bytes:
        """Returns IV || ciphertext || tag; a fresh random IV unless given."""
        if iv is None:
            iv = os.urandom(IV_SIZE)
        if len(iv) != IV_SIZE:
            raise ValueError(f"IV must be {IV_SIZE} bytes")
        return iv + _aesgcm(data_key).encrypt(iv, plaintext, aad)

    @staticmethod
    def decrypt_chunk(transformed: bytes, data_key: bytes, aad: bytes) -> bytes:
        """Inverse of encrypt_chunk: reads the IV from the chunk head
        (reference: DecryptionChunkEnumeration.java:54-62)."""
        if len(transformed) < IV_SIZE + TAG_SIZE:
            raise ValueError("Encrypted chunk shorter than IV+tag")
        iv, ct = transformed[:IV_SIZE], transformed[IV_SIZE:]
        return _aesgcm(data_key).decrypt(iv, ct, aad)

    @staticmethod
    def encrypted_chunk_size(plaintext_size: int) -> int:
        """Fixed size growth: IV + plaintext + tag (GCM is length-preserving).

        Reference: EncryptionChunkEnumeration.encryptedChunkSize:82-84.
        """
        return IV_SIZE + plaintext_size + TAG_SIZE
