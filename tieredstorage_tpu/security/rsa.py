"""RSA key-encryption-key ring with OAEP(SHA3-512, MGF1-SHA3-512) enveloping.

Reference: core/.../security/RsaEncryptionProvider.java (keyring of
`keyId -> KeyPair`, active key id, `RSA/NONE/OAEPWithSHA3-512AndMGF1Padding`
via BouncyCastle :40-43) and RsaKeyReader.java:38-82 (PEM X509 public /
PKCS8 private).

The host OpenSSL backend doesn't support OAEP with SHA3-512, so the padding
is implemented here per RFC 8017 (EME-OAEP, empty label, MGF1 sharing the
OAEP digest — BouncyCastle's convention for that named transformation) over
raw RSA bigint math. Enveloping happens once per segment, so performance is
irrelevant; wire format matches the reference.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from pathlib import Path
from typing import Mapping

try:  # Optional dependency: PEM parsing / keygen only; the OAEP math below
    # is dependency-free, and unencrypted deployments never reach either.
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
except ImportError:  # pragma: no cover - exercised only without cryptography
    serialization = None
    rsa = None

from tieredstorage_tpu.security.keys import EncryptedDataKey


def _require_crypto() -> None:
    if rsa is None:
        raise ModuleNotFoundError(
            "The 'cryptography' package is required for RSA key handling "
            "(encryption.enabled) but is not installed"
        )

_HASH = hashlib.sha3_512


@dataclasses.dataclass(frozen=True)
class KeyPair:
    public_key: rsa.RSAPublicKey
    private_key: rsa.RSAPrivateKey


class RsaKeyReader:
    """PEM files -> KeyPair (X509/SubjectPublicKeyInfo public, PKCS8 private)."""

    @staticmethod
    def read(public_key_path: str | Path, private_key_path: str | Path) -> KeyPair:
        _require_crypto()
        try:
            pub_pem = Path(public_key_path).read_bytes()
            priv_pem = Path(private_key_path).read_bytes()
        except OSError as e:
            raise ValueError(f"Couldn't read RSA key pair paths: {e}") from e
        public_key = serialization.load_pem_public_key(pub_pem)
        private_key = serialization.load_pem_private_key(priv_pem, password=None)
        if not isinstance(public_key, rsa.RSAPublicKey) or not isinstance(
            private_key, rsa.RSAPrivateKey
        ):
            raise ValueError("Key pair files must contain RSA keys")
        return KeyPair(public_key, private_key)


# --- RFC 8017 EME-OAEP with SHA3-512 ---

def _mgf1(seed: bytes, length: int, hash_fn=_HASH) -> bytes:
    h_len = hash_fn(b"").digest_size
    out = bytearray()
    for counter in range(-(-length // h_len)):
        out += hash_fn(seed + counter.to_bytes(4, "big")).digest()
    return bytes(out[:length])


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


# `hash_fn` defaults to the production SHA3-512; tests inject SHA-256 to
# cross-verify the EME-OAEP structure byte-for-byte against the
# `cryptography` library (whose OpenSSL backend lacks SHA3 OAEP — the very
# reason this implementation exists).

def _oaep_encode(message: bytes, k: int, hash_fn=_HASH) -> bytes:
    h_len = hash_fn(b"").digest_size
    max_len = k - 2 * h_len - 2
    if len(message) > max_len:
        raise ValueError(f"Message too long for OAEP: {len(message)} > {max_len}")
    l_hash = hash_fn(b"").digest()
    ps = b"\x00" * (k - len(message) - 2 * h_len - 2)
    db = l_hash + ps + b"\x01" + message
    seed = os.urandom(h_len)
    masked_db = _xor(db, _mgf1(seed, k - h_len - 1, hash_fn))
    masked_seed = _xor(seed, _mgf1(masked_db, h_len, hash_fn))
    return b"\x00" + masked_seed + masked_db


def _oaep_decode(em: bytes, k: int, hash_fn=_HASH) -> bytes:
    """EME-OAEP decode, single-exit with a reduced (not eliminated) timing
    signal.

    All padding checks are evaluated unconditionally and OR-folded into one
    error (RFC 8017 §9.1.1.3 / Manger: distinct early exits on y, lHash,
    and the PS scan would leak which check failed through timing); only the
    public length precondition fails fast, and the lHash compare itself is
    constant-time. This is NOT fully constant-time: the per-byte Python
    loop, _xor, and _mgf1 are variable-time in CPython, so a residual
    data-dependent signal remains — the single-exit structure narrows the
    Manger oracle rather than closing it. Keys here wrap data keys inside a
    trusted broker process (no network-facing decryption oracle), which is
    why the remaining leak is accepted rather than rebuilt branchless."""
    import hmac

    h_len = hash_fn(b"").digest_size
    if len(em) != k or k < 2 * h_len + 2:
        raise ValueError("Decryption error")
    y, masked_seed, masked_db = em[0], em[1 : 1 + h_len], em[1 + h_len :]
    seed = _xor(masked_seed, _mgf1(masked_db, h_len, hash_fn))
    db = _xor(masked_db, _mgf1(seed, k - h_len - 1, hash_fn))
    l_hash = hash_fn(b"").digest()
    bad = y != 0
    bad |= not hmac.compare_digest(db[:h_len], l_hash)
    # Scan the whole post-lHash region without early exit: PS must be all
    # zero up to a mandatory 0x01 separator.
    sep = -1
    seen_nonzero_before_sep = False
    for i in range(h_len, len(db)):
        b = db[i]
        if sep < 0:
            if b == 1:
                sep = i
            elif b != 0:
                seen_nonzero_before_sep = True
    bad |= sep < 0
    bad |= seen_nonzero_before_sep
    if bad:
        raise ValueError("Decryption error")
    return db[sep + 1 :]


def _rsa_public_op(public_key: rsa.RSAPublicKey, data: int) -> int:
    numbers = public_key.public_numbers()
    return pow(data, numbers.e, numbers.n)


def _rsa_private_op(private_key: rsa.RSAPrivateKey, data: int) -> int:
    numbers = private_key.private_numbers()
    n = numbers.public_numbers.n
    # CRT for ~4x speedup over pow(data, d, n).
    m1 = pow(data % numbers.p, numbers.dmp1, numbers.p)
    m2 = pow(data % numbers.q, numbers.dmq1, numbers.q)
    h = ((m1 - m2) * numbers.iqmp) % numbers.p
    return m2 + h * numbers.q


class RsaEncryptionProvider:
    """KEK ring with one active key for encryption; any ring key can decrypt.

    Reference: core/.../security/RsaEncryptionProvider.java:36-102.
    """

    def __init__(self, active_key_id: str, keyring: Mapping[str, KeyPair]):
        if active_key_id not in keyring:
            raise ValueError(f"Active key id {active_key_id!r} not in keyring {sorted(keyring)}")
        self.active_key_id = active_key_id
        self._keyring = dict(keyring)

    @staticmethod
    def from_pem_files(
        active_key_id: str, key_pair_paths: Mapping[str, tuple[str | Path, str | Path]]
    ) -> "RsaEncryptionProvider":
        keyring = {
            key_id: RsaKeyReader.read(pub, priv)
            for key_id, (pub, priv) in key_pair_paths.items()
        }
        return RsaEncryptionProvider(active_key_id, keyring)

    def encrypt_data_key(self, data_key: bytes) -> EncryptedDataKey:
        public_key = self._keyring[self.active_key_id].public_key
        k = (public_key.key_size + 7) // 8
        em = _oaep_encode(data_key, k)
        c = _rsa_public_op(public_key, int.from_bytes(em, "big"))
        return EncryptedDataKey(self.active_key_id, c.to_bytes(k, "big"))

    def decrypt_data_key(self, encrypted: EncryptedDataKey) -> bytes:
        key_pair = self._keyring.get(encrypted.key_encryption_key_id)
        if key_pair is None:
            raise ValueError(
                f"Unknown key encryption key id: {encrypted.key_encryption_key_id!r}"
            )
        k = (key_pair.private_key.key_size + 7) // 8
        m = _rsa_private_op(key_pair.private_key, int.from_bytes(encrypted.encrypted_data_key, "big"))
        return _oaep_decode(m.to_bytes(k, "big"), k)

    # --- manifest serde hooks (manifest.segment_manifest DataKeyEncoder/Decoder) ---
    def data_key_encoder(self, data_key: bytes) -> str:
        return self.encrypt_data_key(data_key).serialize()

    def data_key_decoder(self, s: str) -> bytes:
        return self.decrypt_data_key(EncryptedDataKey.parse(s))


def generate_key_pair_pem_files(
    directory: str | Path, key_size: int = 2048, prefix: str = "test"
) -> tuple[Path, Path]:
    """Generate an RSA pair and write PEM files; returns (public, private) paths.

    The analogue of the reference's RsaKeyAwareTest fixture
    (core/src/test/java/.../RsaKeyAwareTest.java).
    """
    _require_crypto()
    directory = Path(directory)
    private_key = rsa.generate_private_key(public_exponent=65537, key_size=key_size)
    priv_pem = private_key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    pub_pem = private_key.public_key().public_bytes(
        serialization.Encoding.PEM, serialization.PublicFormat.SubjectPublicKeyInfo
    )
    pub_path = directory / f"{prefix}_public.pem"
    priv_path = directory / f"{prefix}_private.pem"
    pub_path.write_bytes(pub_pem)
    priv_path.write_bytes(priv_pem)
    return pub_path, priv_path
