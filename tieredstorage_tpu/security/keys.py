"""Encrypted-data-key string form: `<keyId>:<base64(encrypted DEK)>`.

Reference: core/.../security/EncryptedDataKey.java:38-60.
"""

from __future__ import annotations

import base64
import dataclasses


@dataclasses.dataclass(frozen=True)
class EncryptedDataKey:
    key_encryption_key_id: str
    encrypted_data_key: bytes

    def __post_init__(self) -> None:
        if not self.key_encryption_key_id:
            raise ValueError("keyEncryptionKeyId cannot be empty")
        if ":" in self.key_encryption_key_id:
            raise ValueError("keyEncryptionKeyId cannot contain ':'")
        if not self.encrypted_data_key:
            raise ValueError("encryptedDataKey cannot be empty")

    def serialize(self) -> str:
        return (
            self.key_encryption_key_id
            + ":"
            + base64.b64encode(self.encrypted_data_key).decode("ascii")
        )

    @staticmethod
    def parse(s: str) -> "EncryptedDataKey":
        key_id, sep, b64 = s.partition(":")
        if not sep or not key_id or not b64:
            raise ValueError(f"Malformed encrypted data key string: {s!r}")
        return EncryptedDataKey(key_id, base64.b64decode(b64))
