"""Guarded-by data-race inference: every shared mutable attribute has ONE
guarding lock, held at every write.

The lock-order checker proves acquisition ORDER; it says nothing about
GUARDEDNESS — ``DispatchStats`` relying on a docstring sentence ("mutated
only from the dispatching thread") is exactly the kind of invariant three
perf PRs made load-bearing with zero mechanical enforcement. This checker
applies the lockset idea of Eraser/ThreadSanitizer (see PAPERS.md) at the
AST level, reusing lockorder.py's held-stack walk and call resolution:

1. **Thread reachability.** Entry points that run on another thread are
   seeded mechanically: ``threading.Thread(target=...)`` targets (the
   sanctioned-daemon registry's spawn sites) and every callable handed to
   ``Executor.submit``/``map``. Their static call closure (via the
   lock-order summaries) marks classes whose instances are reachable from
   more than one thread; classes that OWN a witnessed lock are shared by
   self-declaration, and ``SHARED_CLASSES`` names the instances the
   resolver cannot prove (with the reason).

2. **Guarded-by inference.** For each shared class, every non-``__init__``
   write to a ``self`` attribute is collected with the lock stack held at
   the site — including locks inherited interprocedurally: a private
   method only ever called under ``self._lock`` (``*_locked`` helpers)
   analyzes with that lock held (entry-held sets are the intersection over
   all intra-class call sites, propagated to a fixed point; public methods
   and thread entry points start with nothing held). The attribute's guard
   is the lock held at the MAJORITY of its write sites (attributes are
   keyed by their root: all ``self.stats.*`` writes share one guard).

3. **Findings.** With a guard inferred: every write outside it is flagged
   (``torn-rmw`` for ``self.x += 1`` — a lost-update race even on
   CPython — ``unguarded-write`` otherwise). With no guard inferred, only
   augmented writes with NO lock held are flagged: a bare rebinding
   assignment may be a benign publish, but ``+=`` is always a
   read-modify-write.

Escape hatches are themselves checked inventory: a trailing
``# tsa: single-thread`` comment exempts one write site (a dead annotation
— on a line that writes no attribute — is a finding, and an annotation on
an attribute whose other writes inferred a guard is a ``contradictory``
finding); ``self.x = new_unguarded("<stem>.<Class>.x", value)`` in
``__init__`` exempts the whole attribute, with the name validated against
the assignment target and registered with the runtime RaceWitness so the
single-thread claim is observable. ``runtime_crosscheck`` validates the
static inference against what ``make chaos`` / ``make fleet-demo``
actually observed (utils/locks.py RaceWitness).

Like the lock-order checker this is an over-approximation with explicit
resolution limits: container-method mutation (``self.d.pop(k)``), writes
through aliases, and ``getattr``/``setattr`` are invisible; anything the
walk CAN see is enforced, and the RaceWitness covers real executions.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Optional

from tieredstorage_tpu.analysis import lockorder
from tieredstorage_tpu.analysis.core import Finding, ParsedFile, Project

ANNOTATION = "# tsa: single-thread"
UNGUARDED_FACTORY = "new_unguarded"

#: Classes reachable from more than one thread that the call resolver
#: cannot prove (cross-object chains through constructor parameters), each
#: with the reason it is shared. Burn entries down by making the chain
#: resolvable, never by deleting the reason.
SHARED_CLASSES = {
    "tieredstorage_tpu/transform/tpu.py:TpuTransformBackend":
        "one backend instance per RSM, driven by concurrent upload/fetch "
        "requests on the gateway worker pool (DispatchStats counters)",
    "tieredstorage_tpu/fleet/peer_cache.py:PeerChunkCache":
        "one peer tier per instance, hit by every gateway worker thread "
        "and the chunk cache's loader pool",
    "tieredstorage_tpu/fetch/cache/device_hot.py:DeviceHotCache":
        "one hot-window tier per RSM, hit by every gateway worker thread "
        "and the chunk cache's loader pool (serve/admit/evict counters and "
        "the resident-window maps)",
    "tieredstorage_tpu/fetch/cache/device_hot.py:FrequencySketch":
        "the hot tier's admission sketch, touched from every thread the "
        "tier itself is (count-min rows + decay op counter)",
    "tieredstorage_tpu/utils/flightrecorder.py:FlightRecorder":
        "one recorder per RSM, archiving records from every gateway "
        "worker and RSM operation thread (retention rings + counters)",
    "tieredstorage_tpu/transform/batcher.py:WindowBatcher":
        "one device queue per backend: every request thread (fetch "
        "decrypts, produce encrypts, background scrub verification — each "
        "under its work class) submits into the shared class-keyed "
        "buckets while the flusher daemon drains them (pending maps, "
        "in-flight count, coalescing + per-class counters, fair-share "
        "deficit and admission-allowance state)",
    "tieredstorage_tpu/metrics/slo.py:SloEngine":
        "one engine per RSM, ticked by every metrics scrape (gauge reads "
        "on exporter threads) and every GET /slo gateway worker",
    "tieredstorage_tpu/fleet/telemetry.py:FleetTelemetry":
        "one aggregator per fleet member, scraped concurrently by "
        "gateway workers serving GET /fleet/telemetry (client cache + "
        "scrape counters)",
    "tieredstorage_tpu/metrics/timeline.py:TimelineRecorder":
        "one event ring per RSM, fed by the batcher's flusher daemon on "
        "every merged launch and read by gateway workers serving "
        "GET /debug/timeline and by metrics-scrape gauge suppliers "
        "(ring deque + recorded/evicted/launch/expired counters)",
    "tieredstorage_tpu/fetch/readahead.py:ReadaheadManager":
        "one readahead tier per RSM: every gateway worker's foreground "
        "read advances the detector + consumes pre-admitted entries while "
        "the tier's own speculation pool resolves completed/failed "
        "launches and metrics-scrape gauge suppliers read the counters "
        "(stream LRU, speculated-entry map, budget + waste accounting)",
    "tieredstorage_tpu/fetch/manifest_cache.py:ManifestLookahead":
        "one lookahead per RSM: readahead's speculation pool launches "
        "manifest prefetch flights while gateway workers join or race "
        "them on segment-boundary crossings (flight table + counters)",
    # ISSUE 19: the unified failure-policy plane is by construction the
    # most-shared state in the process — every I/O seam on every thread
    # reports into it.
    "tieredstorage_tpu/utils/retry.py:CircuitBreaker":
        "one breaker per guarded target, taken by every gateway worker, "
        "the batcher's flusher daemon, and the gossip daemon (state "
        "machine + transition/fast-fail counters)",
    "tieredstorage_tpu/utils/retry.py:BreakerBoard":
        "one per-target board per peer cache / gossip agent, keyed lazily "
        "from every thread that forwards or probes (breaker map + "
        "aggregated transition totals)",
    "tieredstorage_tpu/utils/retry.py:RetryLedger":
        "ONE process-wide accounting plane: every call_with_retry site on "
        "every thread notes attempts/retries/give-ups into it while "
        "metrics-scrape gauge suppliers read them",
    "tieredstorage_tpu/utils/faults.py:FaultPlane":
        "one installed plane reached by every armed seam concurrently "
        "(per-site call counters, injection log, fired counters)",
}

#: Executor dispatch method names whose first argument runs on a pool thread.
_SUBMIT_ATTRS = {"submit", "map"}


# ------------------------------------------------------------------- model
@dataclasses.dataclass
class WriteSite:
    rel_path: str
    class_name: str
    method: str
    qualname: str
    attr_path: str  # dotted path under self ("stats.hits")
    root: str       # first component ("stats")
    line: int
    held: tuple[str, ...]  # lock ids held lexically at the site
    is_aug: bool
    annotated: bool
    #: held ∪ entry-held(method), filled by the fixed point
    effective_held: tuple[str, ...] = ()


@dataclasses.dataclass
class ClassRaces:
    rel_path: str
    name: str
    shared: bool
    reason: str
    lock_attrs: dict[str, str]               # attr -> static lock id
    lock_names: dict[str, str]               # attr -> new_lock name literal
    unguarded: dict[str, tuple[str, int]]    # attr -> (declared name, line)
    writes: list[WriteSite]
    init_write_lines: set[int]
    #: root attr -> inferred guarding lock id (only roots with writes)
    guards: dict[str, Optional[str]] = dataclasses.field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.rel_path}:{self.name}"

    @property
    def module_stem(self) -> str:
        return Path(self.rel_path).stem

    def site_name(self, root: str) -> str:
        """RaceWitness site naming convention for a root attribute."""
        return f"{self.module_stem}.{self.name}.{root}"


@dataclasses.dataclass
class RaceModel:
    classes: dict[str, ClassRaces]
    thread_entries: set[str]
    reached: set[str]
    #: file -> annotated line numbers without a matching write statement
    dead_annotations: dict[str, list[int]]

    def site_guards(self) -> dict[str, str]:
        """RaceWitness site -> expected witness lock name, for every root
        whose inferred guard was created through a NAMED factory."""
        out: dict[str, str] = {}
        for cr in self.classes.values():
            for root, guard in cr.guards.items():
                if guard is None:
                    continue
                attr = guard.rsplit(".", 1)[-1]
                name = cr.lock_names.get(attr)
                if name:
                    out[cr.site_name(root)] = name
        return out

    def single_thread_sites(self) -> set[str]:
        """Sites claimed single-thread via the ``# tsa: single-thread``
        annotation — the runtime witness must only ever see ONE thread
        mutate them."""
        sites: set[str] = set()
        for cr in self.classes.values():
            for w in cr.writes:
                if w.annotated:
                    sites.add(cr.site_name(w.root))
        return sites

    def unguarded_sites(self) -> set[str]:
        """Sites declared deliberately lock-free via ``new_unguarded`` (a
        torn update is an accepted cost there; no runtime constraint beyond
        being a KNOWN site)."""
        sites: set[str] = set()
        for cr in self.classes.values():
            for attr, (name, _line) in cr.unguarded.items():
                sites.add(name)
                sites.add(cr.site_name(attr))
        return sites


# ---------------------------------------------------------------- the walk
def _self_attr_path(node: ast.AST) -> Optional[str]:
    """Dotted attribute path for a write target rooted at ``self`` (the
    target itself, or the attribute under a subscript: ``self.d[k] = v``
    mutates ``self.d``)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return ".".join(reversed(parts))
    return None


class _ClassWalker:
    """Per-method walk: write sites + intra-class call sites, both with the
    lexically held lock stack (with-statements over the class's lock attrs
    and module locks; nested defs/lambdas run later, not under the locks)."""

    def __init__(self, fm, cm, pf: ParsedFile, annotated_lines: set[int]) -> None:
        self.fm = fm
        self.cm = cm
        self.pf = pf
        self.annotated = annotated_lines
        self.writes: list[WriteSite] = []
        self.init_write_lines: set[int] = set()
        #: (caller method, callee method, held-at-site)
        self.intra_calls: list[tuple[str, str, tuple[str, ...]]] = []
        #: methods referenced as bare ``self.m`` outside a call-func slot
        self.referenced: set[str] = set()
        self.held: list[str] = []
        self.method = ""

    def lock_of(self, expr: ast.AST) -> Optional[str]:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            return self.cm.lock_attrs.get(expr.attr)
        if isinstance(expr, ast.Name):
            return self.fm.module_locks.get(expr.id)
        return None

    def run(self, method_name: str, fn: ast.FunctionDef) -> None:
        self.method = method_name
        self.held = []
        self._stmts(fn.body)

    # -- statements
    def _stmts(self, stmts: list) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.With):
            taken: list[str] = []
            for item in stmt.items:
                self._expr(item.context_expr)
                lock_id = self.lock_of(item.context_expr)
                if lock_id is not None:
                    taken.append(lock_id)
            self.held.extend(taken)
            self._stmts(stmt.body)
            del self.held[len(self.held) - len(taken):]
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            saved, self.held = self.held, []
            self._stmts(stmt.body)
            self.held = saved
            return
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                targets = target.elts if isinstance(target, ast.Tuple) else [target]
                for t in targets:
                    self._write(t, stmt, is_aug=False)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if not (isinstance(stmt, ast.AnnAssign) and stmt.value is None):
                self._write(stmt.target, stmt, is_aug=isinstance(stmt, ast.AugAssign))
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._write(t, stmt, is_aug=False)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.stmt, ast.excepthandler, ast.match_case)):
                self._stmt(child)
            else:
                self._expr(child)

    def _write(self, target: ast.AST, stmt: ast.AST, *, is_aug: bool) -> None:
        path = _self_attr_path(target)
        if path is None:
            return
        if self.method == "__init__" and not self.held:
            # Construction happens-before publication; only remember the
            # line so annotations there are not reported dead.
            self.init_write_lines.add(stmt.lineno)
            return
        self.writes.append(WriteSite(
            rel_path=self.pf.rel_path,
            class_name=self.cm.name,
            method=self.method,
            qualname=f"{self.cm.name}.{self.method}",
            attr_path=path,
            root=path.split(".", 1)[0],
            line=stmt.lineno,
            held=tuple(self.held),
            is_aug=is_aug,
            annotated=stmt.lineno in self.annotated,
        ))

    # -- expressions
    def _expr(self, node: ast.AST) -> None:
        if isinstance(node, ast.Lambda):
            saved, self.held = self.held, []
            self._expr(node.body)
            self.held = saved
            return
        if isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and func.attr in self.cm.methods
            ):
                self.intra_calls.append((self.method, func.attr, tuple(self.held)))
            self._expr(func)
            for child in list(node.args) + [kw.value for kw in node.keywords]:
                self._expr(child)
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.cm.methods
            and isinstance(node.ctx, ast.Load)
            and not isinstance(getattr(node, "_ts_parent", None), ast.Call)
        ):
            # ``self.m`` stored/passed without being the call target: the
            # method can run from anywhere — no inherited entry-held.
            self.referenced.add(node.attr)
        for child in ast.iter_child_nodes(node):
            self._expr(child)


def _annotated_lines(pf: ParsedFile) -> set[int]:
    """Lines carrying the annotation as a real COMMENT token (the literal
    inside a docstring — e.g. this module's own — is not an annotation)."""
    import io
    import tokenize

    lines: set[int] = set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(pf.source).readline):
            if tok.type == tokenize.COMMENT and "tsa: single-thread" in tok.string:
                lines.add(tok.start[0])
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        pass  # unparseable tail: the AST parse already succeeded, best effort
    return lines


def _thread_entry_keys(project: Project, file_models: dict) -> set[str]:
    """Summary keys of callables that run on a spawned thread: Thread
    targets and Executor.submit/map callables (bound methods and module
    functions; lambdas defer to the lock-order walk's own handling)."""
    entries: set[str] = set()
    for pf in project.files:
        fm = file_models[pf.rel_path]
        for node in pf.walk():
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            candidates: list[ast.AST] = []
            name = lockorder._dotted(func)
            if name and name.split(".")[-1] in ("Thread", "start_new_thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        candidates.append(kw.value)
                if node.args:
                    candidates.append(node.args[0])
            elif isinstance(func, ast.Attribute) and func.attr in _SUBMIT_ATTRS:
                if node.args:
                    candidates.append(node.args[0])
            for cand in candidates:
                qual = pf.qualname_of(node)
                cls = qual.split(".", 1)[0]
                if (
                    isinstance(cand, ast.Attribute)
                    and isinstance(cand.value, ast.Name)
                    and cand.value.id == "self"
                    and cls in fm.classes
                    and cand.attr in fm.classes[cls].methods
                ):
                    entries.add(f"{pf.rel_path}:{cls}.{cand.attr}")
                elif isinstance(cand, ast.Name) and cand.id in fm.functions:
                    entries.add(f"{pf.rel_path}:{cand.id}")
    return entries


def _reached_from(entries: set[str], summaries: dict) -> set[str]:
    seen = set()
    stack = [k for k in entries if k in summaries]
    while stack:
        key = stack.pop()
        if key in seen:
            continue
        seen.add(key)
        summary = summaries.get(key)
        if summary is None:
            continue
        for site in summary.calls:
            if site.callee not in seen:
                stack.append(site.callee)
    return seen


def _entry_held_fixed_point(
    cm, walker: _ClassWalker, thread_entries: set[str], rel_path: str
) -> dict[str, frozenset]:
    """Entry-held set per method: the locks guaranteed held on entry.

    Public methods, thread/executor entry points, and methods stored as
    bare references start with nothing held; a private method inherits the
    INTERSECTION over all its intra-class call sites of (locks held at the
    site ∪ the caller's entry-held), narrowed to a fixed point from ⊤.
    """
    TOP = None  # not yet constrained
    entry: dict[str, Optional[frozenset]] = {}
    callers: dict[str, list[tuple[str, tuple[str, ...]]]] = {}
    for caller, callee, held in walker.intra_calls:
        callers.setdefault(callee, []).append((caller, held))
    for m in cm.methods:
        unconstrained = (
            not m.startswith("_")
            or m.startswith("__")
            or m in walker.referenced
            or f"{rel_path}:{cm.name}.{m}" in thread_entries
            or m not in callers
        )
        entry[m] = frozenset() if unconstrained else TOP
    changed = True
    while changed:
        changed = False
        for m, sites in callers.items():
            if entry[m] == frozenset():
                continue
            known = [
                frozenset(held) | entry[caller]
                for caller, held in sites
                if entry.get(caller) is not TOP
            ]
            if not known:
                continue
            new = frozenset.intersection(*known)
            candidate = new if entry[m] is TOP else entry[m] & new
            if candidate != entry[m]:
                entry[m] = candidate
                changed = True
    return {m: (e if e is not TOP else frozenset()) for m, e in entry.items()}


# ------------------------------------------------------------- model build
def _scan_init_declarations(
    fm, cm
) -> tuple[dict[str, str], dict[str, tuple[str, int]], list[Finding]]:
    """(lock name literals, new_unguarded declarations, naming findings)."""
    lock_names: dict[str, str] = {}
    unguarded: dict[str, tuple[str, int]] = {}
    findings: list[Finding] = []
    for method in cm.methods.values():
        for node in ast.walk(method):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id == "self"
                and isinstance(node.value, ast.Call)
            ):
                continue
            attr = node.targets[0].attr
            callee = lockorder._dotted(node.value.func)
            last = callee.split(".")[-1] if callee else None
            if last in lockorder.LOCK_FACTORY_NAMES and node.value.args:
                first = node.value.args[0]
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    lock_names[attr] = first.value
            elif last == UNGUARDED_FACTORY:
                first = node.value.args[0] if node.value.args else None
                name = (
                    first.value
                    if isinstance(first, ast.Constant) and isinstance(first.value, str)
                    else None
                )
                expected_suffix = f"{cm.name}.{attr}"
                if name is None or not name.endswith(expected_suffix):
                    findings.append(Finding(
                        checker="races",
                        path=fm.pf.rel_path,
                        line=node.lineno,
                        qualname=f"{cm.name}.__init__",
                        detail=f"bad-unguarded-name:{cm.name}.{attr}",
                        message=(
                            f"new_unguarded name for self.{attr} must be a "
                            f"string literal ending in {expected_suffix!r} "
                            "(the RaceWitness site convention), got "
                            f"{name!r}"
                        ),
                    ))
                else:
                    unguarded[attr] = (name, node.lineno)
    return lock_names, unguarded, findings


def build_race_model(project: Project) -> tuple[RaceModel, list[Finding]]:
    file_models = {
        pf.rel_path: lockorder._build_file_model(pf) for pf in project.files
    }
    class_registry = {}
    for fm in file_models.values():
        for cm in fm.classes.values():
            class_registry[f"{fm.module_name}.{cm.name}"] = cm
    for fm in file_models.values():
        lockorder._bind_class_attrs(fm, class_registry)
    summaries, _edges, _blocking = lockorder.build_lock_model(project)
    thread_entries = _thread_entry_keys(project, file_models)
    reached = _reached_from(thread_entries, summaries)

    findings: list[Finding] = []
    classes: dict[str, ClassRaces] = {}
    dead: dict[str, list[int]] = {}
    for pf in project.files:
        fm = file_models[pf.rel_path]
        annotated = _annotated_lines(pf)
        covered: set[int] = set()
        for cm in fm.classes.values():
            key = f"{pf.rel_path}:{cm.name}"
            reasons = []
            if cm.lock_attrs:
                reasons.append("owns a lock (shared by self-declaration)")
            touched = [
                m for m in cm.methods
                if f"{pf.rel_path}:{cm.name}.{m}" in reached
                or f"{pf.rel_path}:{cm.name}.{m}" in thread_entries
            ]
            if touched:
                reasons.append(
                    f"reachable from a spawned thread via {touched[0]}()"
                )
            if key in SHARED_CLASSES:
                reasons.append(SHARED_CLASSES[key])
            walker = _ClassWalker(fm, cm, pf, annotated)
            for name, fn in cm.methods.items():
                walker.run(name, fn)
            lock_names, unguarded, naming = _scan_init_declarations(fm, cm)
            findings.extend(naming)
            entry_held = _entry_held_fixed_point(
                cm, walker, thread_entries, pf.rel_path
            )
            for w in walker.writes:
                w.effective_held = tuple(
                    dict.fromkeys(list(w.held) + sorted(entry_held.get(w.method, ())))
                )
            covered |= {w.line for w in walker.writes}
            covered |= walker.init_write_lines
            covered |= {line for _name, line in unguarded.values()}
            classes[key] = ClassRaces(
                rel_path=pf.rel_path,
                name=cm.name,
                shared=bool(reasons),
                reason="; ".join(reasons),
                lock_attrs=dict(cm.lock_attrs),
                lock_names=lock_names,
                unguarded=unguarded,
                writes=walker.writes,
                init_write_lines=walker.init_write_lines,
            )
        stale = sorted(annotated - covered)
        if stale:
            dead[pf.rel_path] = stale
            for line in stale:
                f = Finding(
                    checker="races",
                    path=pf.rel_path,
                    line=line,
                    qualname=pf.qualname_of(pf.tree),
                    detail="dead-annotation",
                    message=(
                        f"'{ANNOTATION}' on a line that writes no self "
                        "attribute (annotations must sit on the write "
                        "statement's first line); remove or move it"
                    ),
                )
                if f.fingerprint not in {x.fingerprint for x in findings}:
                    findings.append(f)

    # Guard inference + race findings, shared classes only.
    for cr in classes.values():
        if not cr.shared:
            continue
        by_root: dict[str, list[WriteSite]] = {}
        for w in cr.writes:
            if w.root in cr.unguarded or w.root in cr.lock_attrs:
                continue  # declared lock-free / the locks themselves
            by_root.setdefault(w.root, []).append(w)
        for root, sites in sorted(by_root.items()):
            counts: dict[str, int] = {}
            for w in sites:
                for lock in w.effective_held:
                    counts[lock] = counts.get(lock, 0) + 1
            guard: Optional[str] = None
            if counts:
                best = max(sorted(counts), key=lambda k: counts[k])
                if counts[best] * 2 > len(sites):
                    guard = best
            cr.guards[root] = guard
            seen_fps: set[str] = set()
            for w in sites:
                if guard is not None:
                    if guard in w.effective_held:
                        continue
                    if w.annotated:
                        f = Finding(
                            checker="races",
                            path=cr.rel_path, line=w.line, qualname=w.qualname,
                            detail=f"contradictory-annotation:{cr.name}.{w.attr_path}",
                            message=(
                                f"self.{w.attr_path} is annotated "
                                "single-thread here but its other writes "
                                f"inferred the guard {guard}; pick one "
                                "discipline"
                            ),
                        )
                    else:
                        kind = "torn-rmw" if w.is_aug else "unguarded-write"
                        f = Finding(
                            checker="races",
                            path=cr.rel_path, line=w.line, qualname=w.qualname,
                            detail=f"{kind}:{cr.name}.{w.attr_path}",
                            message=(
                                f"write to self.{w.attr_path} outside its "
                                f"inferred guard {guard} (held at the "
                                "majority of write sites) in a class "
                                f"reachable from more than one thread "
                                f"({cr.reason}); guard it, or annotate "
                                f"'{ANNOTATION}' with evidence"
                            ),
                        )
                elif w.is_aug and not w.effective_held and not w.annotated:
                    f = Finding(
                        checker="races",
                        path=cr.rel_path, line=w.line, qualname=w.qualname,
                        detail=f"torn-rmw:{cr.name}.{w.attr_path}",
                        message=(
                            f"read-modify-write of self.{w.attr_path} with "
                            "no lock held in a class reachable from more "
                            f"than one thread ({cr.reason}); a concurrent "
                            "writer loses updates — guard it, declare it "
                            f"with new_unguarded(), or annotate "
                            f"'{ANNOTATION}' with evidence"
                        ),
                    )
                else:
                    continue
                if f.fingerprint not in seen_fps:
                    seen_fps.add(f.fingerprint)
                    findings.append(f)

    model = RaceModel(
        classes=classes,
        thread_entries=thread_entries,
        reached=reached,
        dead_annotations=dead,
    )
    return model, findings


def check_races(project: Project) -> list[Finding]:
    _model, findings = build_race_model(project)
    return findings


# ------------------------------------------------------ runtime cross-check
def runtime_crosscheck(
    project: Optional[Project] = None,
    *,
    race=None,
    lock_witness=None,
) -> dict:
    """Validate the static guarded-by inference against runtime evidence.

    Returns ``{"violations": [...], "validated": [...], "unobserved":
    [...]}``. A violation is an OBSERVED contradiction: a sampled mutation
    of an inferred-guarded site with the wrong (or no) witnessed lock held,
    a single-thread-annotated site mutated from more than one thread, or a
    runtime site name the static model does not know (stale hook).
    Inferred guards with no sampled mutations are merely ``unobserved``
    (the suites do not exercise every path every run) — unless the guard
    lock itself was never even acquired, which is also only informational.
    """
    from tieredstorage_tpu.analysis.core import load_project
    from tieredstorage_tpu.utils import locks as locks_mod

    if project is None:
        project = load_project(Path(__file__).resolve().parents[2])
    race = race if race is not None else locks_mod.race_witness()
    lw = lock_witness if lock_witness is not None else locks_mod.witness()
    model, _findings = build_race_model(project)
    guards = model.site_guards()
    single = model.single_thread_sites()
    unguarded = model.unguarded_sites() | set(race.unguarded_names)

    violations: list[str] = []
    validated: list[str] = []
    for site in race.sites():
        helds = race.held_at.get(site, set())
        threads = race.threads_at.get(site, set())
        if site in guards:
            expected = guards[site]
            wrong = sorted(
                "<none>" if h is None else h for h in helds if h != expected
            )
            if wrong:
                violations.append(
                    f"{site}: statically inferred guard {expected!r} but "
                    f"observed mutations holding {wrong}"
                )
            else:
                validated.append(site)
        elif site in single:
            if len(threads) > 1:
                violations.append(
                    f"{site}: declared single-thread but mutated from "
                    f"{len(threads)} distinct threads"
                )
            else:
                validated.append(site)
        elif site in unguarded:
            validated.append(site)  # lock-free by declaration
        else:
            violations.append(
                f"{site}: observed at runtime but unknown to the static "
                "race model (stale note_mutation hook?)"
            )
    acquired = lw.acquired_names()
    unobserved = sorted(
        f"{site} (guard {guards[site]}"
        + ("" if guards[site] in acquired else ", lock never acquired")
        + ")"
        for site in guards
        if site not in race.held_at
    )
    return {
        "violations": violations,
        "validated": sorted(validated),
        "unobserved": unobserved,
    }
