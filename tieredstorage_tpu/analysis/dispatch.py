"""Device-dispatch discipline: the fused window path stays one launch, one
transfer, one fetch per window — statically.

PR 8 made the packed single-dispatch GCM program the production transform
path and PR 9 sharded it; the invariant that makes those PRs worth their
complexity — ONE device dispatch per window, nothing materializing device
values mid-pipeline — is enforced today only by the runtime counters that
``make transform-demo``/``multichip-demo`` assert. A hidden ``np.asarray``
or ``block_until_ready`` added anywhere on the hot path serializes the
double-buffered pipeline and reintroduces the ~62 ms per-launch floor
(PROFILE.md) *silently* until the next bench round. This checker closes
that gap at the AST level:

1. **Closure.** The static call closure of the hot window path — from
   ``TpuTransformBackend.transform_windows`` /
   ``_encrypt_dispatch``/``_decrypt_batch`` through
   ``_stage_packed``/``_launch_packed`` into the ``ops/gcm.py`` packed
   entry points and the kernel modules they call — resolved through
   imports, ``self`` methods, and module functions, restricted to
   ``HOT_PATH_MODULES`` (the codec paths have their own disciplines).

2. **Materialization/sync.** Inside the closure: ``block_until_ready`` and
   ``jax.device_get`` are findings anywhere; ``np.asarray``/``np.array``/
   ``float()``/``int()``/``bool()``/``.item()``/``.tobytes()`` are
   findings when their operand is *device-tainted* (assigned from a launch
   / staging / ``jnp.*`` producer — host-side packing of numpy buffers is
   the point of the path and stays legal). The sanctioned finish set
   (``SANCTIONED_MATERIALIZERS``: ``_encrypt_finish`` and peers, each with
   its justification) is where the window's ONE materialization lives.

3. **Retrace hazards.** A ``jax.jit`` call outside the vetted wrapper
   (``_packed_jit``, which lru-caches per shape family), or a bypass of
   the context caches (direct ``GcmContext``/``GcmVarlenContext``
   construction or ``_*context_cached`` calls outside ``ops/gcm.py``)
   whose shapes therefore do not flow through ``bucket_max_bytes``'s
   ladder, is a finding: an unbucketed shape recompiles the whole window
   program per distinct size (round-1 VERDICT weak 2).

4. **Donation.** The staged buffer is donated to XLA as the output
   allocation; touching it after the launch reads freed memory. Any load
   of a name passed as the donated operand (``donate=True`` packed calls,
   or ``_launch_packed`` which donates internally) on a later line of the
   same function is a finding — ``.is_deleted()`` excepted (it is the
   donation *probe*).

5. **Inter-stage materialization inside the fused closure** (ISSUE 13).
   A second closure is built from the TRACE-scope roots — the packed
   window impls that run under ``_packed_jit`` — where every non-static
   parameter is a tracer by construction. Inside it, any host
   materializer or sync on a traced value (``interstage:...`` findings)
   splits the one-program window into multiple programs, and any
   staged matmul reduction loop outside the sanctioned ladder fallback
   (``interstage:staged-ladder``) reintroduces the per-level HBM round
   trips the fused GHASH tree kernel exists to remove. The runtime
   counterpart is ``ops.gcm.planned_hbm_roundtrips`` /
   ``DispatchStats.hbm_roundtrips_per_window``, CI-gated <= 1 by
   ``make transform-demo``.

Like the other whole-project checkers this is an over-approximation with
explicit limits: taint does not flow through containers or across calls,
and lexical line order stands in for execution order. The runtime
counters (``DispatchStats``, ``ops.gcm.device_dispatches``) remain the
ground truth the demos assert; this pass catches the regression at review
time instead of the next bench round.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from tieredstorage_tpu.analysis import lockorder
from tieredstorage_tpu.analysis.core import Finding, Project

#: Entry points of the hot window path (summary keys). The device hot-cache
#: roots (ISSUE 12) cover the serve side: a resident decrypt buffer must be
#: SLICED device-side, never materialized mid-serve — a hidden np.asarray
#: on the hot serve path would turn every "free" hit into a device->host
#: fetch and is a static finding here.
HOT_PATH_ROOTS = (
    "tieredstorage_tpu/transform/tpu.py:TpuTransformBackend.transform_windows",
    "tieredstorage_tpu/transform/tpu.py:TpuTransformBackend._encrypt_dispatch",
    "tieredstorage_tpu/transform/tpu.py:TpuTransformBackend._decrypt_batch",
    "tieredstorage_tpu/ops/gcm.py:gcm_window_packed",
    "tieredstorage_tpu/ops/gcm.py:gcm_varlen_window_packed",
    "tieredstorage_tpu/fetch/cache/device_hot.py:DeviceHotCache.get_chunks",
    "tieredstorage_tpu/fetch/cache/device_hot.py:DeviceHotCache.device_rows",
    # The cross-request batcher (ISSUE 15) is the decrypt hot path under
    # concurrency: a hidden materialization in submit or the merged flush
    # would pay once per COALESCED launch and stall every waiter at once.
    "tieredstorage_tpu/transform/batcher.py:WindowBatcher.submit",
    "tieredstorage_tpu/transform/batcher.py:WindowBatcher._flush_group",
    # The work-class scheduler half (ISSUE 16): the encrypt submit path is
    # the produce hot path under concurrency, same bar as submit.
    "tieredstorage_tpu/transform/batcher.py:WindowBatcher.submit_encrypt",
)

#: Modules the closure may traverse: the window path and the kernel stack
#: under it. The compression codecs (thuff/lzhuff/zstd) materialize on
#: their own schedules and are checked by their own demos.
HOT_PATH_MODULES = (
    "tieredstorage_tpu/transform/tpu.py",
    "tieredstorage_tpu/ops/gcm.py",
    "tieredstorage_tpu/ops/gf128.py",
    "tieredstorage_tpu/ops/aes.py",
    "tieredstorage_tpu/ops/aes_bitsliced.py",
    "tieredstorage_tpu/ops/aes_pallas.py",
    "tieredstorage_tpu/ops/ghash_pallas.py",
    "tieredstorage_tpu/parallel/mesh.py",
    "tieredstorage_tpu/fetch/cache/device_hot.py",
    "tieredstorage_tpu/transform/batcher.py",
    "tieredstorage_tpu/transform/scheduler.py",
)

#: Functions allowed to materialize device values, with the reason. This is
#: the "finish set": burn entries down, never add one without a sentence.
SANCTIONED_MATERIALIZERS = {
    "tieredstorage_tpu/transform/tpu.py:TpuTransformBackend._encrypt_finish":
        "the window's ONE device->host fetch: blocks on the oldest staged "
        "window after pipeline_depth newer ones were dispatched",
    "tieredstorage_tpu/transform/tpu.py:TpuTransformBackend._decrypt_window":
        "decrypt finish half: one fetch of plaintext+expected tags, "
        "verified host-side (the launch half is still checked upstream)",
    "tieredstorage_tpu/ops/gcm.py:_derive_h":
        "once-per-key host precompute of the GHASH key H, lru_cached - "
        "never on the per-window path",
    "tieredstorage_tpu/ops/aes_bitsliced.py:_forced_crosscheck_ok":
        "one-time forced-Pallas output cross-check at first use, memoized",
    "tieredstorage_tpu/transform/batcher.py:WindowBatcher._flush_group":
        "the merged flush's ONE device->host fetch, demultiplexed to every "
        "coalesced waiter with per-row tag verification (the batched "
        "counterpart of _decrypt_batch's finish half)",
}

#: Vetted jit wrappers: every shape family they compile is bounded (the
#: packed wrapper is lru_cached and its static shapes come from the
#: bucketed contexts).
SANCTIONED_JIT_WRAPPERS = {
    "tieredstorage_tpu/ops/gcm.py:_packed_jit",
}

#: Roots of the TRACE-scope closure (ISSUE 13): the packed window impls
#: that run under `_packed_jit`. Everything they reach executes inside ONE
#: traced program — the fused-window closure the tree kernel keeps to a
#: single stage.
TRACE_CLOSURE_ROOTS = (
    "tieredstorage_tpu/ops/gcm.py:_packed_fixed_impl",
    "tieredstorage_tpu/ops/gcm.py:_packed_varlen_impl",
)

#: Trace-scope parameters that carry static Python values (jit
#: static_argnames and host ints threaded through) — every OTHER parameter
#: of a trace-scope function is a tracer by construction.
TRACE_STATIC_PARAMS = {
    "self", "chunk_bytes", "n_blocks", "decrypt", "max_bytes", "m_max",
    "m_a", "m_cap", "aad_bit_len", "first_counter", "interpret",
}

#: Trace-scope functions allowed to contain a staged matmul-reduction loop,
#: with the reason. Burn down, never add without a sentence.
SANCTIONED_STAGED_REDUCERS = {
    "tieredstorage_tpu/ops/gcm.py:_ghash_grouped":
        "the XLA grouped-power ladder is the TESTED FALLBACK when the "
        "fused GHASH tree kernel cannot engage (no Mosaic on this "
        "platform, single-level shapes); its per-level HBM round trips "
        "are counted honestly by planned_hbm_roundtrips and gated by "
        "make transform-demo",
}

#: Calls that produce (or carry) device values: assignment from one taints
#: the bound name for the rest of the function.
DEVICE_PRODUCER_NAMES = {
    "gcm_window_packed", "gcm_varlen_window_packed",
    "gcm_encrypt_chunks", "gcm_decrypt_chunks",
    "gcm_encrypt_varlen", "gcm_decrypt_varlen", "_run_varlen",
    "_launch_packed", "_stage_packed", "_encrypt_dispatch",
    # ISSUE 16 seam: the batcher-aware encrypt dispatch returns either a
    # staged device tuple or an _EncryptHandle wrapping one — tainted
    # either way.
    "_dispatch_encrypt_window",
    "_gcm_process_batch", "_gcm_varlen_batch",
    "aes_encrypt_blocks", "ctr_keystream_batch",
    "aes_encrypt_planes_pallas", "ghash_level1_pallas",
    "device_put", "shard",
    # Device hot-cache tier: retained decrypt rows stay device values.
    "device_rows", "offer_decrypt_window",
}
DEVICE_PRODUCER_PREFIXES = ("jnp.", "jax.numpy.", "jax.device_put")

#: Parameters conventionally carrying staged device buffers.
DEVICE_PARAM_NAMES = {"staged", "data_packed"}

#: Materializers that are findings only on device-tainted operands.
MATERIALIZE_CALL_NAMES = {"np.asarray", "np.array", "np.copy", "numpy.asarray",
                          "numpy.array", "float", "int", "bool"}
MATERIALIZE_ATTRS = {"item", "tobytes"}
#: Sync calls that are findings on ANY operand inside the closure.
SYNC_ATTRS = {"block_until_ready"}
SYNC_CALL_NAMES = {"jax.device_get", "jax.block_until_ready"}

#: Attribute reads of a donated buffer that are still legal.
ALLOWED_AFTER_DONATE = {"is_deleted"}

#: Donating calls -> positional index of the donated operand.
_DONATING_CALLS = {
    "gcm_window_packed": 2,
    "gcm_varlen_window_packed": 2,
    "_launch_packed": 1,  # self._launch_packed(ctx, staged, ...)
}


# ---------------------------------------------------------------- closure
@dataclasses.dataclass
class _Fn:
    key: str
    rel_path: str
    qualname: str
    node: ast.FunctionDef
    fm: object
    class_name: Optional[str]


def _module_index(file_models: dict) -> dict[str, str]:
    return {fm.module_name: rel for rel, fm in file_models.items()}


def _resolve_call(func: ast.AST, fn: _Fn, modules: dict[str, str]) -> Optional[str]:
    """Summary key for a call target: local/module functions, imported
    module functions (``from x import f`` and ``import x as y; y.f()``),
    and ``self`` methods."""
    fm = fn.fm
    if isinstance(func, ast.Name):
        if func.id in fm.functions:
            return f"{fn.rel_path}:{func.id}"
        dotted = fm.imports.get(func.id)
        if dotted and "." in dotted:
            mod, _, name = dotted.rpartition(".")
            rel = modules.get(mod)
            if rel is not None:
                return f"{rel}:{name}"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    recv, meth = func.value, func.attr
    if isinstance(recv, ast.Name) and recv.id == "self" and fn.class_name:
        cm = fm.classes.get(fn.class_name)
        if cm is not None and meth in cm.methods:
            return f"{fn.rel_path}:{fn.class_name}.{meth}"
        return None
    dotted = lockorder._dotted(func)
    if dotted and "." in dotted:
        head, _, rest = dotted.partition(".")
        base = fm.imports.get(head)
        if base:
            full = f"{base}.{rest}"
            mod, _, name = full.rpartition(".")
            rel = modules.get(mod)
            if rel is not None:
                return f"{rel}:{name}"
    return None


def build_closure(project: Project, roots=HOT_PATH_ROOTS, stop_at=()):
    """(closure functions by key, file models, module index) — exposed for
    tests and the docs. `roots` selects the entry set: the hot window path
    (default) or TRACE_CLOSURE_ROOTS for the fused trace scope. Functions
    in `stop_at` are kept in the closure but their callees are not
    traversed (the sanctioned host-gate subtrees of the trace scope run
    eagerly at trace time, not inside the program)."""
    file_models = {
        pf.rel_path: lockorder._build_file_model(pf)
        for pf in project.files
        if pf.rel_path in HOT_PATH_MODULES
    }
    modules = _module_index(file_models)
    fns: dict[str, _Fn] = {}
    for rel, fm in file_models.items():
        for name, node in fm.functions.items():
            fns[f"{rel}:{name}"] = _Fn(
                key=f"{rel}:{name}", rel_path=rel, qualname=name,
                node=node, fm=fm, class_name=None,
            )
        for cls_name, cm in fm.classes.items():
            for m, node in cm.methods.items():
                key = f"{rel}:{cls_name}.{m}"
                fns[key] = _Fn(
                    key=key, rel_path=rel, qualname=f"{cls_name}.{m}",
                    node=node, fm=fm, class_name=cls_name,
                )

    closure: dict[str, _Fn] = {}
    stack = [k for k in roots if k in fns]
    while stack:
        key = stack.pop()
        if key in closure:
            continue
        fn = fns.get(key)
        if fn is None:
            continue
        closure[key] = fn
        if key in stop_at:
            continue
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                callee = _resolve_call(node.func, fn, modules)
                if callee is not None and callee not in closure:
                    stack.append(callee)
    return closure, file_models, modules


# ------------------------------------------------------------------ scans
def _call_name(func: ast.AST) -> Optional[str]:
    return lockorder._dotted(func)


def _tainted_names(fn: _Fn) -> set[str]:
    """Names bound (directly or via tuple unpack) from device producers,
    plus conventionally named device parameters. Two passes so a name
    assigned from another tainted name late in the function still taints
    earlier reported uses conservatively (propagation shared with the
    trace-scope scan, `_propagate_taint`)."""
    tainted: set[str] = {
        a.arg for a in fn.node.args.args if a.arg in DEVICE_PARAM_NAMES
    }
    return _propagate_taint(fn, tainted)


def _scan_materialization(fn: _Fn, findings: list[Finding]) -> None:
    if fn.key in SANCTIONED_MATERIALIZERS:
        return
    tainted = _tainted_names(fn)

    def arg_tainted(call: ast.Call) -> bool:
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            for node in ast.walk(a):
                if isinstance(node, ast.Name) and node.id in tainted:
                    return True
                if isinstance(node, ast.Call):
                    name = _call_name(node.func)
                    if name and (
                        name.split(".")[-1] in DEVICE_PRODUCER_NAMES
                        or name.startswith(DEVICE_PRODUCER_PREFIXES)
                    ):
                        return True
        return False

    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = _call_name(func)
        if isinstance(func, ast.Attribute) and func.attr in SYNC_ATTRS:
            findings.append(Finding(
                checker="device-dispatch",
                path=fn.rel_path, line=node.lineno, qualname=fn.qualname,
                detail=f"sync:{func.attr}",
                message=(
                    f"{func.attr}() inside the fused-window closure "
                    "serializes the double-buffered pipeline (every launch "
                    "re-pays the ~62 ms floor); only _encrypt_finish may "
                    "block, on the window's single packed buffer"
                ),
            ))
            continue
        if name in SYNC_CALL_NAMES:
            findings.append(Finding(
                checker="device-dispatch",
                path=fn.rel_path, line=node.lineno, qualname=fn.qualname,
                detail=f"sync:{name}",
                message=(
                    f"{name}() inside the fused-window closure forces a "
                    "device->host sync mid-pipeline; materialize only in "
                    "the sanctioned finish set"
                ),
            ))
            continue
        is_materializer = name in MATERIALIZE_CALL_NAMES or (
            isinstance(func, ast.Attribute) and func.attr in MATERIALIZE_ATTRS
        )
        if not is_materializer:
            continue
        receiver_tainted = (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in tainted
        )
        if receiver_tainted or arg_tainted(node):
            label = name or func.attr
            findings.append(Finding(
                checker="device-dispatch",
                path=fn.rel_path, line=node.lineno, qualname=fn.qualname,
                detail=f"materialize:{label.split('.')[-1]}",
                message=(
                    f"{label}() materializes a device value inside the "
                    "fused-window closure (outside the sanctioned finish "
                    "set): the hidden sync stalls the pipeline and "
                    "reintroduces the per-launch floor; keep the value on "
                    "device or move the fetch into _encrypt_finish"
                ),
            ))


def _scan_retrace(fn: _Fn, findings: list[Finding]) -> None:
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name is None:
            continue
        last = name.split(".")[-1]
        if (
            (name in ("jax.jit", "jit") or last == "jit")
            and fn.key not in SANCTIONED_JIT_WRAPPERS
        ):
            findings.append(Finding(
                checker="device-dispatch",
                path=fn.rel_path, line=node.lineno, qualname=fn.qualname,
                detail="unvetted-jit",
                message=(
                    "jax.jit call outside the vetted _packed_jit wrapper: "
                    "without the lru-cached wrapper + bucketed static "
                    "shapes every distinct window shape recompiles the "
                    "program (multi-second XLA compile per window)"
                ),
            ))
        elif (
            last in ("GcmContext", "GcmVarlenContext",
                     "_context_cached", "_varlen_context_cached")
            and fn.rel_path != "tieredstorage_tpu/ops/gcm.py"
        ):
            findings.append(Finding(
                checker="device-dispatch",
                path=fn.rel_path, line=node.lineno, qualname=fn.qualname,
                detail=f"shape-not-bucketed:{last}",
                message=(
                    f"{last} constructed outside ops/gcm.py bypasses "
                    "make_context/make_varlen_context, so the window shape "
                    "does not flow through bucket_max_bytes's ladder - a "
                    "retrace hazard (one XLA compile per distinct "
                    "compressed size)"
                ),
            ))


def _scan_donation(fn: _Fn, findings: list[Finding]) -> None:
    donated: list[tuple[str, int]] = []  # (name, last line of the donating call)
    in_donating_call: set[int] = set()   # id() of Name nodes inside one
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        last = name.split(".")[-1] if name else None
        if last not in _DONATING_CALLS:
            continue
        if last != "_launch_packed" and not any(
            kw.arg == "donate"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in node.keywords
        ):
            continue
        # A later donating call (the fixed/varlen sibling branch) passing
        # the same buffer is not a use-after-donate: only one branch runs.
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                in_donating_call.add(id(sub))
        idx = _DONATING_CALLS[last]
        if idx < len(node.args) and isinstance(node.args[idx], ast.Name):
            donated.append((node.args[idx].id, node.end_lineno or node.lineno))
    if not donated:
        return
    seen_fp: set[str] = set()
    for node in ast.walk(fn.node):
        if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
            continue
        if id(node) in in_donating_call:
            continue
        parent = getattr(node, "_ts_parent", None)
        if (
            isinstance(parent, ast.Attribute)
            and parent.attr in ALLOWED_AFTER_DONATE
        ):
            continue
        for dname, dline in donated:
            if node.id == dname and node.lineno > dline:
                f = Finding(
                    checker="device-dispatch",
                    path=fn.rel_path, line=node.lineno, qualname=fn.qualname,
                    detail=f"use-after-donate:{dname}",
                    message=(
                        f"{dname!r} was donated to XLA as the launch's "
                        "output allocation and is deleted after dispatch; "
                        "reading it here is use-after-free (only "
                        ".is_deleted() is legal - it is the donation "
                        "probe)"
                    ),
                )
                if f.fingerprint not in seen_fp:
                    seen_fp.add(f.fingerprint)
                    findings.append(f)


# ----------------------------------------------- fused trace scope (rule 5)
def _trace_tainted_names(fn: _Fn) -> set[str]:
    """Traced-value names inside a trace-scope function: every parameter
    that is not a known static is a tracer by construction (the function
    runs under `_packed_jit`), then the same producer/assignment
    propagation as `_tainted_names`."""
    args = fn.node.args
    params = list(getattr(args, "posonlyargs", [])) + list(args.args) + list(
        args.kwonlyargs
    )
    tainted = {a.arg for a in params if a.arg not in TRACE_STATIC_PARAMS}
    return _propagate_taint(fn, tainted)


def _propagate_taint(fn: _Fn, tainted: set[str]) -> set[str]:
    """Two-pass producer/assignment taint propagation shared by the hot
    and trace closures (extracted from `_tainted_names`)."""

    def is_producer(call: ast.Call) -> bool:
        name = _call_name(call.func)
        if name is None:
            return False
        if name.split(".")[-1] in DEVICE_PRODUCER_NAMES:
            return True
        return name.startswith(DEVICE_PRODUCER_PREFIXES)

    def expr_tainted(expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in tainted:
                return True
            if isinstance(node, ast.Call) and is_producer(node):
                return True
        return False

    for _ in range(2):
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and expr_tainted(node.value):
                for target in node.targets:
                    elts = target.elts if isinstance(target, ast.Tuple) else [target]
                    for t in elts:
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)
    return tainted


def _scan_interstage(fn: _Fn, findings: list[Finding]) -> None:
    """Host materializers/syncs inside the TRACED fused closure. Every
    value here is a tracer, so a materialization cannot be a cheap host
    peek: it cuts the one-program window into multiple programs with an
    HBM round trip (and a relay sync) at the cut. The sanctioned set is
    the trace-time host gates (memoized preflight cross-checks under
    ensure_compile_time_eval)."""
    if fn.key in SANCTIONED_MATERIALIZERS:
        return
    tainted = _trace_tainted_names(fn)
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = _call_name(func)
        is_sync = (
            isinstance(func, ast.Attribute) and func.attr in SYNC_ATTRS
        ) or name in SYNC_CALL_NAMES
        if is_sync:
            findings.append(Finding(
                checker="device-dispatch",
                path=fn.rel_path, line=node.lineno, qualname=fn.qualname,
                detail=f"interstage:sync:{(name or func.attr).split('.')[-1]}",
                message=(
                    "device sync inside the TRACED fused-window closure: "
                    "the window must stay one device program "
                    "(hbm_roundtrips_per_window <= 1); move host work "
                    "outside the packed impls"
                ),
            ))
            continue
        is_materializer = name in MATERIALIZE_CALL_NAMES or (
            isinstance(func, ast.Attribute) and func.attr in MATERIALIZE_ATTRS
        )
        if not is_materializer:
            continue
        receiver_tainted = (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in tainted
        )
        operand_tainted = any(
            isinstance(sub, ast.Name) and sub.id in tainted
            for a in list(node.args) + [kw.value for kw in node.keywords]
            for sub in ast.walk(a)
        )
        if receiver_tainted or operand_tainted:
            label = (name or func.attr).split(".")[-1]
            findings.append(Finding(
                checker="device-dispatch",
                path=fn.rel_path, line=node.lineno, qualname=fn.qualname,
                detail=f"interstage:materialize:{label}",
                message=(
                    f"{label}() materializes a traced value inside the "
                    "fused-window closure: XLA must cut the one-program "
                    "window here and round-trip the intermediate through "
                    "HBM — exactly the inter-stage materialization the "
                    "fused GHASH tree kernel removes (ISSUE 13)"
                ),
            ))


#: Calls that stage a matmul reduction level (HBM materialization of the
#: per-level node tensor between them when looped).
_MATMUL_NAMES = {"dot_general", "dot", "matmul", "einsum", "tensordot"}


def _scan_staged_reduction(fn: _Fn, findings: list[Finding]) -> None:
    """A matmul inside a loop in trace scope is a STAGED reduction: each
    iteration materializes its node tensor in HBM before the next
    contracts it — the grouped-power ladder shape. Only the sanctioned
    fallback (`_ghash_grouped`, counted by planned_hbm_roundtrips) may
    carry one; anywhere else it silently reintroduces the per-level round
    trips."""
    if fn.key in SANCTIONED_STAGED_REDUCERS:
        return
    for loop in ast.walk(fn.node):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for sub in ast.walk(loop):
            if not isinstance(sub, ast.Call):
                continue
            name = _call_name(sub.func) or ""
            if name.split(".")[-1] in _MATMUL_NAMES:
                findings.append(Finding(
                    checker="device-dispatch",
                    path=fn.rel_path, line=sub.lineno, qualname=fn.qualname,
                    detail="interstage:staged-ladder",
                    message=(
                        "matmul-in-a-loop inside the traced fused closure "
                        "is a staged reduction (one HBM round trip per "
                        "level); the ladder lives only in the sanctioned "
                        "fallback — route the reduction through the fused "
                        "GHASH tree kernel instead"
                    ),
                ))
                break  # one finding per loop
    return


def check_device_dispatch(project: Project) -> list[Finding]:
    closure, _file_models, _modules = build_closure(project)
    findings: list[Finding] = []
    for key in sorted(closure):
        fn = closure[key]
        _scan_materialization(fn, findings)
        _scan_retrace(fn, findings)
        _scan_donation(fn, findings)
    trace_closure, _tfm, _tmod = build_closure(
        project, TRACE_CLOSURE_ROOTS,
        stop_at=frozenset(SANCTIONED_MATERIALIZERS),
    )
    for key in sorted(trace_closure):
        fn = trace_closure[key]
        _scan_interstage(fn, findings)
        _scan_staged_reduction(fn, findings)
    return findings
