"""Project-invariant static analysis (ISSUE 7).

An AST-based checker framework (stdlib ``ast``, zero dependencies) that
mechanically enforces the concurrency and observability discipline the
serving tier relies on — the counterpart of the reference repo's pitest
merge gate, but aimed at *project invariants* instead of test strength:

- ``lock-order``        cross-module lock-acquisition graph stays a DAG; no
                        blocking calls (socket/HTTP/waits) under a held lock
- ``races``             guarded-by data-race inference: every shared mutable
                        attribute of a thread-reachable class has one
                        inferred guarding lock, held at every write
                        (``# tsa: single-thread`` / ``new_unguarded`` are
                        checked escape hatches)
- ``device-dispatch``   the fused window path stays one launch/transfer/
                        fetch per window: no hidden materialization or sync
                        in its closure, no unvetted jit (retrace hazard),
                        no donated-buffer use after launch
- ``deadline``          blocking waits in request-path modules clamp to the
                        end-to-end ``Deadline`` budget
- ``bounded-concurrency``  no unsanctioned ``threading.Thread`` and no
                        unbounded executors
- ``monotonic-clock``   no ``time.time()`` (durations/timeouts must ride the
                        monotonic clock)
- ``swallowed-exception``  no broad ``except: pass`` without a trace event,
                        metric, or log
- ``config-drift``      every config key read is declared; generated docs
                        (configs.rst / metrics.rst) match the live code

Entry points: ``python -m tieredstorage_tpu.analysis`` / ``make analyze``
(CI-gated; ``--paths <files...>`` is the sub-second incremental mode over
a content-hash parse cache). Findings carry stable line-independent
fingerprints; legacy violations live in ``tools/analysis_suppressions.txt``
with one-line justifications and are burned down, never silently
grandfathered. The static lock-order and guarded-by proofs are
cross-validated at runtime by ``tieredstorage_tpu.utils.locks.LockWitness``
and ``RaceWitness`` (``TSTPU_LOCK_WITNESS=1`` under ``make chaos`` /
``make fleet-demo``).
"""

from tieredstorage_tpu.analysis.core import (
    AnalysisReport,
    Finding,
    Project,
    Suppressions,
    load_project,
    run_analysis,
)

__all__ = [
    "AnalysisReport",
    "Finding",
    "Project",
    "Suppressions",
    "load_project",
    "run_analysis",
]
