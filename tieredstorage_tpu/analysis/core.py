"""Checker framework: parsed project model, findings, suppressions, reports.

Design (mirrors how the mutation harness treats the tree,
tools/mutation_test.py): pure stdlib ``ast``, every checker is a function
``(Project) -> list[Finding]`` registered in ``CHECKERS``, and the CLI
(``__main__.py``) renders text + a JSON artifact and exits non-zero on any
unsuppressed finding OR any stale suppression — the suppression file is a
burn-down list, not a grandfather clause.

Fingerprints are deliberately line-independent
(``checker:path:qualname:detail``) so a suppression survives unrelated edits
to the file but dies with the code it covers.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import pickle
from pathlib import Path
from typing import Callable, Iterable, Optional


# --------------------------------------------------------------------- model
@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str
    path: str  # repo-relative, posix separators
    line: int
    qualname: str  # enclosing class.function ("<module>" at top level)
    detail: str  # stable short code (call name, lock edge, config key...)
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.checker}:{self.path}:{self.qualname}:{self.detail}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.checker}] {self.message}"
            f"\n    fingerprint: {self.fingerprint}"
        )


class ParsedFile:
    """One source file: AST with parent links and enclosing-scope names."""

    def __init__(self, path: Path, rel_path: str, source: str) -> None:
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.tree = ast.parse(source, filename=rel_path)
        self._annotate()

    def _annotate(self) -> None:
        """Attach ``_ts_parent`` and ``_ts_qual`` (enclosing qualname) to
        every node; scope nodes are Module / ClassDef / FunctionDef."""
        scopes = [(self.tree, "<module>")]
        self.tree._ts_qual = "<module>"  # type: ignore[attr-defined]
        stack = [(self.tree, "<module>")]
        while stack:
            node, qual = stack.pop()
            for child in ast.iter_child_nodes(node):
                child._ts_parent = node  # type: ignore[attr-defined]
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    child_qual = child.name if qual == "<module>" else f"{qual}.{child.name}"
                else:
                    child_qual = qual
                child._ts_qual = child_qual  # type: ignore[attr-defined]
                stack.append((child, child_qual))
        del scopes

    def qualname_of(self, node: ast.AST) -> str:
        return getattr(node, "_ts_qual", "<module>")

    def walk(self) -> Iterable[ast.AST]:
        return ast.walk(self.tree)


class Project:
    """Every parsed file under the scan root, plus repo-level context."""

    def __init__(self, root: Path, files: list[ParsedFile]) -> None:
        self.root = root
        self.files = files

    def file(self, rel_path: str) -> Optional[ParsedFile]:
        for pf in self.files:
            if pf.rel_path == rel_path:
                return pf
        return None


def load_project(
    root: Path,
    scan_dirs: Optional[list[str]] = None,
    *,
    cache_path: Optional[Path] = None,
) -> Project:
    """Parse every ``.py`` file under ``scan_dirs`` (default: the package).

    With ``cache_path``, parsed+annotated trees are reused from a
    content-hash pickle (the incremental ``--paths`` mode's parse cache —
    whole-tree runs parse faster than they unpickle, so the CI gate never
    passes one). The cache is strictly best-effort: any read/write failure
    degrades to a plain parse.
    """
    root = Path(root).resolve()
    dirs = scan_dirs or ["tieredstorage_tpu"]
    cache: dict[str, tuple[str, ParsedFile]] = {}
    if cache_path is not None and cache_path.exists():
        try:
            cache = pickle.loads(cache_path.read_bytes())
        except Exception as e:  # noqa: BLE001 — corrupt/foreign cache: reparse
            _note_cache_failure(e)
            cache = {}
    changed = False
    files: list[ParsedFile] = []
    for d in dirs:
        base = root / d
        paths = sorted(base.rglob("*.py")) if base.is_dir() else [base]
        for path in paths:
            if "__pycache__" in path.parts:
                continue
            rel = path.relative_to(root).as_posix()
            source = path.read_text()
            digest = hashlib.sha256(source.encode()).hexdigest()
            hit = cache.get(rel)
            if hit is not None and hit[0] == digest:
                files.append(hit[1])
                continue
            pf = ParsedFile(path, rel, source)
            cache[rel] = (digest, pf)
            changed = True
            files.append(pf)
    if cache_path is not None and changed:
        try:
            cache_path.parent.mkdir(parents=True, exist_ok=True)
            cache_path.write_bytes(
                pickle.dumps(cache, protocol=pickle.HIGHEST_PROTOCOL)
            )
        except Exception as e:  # noqa: BLE001 — cache is an optimization only
            _note_cache_failure(e)
    return Project(root, files)


#: Last parse-cache read/write failure, for inspection (the cache is a pure
#: optimization — every failure degrades to a plain parse, but must not
#: vanish without a trace: swallowed-exception checker).
_CACHE_LAST_ERROR: list[str] = []


def _note_cache_failure(exc: BaseException) -> None:
    _CACHE_LAST_ERROR[:] = [repr(exc)]


# --------------------------------------------------------------- suppressions
class SuppressionError(ValueError):
    pass


class Suppressions:
    """Vetted per-finding suppressions: ``<fingerprint>  # <justification>``.

    Every entry MUST carry a non-empty justification; entries that no longer
    match any finding are STALE and fail the run (burn-down semantics: fixed
    code must shed its suppression in the same change).
    """

    def __init__(self, entries: Optional[dict[str, str]] = None) -> None:
        #: fingerprint -> justification, insertion-ordered
        self.entries: dict[str, str] = dict(entries or {})

    @classmethod
    def parse(cls, text: str, *, origin: str = "<suppressions>") -> "Suppressions":
        entries: dict[str, str] = {}
        for lineno, raw in enumerate(text.splitlines(), 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            fingerprint, sep, justification = line.partition("#")
            fingerprint = fingerprint.strip()
            justification = justification.strip()
            if not sep or not justification:
                raise SuppressionError(
                    f"{origin}:{lineno}: suppression {fingerprint!r} needs a "
                    "'# <one-line justification>'"
                )
            if fingerprint in entries:
                raise SuppressionError(
                    f"{origin}:{lineno}: duplicate suppression {fingerprint!r}"
                )
            entries[fingerprint] = justification
        return cls(entries)

    @classmethod
    def load(cls, path: Path) -> "Suppressions":
        if not path.exists():
            return cls()
        return cls.parse(path.read_text(), origin=str(path))

    def serialize(self) -> str:
        lines = [
            "# Static-analysis suppressions (tools/analysis_suppressions.txt).",
            "# One vetted legacy finding per line: <fingerprint>  # <justification>.",
            "# Stale entries FAIL `make analyze` - remove them with the fix.",
            "",
        ]
        lines += [f"{fp}  # {why}" for fp, why in self.entries.items()]
        return "\n".join(lines) + "\n"

    def justification(self, fingerprint: str) -> Optional[str]:
        return self.entries.get(fingerprint)


# -------------------------------------------------------------------- report
@dataclasses.dataclass
class AnalysisReport:
    root: str
    files_scanned: int
    checkers: list[str]
    findings: list[Finding]
    suppressions: Suppressions
    notes: list[str] = dataclasses.field(default_factory=list)

    @property
    def suppressed(self) -> list[tuple[Finding, str]]:
        return [
            (f, self.suppressions.entries[f.fingerprint])
            for f in self.findings
            if f.fingerprint in self.suppressions.entries
        ]

    @property
    def unsuppressed(self) -> list[Finding]:
        return [
            f for f in self.findings
            if f.fingerprint not in self.suppressions.entries
        ]

    @property
    def stale_suppressions(self) -> list[str]:
        live = {f.fingerprint for f in self.findings}
        return [fp for fp in self.suppressions.entries if fp not in live]

    @property
    def ok(self) -> bool:
        return not self.unsuppressed and not self.stale_suppressions

    def to_json(self) -> dict:
        return {
            "version": 1,
            "generated_by": "tieredstorage_tpu.analysis",
            "root": self.root,
            "files_scanned": self.files_scanned,
            "checkers": list(self.checkers),
            "findings": [
                {
                    "checker": f.checker,
                    "path": f.path,
                    "line": f.line,
                    "qualname": f.qualname,
                    "detail": f.detail,
                    "message": f.message,
                    "fingerprint": f.fingerprint,
                    "suppressed": f.fingerprint in self.suppressions.entries,
                    "justification": self.suppressions.justification(f.fingerprint),
                }
                for f in self.findings
            ],
            "stale_suppressions": self.stale_suppressions,
            "notes": list(self.notes),
            "summary": {
                "total": len(self.findings),
                "suppressed": len(self.suppressed),
                "unsuppressed": len(self.unsuppressed),
                "stale_suppressions": len(self.stale_suppressions),
                "ok": self.ok,
            },
        }

    def render_text(self) -> str:
        out: list[str] = []
        for f in self.unsuppressed:
            out.append(f.render())
        if self.stale_suppressions:
            out.append("stale suppressions (no longer match any finding):")
            out += [f"    {fp}" for fp in self.stale_suppressions]
        out.append(
            f"analysis: {self.files_scanned} files, "
            f"{len(self.findings)} finding(s) "
            f"({len(self.suppressed)} suppressed, "
            f"{len(self.unsuppressed)} unsuppressed, "
            f"{len(self.stale_suppressions)} stale suppression(s))"
        )
        out.append("analysis: OK" if self.ok else "analysis: FAIL")
        return "\n".join(out)

    def write_json(self, path: Path) -> None:
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.to_json(), indent=1) + "\n")


# ----------------------------------------------------------------- execution
CheckerFn = Callable[[Project], list[Finding]]


def checker_registry() -> dict[str, CheckerFn]:
    """Name -> checker function (import deferred to avoid cycles)."""
    from tieredstorage_tpu.analysis import checkers, dispatch, drift, lockorder, races

    return {
        "lock-order": lockorder.check_lock_order,
        "races": races.check_races,
        "device-dispatch": dispatch.check_device_dispatch,
        "deadline": checkers.check_deadline_discipline,
        "bounded-concurrency": checkers.check_bounded_concurrency,
        "monotonic-clock": checkers.check_monotonic_clock,
        "swallowed-exception": checkers.check_swallowed_exceptions,
        "config-drift": drift.check_config_drift,
    }


def run_analysis(
    project: Project,
    *,
    suppressions: Optional[Suppressions] = None,
    only: Optional[list[str]] = None,
) -> AnalysisReport:
    registry = checker_registry()
    names = list(registry) if only is None else list(only)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown checker(s): {', '.join(unknown)}")
    findings: list[Finding] = []
    notes: list[str] = []
    for name in names:
        result = registry[name](project)
        for item in result:
            if isinstance(item, Finding):
                findings.append(item)
            else:  # (finding-list, notes) escape hatch for drift checkers
                notes.append(str(item))
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.detail))
    return AnalysisReport(
        root=str(project.root),
        files_scanned=len(project.files),
        checkers=names,
        findings=findings,
        suppressions=suppressions or Suppressions(),
        notes=notes,
    )
