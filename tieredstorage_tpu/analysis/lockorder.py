"""Lock-order checker: the cross-module acquisition graph must stay a DAG,
and nothing blocking may run while a lock is held.

Model (two passes, whole-project):

1. **Inventory.** Every ``self.X = threading.Lock()/RLock()/Condition()`` (or
   the witnessed ``new_lock``/``new_rlock``/``new_condition`` factories from
   utils/locks.py) becomes the lock node ``<file>:<Class>.<attr>``; module
   level ``X = threading.Lock()`` becomes ``<file>:<var>``. Assignments
   ``self.Y = SomeProjectClass(...)`` bind the attribute's type so calls
   through it resolve cross-module.

2. **Summaries + fixed point.** Each function gets a summary: locks it
   acquires directly (``with self.X:`` bodies and explicit ``.acquire()``),
   whether it makes a blocking call (socket/HTTP/``wait``/``result``/
   executor dispatch/connection ``close``), and its resolvable call sites
   (``self.m()``, module functions, constructors, and one level of
   ``self.attr.m()`` through the type bindings). Acquire-sets and the
   blocks flag propagate through the call graph to a fixed point, so
   "holding A, call helper that takes B" yields the edge A -> B and
   "holding A, call helper that does a socket round-trip" is flagged even
   when the round-trip is two calls deep.

Findings: one per cycle in the resulting graph (potential deadlock by
circular wait), and one per blocking call site made while a lock is held.
``Condition.wait`` on the lock actually held is NOT blocking-under-lock (the
wait releases it); waiting on anything else while holding a lock is.

This is deliberately an over-approximation with explicit resolution limits
(no aliasing through locals, no duck-typed delegates): anything it cannot
resolve is silent, anything it CAN resolve is enforced, and the runtime
LockWitness covers the remainder from real executions.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Optional

from tieredstorage_tpu.analysis.core import Finding, ParsedFile, Project

LOCK_FACTORY_NAMES = {"new_lock", "new_rlock", "new_condition"}
THREADING_LOCK_CTORS = {"Lock", "RLock", "Condition"}

#: Method names that block the calling thread (socket / HTTP / futures /
#: condition waits / executor dispatch). ``wait`` on the very lock being
#: held is exempted at the call site.
BLOCKING_ATTRS = {
    "request", "request_stream", "urlopen", "getresponse", "connect",
    "accept", "recv", "recv_into", "send", "sendall", "wait", "result",
    "submit", "shutdown",
}
#: ``.close()`` counts as blocking only on connection-ish receivers (socket
#: teardown does a network round-trip); matched against the receiver source.
CLOSE_RECEIVER_RE = re.compile(r"(conn|client|sock|stream|resp|idle)", re.IGNORECASE)


# ------------------------------------------------------------------- models
@dataclasses.dataclass
class ClassModel:
    rel_path: str
    name: str
    lock_attrs: dict[str, str] = dataclasses.field(default_factory=dict)  # attr -> lock id
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)  # attr -> class full name
    methods: dict[str, ast.FunctionDef] = dataclasses.field(default_factory=dict)

    @property
    def full_name_suffix(self) -> str:
        return self.name


@dataclasses.dataclass
class FileModel:
    pf: ParsedFile
    imports: dict[str, str] = dataclasses.field(default_factory=dict)  # local -> dotted
    classes: dict[str, ClassModel] = dataclasses.field(default_factory=dict)
    module_locks: dict[str, str] = dataclasses.field(default_factory=dict)  # var -> lock id
    functions: dict[str, ast.FunctionDef] = dataclasses.field(default_factory=dict)

    @property
    def module_name(self) -> str:
        return self.pf.rel_path[: -len(".py")].replace("/", ".")


@dataclasses.dataclass
class CallSite:
    callee: str  # summary key
    held: tuple[str, ...]  # lock ids held at the call
    line: int
    qualname: str
    rel_path: str
    label: str  # short human label for the callee


@dataclasses.dataclass
class FnSummary:
    key: str
    rel_path: str
    qualname: str
    acquires: set[str] = dataclasses.field(default_factory=set)
    blocks: Optional[str] = None  # description of first direct blocking call
    blocks_trans: Optional[str] = None
    calls: list[CallSite] = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------- inventory
def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _is_lock_ctor(call: ast.Call, imports: dict[str, str]) -> bool:
    name = _dotted(call.func)
    if name is None:
        return False
    last = name.split(".")[-1]
    if last in LOCK_FACTORY_NAMES:  # utils.locks factories, however imported
        return True
    if name.startswith("threading.") and last in THREADING_LOCK_CTORS:
        return True
    return imports.get(name) in {f"threading.{c}" for c in THREADING_LOCK_CTORS}


def _resolve_dotted(name: str, fm: "FileModel") -> str:
    """Expand a local (possibly dotted) name to its full module path using
    the file's import table; bare names default to the file's own module."""
    if "." in name:
        head, _, rest = name.partition(".")
        base = fm.imports.get(head)
        return f"{base}.{rest}" if base else name
    return fm.imports.get(name, f"{fm.module_name}.{name}")


def _build_file_model(pf: ParsedFile) -> FileModel:
    fm = FileModel(pf=pf)
    for node in pf.walk():
        if isinstance(node, ast.Import):
            for alias in node.names:
                fm.imports[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                fm.imports[alias.asname or alias.name] = f"{node.module}.{alias.name}"
    for node in ast.iter_child_nodes(pf.tree):
        if isinstance(node, ast.ClassDef):
            cm = ClassModel(rel_path=pf.rel_path, name=node.name)
            fm.classes[node.name] = cm
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    cm.methods[item.name] = item
        elif isinstance(node, ast.FunctionDef):
            fm.functions[node.name] = node
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if (
                isinstance(target, ast.Name)
                and isinstance(node.value, ast.Call)
                and _is_lock_ctor(node.value, fm.imports)
            ):
                fm.module_locks[target.id] = f"{pf.rel_path}:{target.id}"
    return fm


def _bind_class_attrs(fm: FileModel, class_registry: dict[str, ClassModel]) -> None:
    """Scan every method for ``self.X = <lock ctor | ProjectClass(...)>``."""
    for cm in fm.classes.values():
        for method in cm.methods.values():
            for node in ast.walk(method):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                target = node.targets[0]
                if not (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                if _is_lock_ctor(node.value, fm.imports):
                    cm.lock_attrs[target.attr] = (
                        f"{fm.pf.rel_path}:{cm.name}.{target.attr}"
                    )
                    continue
                ctor = _dotted(node.value.func)
                if ctor is None:
                    continue
                full = _resolve_dotted(ctor, fm)
                if full in class_registry:
                    cm.attr_types[target.attr] = full


# ---------------------------------------------------------------- summaries
class _FnWalker:
    """Single-function walk tracking the statically-held lock stack."""

    def __init__(
        self,
        summary: FnSummary,
        fm: FileModel,
        cm: Optional[ClassModel],
        class_registry: dict[str, ClassModel],
        edges: dict[tuple[str, str], tuple[str, int, str]],
        blocking_sites: list[tuple[str, int, str, str, str]],
    ) -> None:
        self.s = summary
        self.fm = fm
        self.cm = cm
        self.registry = class_registry
        self.edges = edges
        self.blocking_sites = blocking_sites
        self.held: list[str] = []

    # -- resolution helpers
    def lock_of(self, expr: ast.AST) -> Optional[str]:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and self.cm is not None
        ):
            return self.cm.lock_attrs.get(expr.attr)
        if isinstance(expr, ast.Name):
            return self.fm.module_locks.get(expr.id)
        return None

    def callee_key(self, func: ast.AST) -> Optional[tuple[str, str]]:
        """(summary key, short label) for a resolvable call target."""
        if isinstance(func, ast.Name):
            if func.id in self.fm.functions:
                return f"{self.fm.pf.rel_path}:{func.id}", func.id
            target = self.registry.get(_resolve_dotted(func.id, self.fm))
            if target is not None and "__init__" in target.methods:
                return f"{target.rel_path}:{target.name}.__init__", f"{target.name}()"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        recv, meth = func.value, func.attr
        if isinstance(recv, ast.Name) and recv.id == "self" and self.cm is not None:
            if meth in self.cm.methods:
                return f"{self.cm.rel_path}:{self.cm.name}.{meth}", f"self.{meth}"
            return None
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and self.cm is not None
        ):
            bound = self.cm.attr_types.get(recv.attr)
            target = self.registry.get(bound) if bound else None
            if target is not None and meth in target.methods:
                return (
                    f"{target.rel_path}:{target.name}.{meth}",
                    f"self.{recv.attr}.{meth}",
                )
        return None

    def blocking_label(self, call: ast.Call) -> tuple[Optional[str], Optional[str]]:
        """(label, holder-lock id) for a blocking call, (None, None) if benign.

        ``Condition.wait`` on a held lock releases that lock for the wait, so
        it only counts as blocking with respect to OTHER locks still held.
        """
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None, None
        recv, attr = func.value, func.attr
        recv_src = ast.unparse(recv)
        holder = self.held[-1] if self.held else None
        if attr == "sleep" and recv_src == "time":
            return "time.sleep", holder
        if attr in BLOCKING_ATTRS:
            if attr == "wait":
                waited = self.lock_of(recv)
                if waited is not None and waited in self.held:
                    others = [h for h in self.held if h != waited]
                    if not others:
                        return None, None
                    return "wait", others[-1]
            return attr, holder
        if attr == "close" and CLOSE_RECEIVER_RE.search(recv_src):
            return "close", holder
        return None, None

    # -- traversal
    def run(self, fn: ast.FunctionDef) -> None:
        self._stmts(fn.body)

    def _stmts(self, stmts: list) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.With):
            taken: list[str] = []
            for item in stmt.items:
                self._expr(item.context_expr)
                lock_id = self.lock_of(item.context_expr)
                if lock_id is not None:
                    self._acquired(lock_id, stmt.lineno)
                    taken.append(lock_id)
            self.held.extend(taken)
            self._stmts(stmt.body)
            del self.held[len(self.held) - len(taken):]
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs (callbacks) run later, not under the current locks.
            saved, self.held = self.held, []
            self._stmts(stmt.body)
            self.held = saved
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.stmt, ast.excepthandler, ast.match_case)):
                self._stmt(child)
            else:
                self._expr(child)

    def _expr(self, node: ast.AST) -> None:
        if isinstance(node, ast.Lambda):
            # Deferred execution: the body does not run under current locks.
            saved, self.held = self.held, []
            self._expr(node.body)
            self.held = saved
            return
        if isinstance(node, ast.Call):
            self._call(node)
        for child in ast.iter_child_nodes(node):
            self._expr(child)

    def _call(self, call: ast.Call) -> None:
        func = call.func
        # Explicit lock.acquire() without a with-scope.
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            lock_id = self.lock_of(func.value)
            if lock_id is not None:
                self._acquired(lock_id, call.lineno)
                return
        label, holder = self.blocking_label(call)
        if label is not None:
            if self.s.blocks is None:
                self.s.blocks = f"{label} (line {call.lineno})"
            if holder is not None:
                self.blocking_sites.append(
                    (self.s.rel_path, call.lineno, self.s.qualname, label, holder)
                )
        resolved = self.callee_key(func)
        if resolved is not None:
            key, short = resolved
            self.s.calls.append(CallSite(
                callee=key, held=tuple(self.held), line=call.lineno,
                qualname=self.s.qualname, rel_path=self.s.rel_path, label=short,
            ))

    def _acquired(self, lock_id: str, lineno: int) -> None:
        self.s.acquires.add(lock_id)
        for holder in self.held:
            if holder != lock_id:
                self.edges.setdefault(
                    (holder, lock_id), (self.s.rel_path, lineno, self.s.qualname)
                )


# ------------------------------------------------------------------ checker
def build_lock_model(project: Project):
    """(summaries, edges, blocking_sites) — exposed for tests/tools."""
    file_models = {pf.rel_path: _build_file_model(pf) for pf in project.files}
    class_registry: dict[str, ClassModel] = {}
    for fm in file_models.values():
        for cm in fm.classes.values():
            class_registry[f"{fm.module_name}.{cm.name}"] = cm
    for fm in file_models.values():
        _bind_class_attrs(fm, class_registry)

    summaries: dict[str, FnSummary] = {}
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    blocking_sites: list[tuple[str, int, str, str, str]] = []

    def summarize(fm: FileModel, cm: Optional[ClassModel], fn: ast.FunctionDef, qual: str):
        key = f"{fm.pf.rel_path}:{qual}"
        s = FnSummary(key=key, rel_path=fm.pf.rel_path, qualname=qual)
        summaries[key] = s
        _FnWalker(s, fm, cm, class_registry, edges, blocking_sites).run(fn)

    for fm in file_models.values():
        for name, fn in fm.functions.items():
            summarize(fm, None, fn, name)
        for cm in fm.classes.values():
            for name, fn in cm.methods.items():
                summarize(fm, cm, fn, f"{cm.name}.{name}")

    # Fixed point: propagate acquire-sets and the blocks flag through calls.
    changed = True
    while changed:
        changed = False
        for s in summaries.values():
            for site in s.calls:
                callee = summaries.get(site.callee)
                if callee is None:
                    continue
                if not callee.acquires <= s.acquires:
                    s.acquires |= callee.acquires
                    changed = True
                callee_blocks = callee.blocks_trans or callee.blocks
                if callee_blocks and s.blocks_trans is None and s.blocks is None:
                    s.blocks_trans = f"via {site.label}: {callee_blocks}"
                    changed = True

    # Call-site effects: edges + blocking-through-calls.
    for s in summaries.values():
        for site in s.calls:
            callee = summaries.get(site.callee)
            if callee is None or not site.held:
                continue
            for holder in site.held:
                for acquired in callee.acquires:
                    if acquired != holder:
                        edges.setdefault(
                            (holder, acquired), (site.rel_path, site.line, site.qualname)
                        )
            callee_blocks = callee.blocks_trans or callee.blocks
            if callee_blocks:
                blocking_sites.append((
                    site.rel_path, site.line, site.qualname,
                    f"{site.label} -> {callee_blocks}", site.held[-1],
                ))
    return summaries, edges, blocking_sites


def _cycles(edges: dict[tuple[str, str], tuple[str, int, str]]) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:  # iterative Tarjan
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1 or node in graph[node]:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def check_lock_order(project: Project) -> list[Finding]:
    _, edges, blocking_sites = build_lock_model(project)
    findings: list[Finding] = []
    for scc in _cycles(edges):
        first_edge = next(
            ((a, b) for (a, b) in sorted(edges) if a in scc and b in scc), None
        )
        rel_path, line, qual = edges[first_edge] if first_edge else (scc[0].split(":")[0], 1, "<module>")
        findings.append(Finding(
            checker="lock-order",
            path=rel_path,
            line=line,
            qualname=qual,
            detail="cycle:" + "->".join(scc),
            message=(
                "lock-acquisition cycle (potential deadlock by circular "
                "wait): " + " -> ".join(scc)
            ),
        ))
    seen: set[str] = set()
    for rel_path, line, qual, label, holder in blocking_sites:
        lock_short = holder.split(":")[-1]
        f = Finding(
            checker="lock-order",
            path=rel_path,
            line=line,
            qualname=qual,
            detail=f"blocking:{label.split(' ')[0].split(':')[0]}@{lock_short}",
            message=(
                f"blocking call ({label}) while holding lock {holder}; "
                "move the slow operation outside the critical section"
            ),
        )
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            findings.append(f)
    return findings
