"""CLI: ``python -m tieredstorage_tpu.analysis`` (a.k.a. ``make analyze``).

Exit status: 0 when every finding is suppressed-with-justification and no
suppression is stale; 1 otherwise. ``--json`` writes the machine-readable
report (uploaded as a CI artifact next to the demo reports).

``--paths <files...>`` is the INCREMENTAL developer mode: only the given
files are parsed (through a content-hash parse cache under ``artifacts/``,
so an editor-save lint loop on a small diff is sub-second), every checker
runs on that subset, and the stale-suppression gate is skipped (a subset
cannot see every finding a suppression covers). Cross-module context
outside the given files is invisible there, so full-project mode — plain
``make analyze`` — stays the CI gate.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tieredstorage_tpu.analysis.core import (
    Suppressions,
    SuppressionError,
    checker_registry,
    load_project,
    run_analysis,
)

DEFAULT_SUPPRESSIONS = "tools/analysis_suppressions.txt"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tieredstorage_tpu.analysis", description=__doc__
    )
    ap.add_argument(
        "--root", default=None,
        help="repo root (default: autodetected from the package location)",
    )
    ap.add_argument(
        "--json", default=None, metavar="PATH",
        help="write the JSON findings artifact here",
    )
    ap.add_argument(
        "--suppressions", default=None, metavar="PATH",
        help=f"suppression file (default: <root>/{DEFAULT_SUPPRESSIONS})",
    )
    ap.add_argument(
        "--checker", action="append", default=None, metavar="NAME",
        help="run only this checker (repeatable); default: all",
    )
    ap.add_argument(
        "--scan", action="append", default=None, metavar="DIR",
        help="directory/file under root to scan (default: tieredstorage_tpu)",
    )
    ap.add_argument(
        "--paths", nargs="+", default=None, metavar="FILE",
        help="incremental mode: analyze only these files (repo-relative), "
        "via the parse cache; stale-suppression check skipped",
    )
    ap.add_argument(
        "--parse-cache", default=None, metavar="PATH",
        help="parse-cache pickle for --paths mode "
        "(default: <root>/artifacts/analysis_parse_cache.pkl)",
    )
    ap.add_argument(
        "--list-checkers", action="store_true", help="list checkers and exit"
    )
    ap.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress the per-finding text output (summary only)",
    )
    args = ap.parse_args(argv)

    if args.list_checkers:
        for name in checker_registry():
            print(name)
        return 0

    root = (
        Path(args.root).resolve()
        if args.root
        else Path(__file__).resolve().parents[2]
    )
    suppressions_path = (
        Path(args.suppressions) if args.suppressions else root / DEFAULT_SUPPRESSIONS
    )
    try:
        suppressions = Suppressions.load(suppressions_path)
    except SuppressionError as e:
        print(f"analysis: bad suppression file: {e}", file=sys.stderr)
        return 2

    if args.paths:
        cache = (
            Path(args.parse_cache)
            if args.parse_cache
            else root / "artifacts" / "analysis_parse_cache.pkl"
        )
        scan = [
            Path(p).resolve().relative_to(root).as_posix()
            if Path(p).is_absolute()
            else p
            for p in args.paths
        ]
        project = load_project(root, scan, cache_path=cache)
    else:
        project = load_project(root, args.scan)
    only = args.checker
    if args.paths and only is None:
        # config-drift's declared-keys check is whole-project by nature
        # (declarations live in other files); a subset view would flood
        # with false undeclared-key findings.
        only = [n for n in checker_registry() if n != "config-drift"]
    report = run_analysis(project, suppressions=suppressions, only=only)
    if args.paths:
        # Subset view: a suppression whose finding lives elsewhere is not
        # stale — drop unmatched entries so only real findings gate.
        for fingerprint in report.stale_suppressions:
            del report.suppressions.entries[fingerprint]

    if args.json:
        report.write_json(Path(args.json))
    text = report.render_text()
    if args.quiet:
        text = "\n".join(text.splitlines()[-2:])
    print(text)
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
