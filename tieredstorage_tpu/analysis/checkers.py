"""Per-file invariant checkers: deadline discipline, bounded concurrency,
monotonic clock, swallowed exceptions.

Each is a small AST pass with project-specific knowledge encoded up front
(the request-path module set, the sanctioned-daemon registry, the
deadline-wrapper allowlist) so that a violation is a *finding*, not a style
opinion: every rule here maps to a production invariant the serving tier
already relies on (PR 4's Deadline budget, PR 6's bounded pools).
"""

from __future__ import annotations

import ast
from typing import Optional

from tieredstorage_tpu.analysis.core import Finding, Project

# ---------------------------------------------------------------- deadline
#: Modules on the request path: every blocking wait here must clamp its
#: timeout to the end-to-end Deadline budget (utils/deadline.py).
REQUEST_PATH_PREFIXES = (
    "tieredstorage_tpu/storage/",
    "tieredstorage_tpu/fetch/",
    "tieredstorage_tpu/fleet/",
    "tieredstorage_tpu/sidecar/",
)

#: Identifier fragments that mark a timeout expression as budget-derived:
#: the Deadline API (remaining/deadline/budget), an explicit timeout knob
#: plumbed from config, or a hedge delay (itself p95-derived and bounded).
DEADLINE_NAME_FRAGMENTS = (
    "deadline", "remaining", "budget", "timeout", "delay", "grace",
)

#: Functions that ARE the sanctioned daemons' run loops: their idle waits
#: pace a background thread (interval sleeps), not a caller's request.
DAEMON_LOOP_FUNCTIONS = {
    "tieredstorage_tpu/storage/replicated.py:HealthProber._run",
    "tieredstorage_tpu/sidecar/server.py:main",
    "tieredstorage_tpu/fleet/gossip.py:GossipAgent._run",
    "tieredstorage_tpu/transform/batcher.py:WindowBatcher._run",
}

#: Blocking-wait method names checked for a clamped timeout argument.
WAIT_METHODS = {"wait", "result"}


def _timeout_expr(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return kw.value
    if call.args:
        return call.args[0]
    return None


def _mentions_budget(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.keyword):
            name = node.arg
        if name and any(frag in name.lower() for frag in DEADLINE_NAME_FRAGMENTS):
            return True
    return False


def check_deadline_discipline(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for pf in project.files:
        if not pf.rel_path.startswith(REQUEST_PATH_PREFIXES):
            continue
        for node in pf.walk():
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr not in WAIT_METHODS:
                continue
            qual = pf.qualname_of(node)
            if f"{pf.rel_path}:{qual}" in DAEMON_LOOP_FUNCTIONS:
                continue
            recv = ast.unparse(node.func.value)
            timeout = _timeout_expr(node)
            if timeout is None:
                findings.append(Finding(
                    checker="deadline",
                    path=pf.rel_path,
                    line=node.lineno,
                    qualname=qual,
                    detail=f"unbounded:{node.func.attr}@{recv}",
                    message=(
                        f"unbounded blocking {node.func.attr}() on {recv!r} in a "
                        "request-path module; pass a timeout clamped to the "
                        "remaining Deadline budget"
                    ),
                ))
            elif not _mentions_budget(timeout):
                findings.append(Finding(
                    checker="deadline",
                    path=pf.rel_path,
                    line=node.lineno,
                    qualname=qual,
                    detail=f"unclamped:{node.func.attr}@{recv}",
                    message=(
                        f"blocking {node.func.attr}() on {recv!r} has a timeout "
                        f"({ast.unparse(timeout)!r}) that is not derived from the "
                        "Deadline budget (expected a deadline/remaining/budget/"
                        "timeout/delay expression)"
                    ),
                ))
    return findings


# ----------------------------------------------------- bounded concurrency
#: The ONLY places allowed to spawn a raw thread: long-lived, named,
#: daemonized singletons with a stop() path. Everything else must ride a
#: bounded executor.
SANCTIONED_THREAD_SPAWNS = {
    "tieredstorage_tpu/metrics/prometheus.py:PrometheusExporter.__init__":
        "metrics exporter serve loop (one per endpoint, stopped via close)",
    "tieredstorage_tpu/storage/replicated.py:HealthProber.start":
        "replica health-probe daemon (one per replicated backend)",
    "tieredstorage_tpu/scrub/antientropy.py:AntiEntropyScheduler.start":
        "anti-entropy daemon (one per RSM)",
    "tieredstorage_tpu/scrub/scheduler.py:ScrubScheduler.start":
        "scrub daemon (one per RSM)",
    "tieredstorage_tpu/scrub/sweeper.py:SweepScheduler.start":
        "recovery-sweep daemon (one per RSM, stopped via stop)",
    "tieredstorage_tpu/sidecar/http_gateway.py:SidecarHttpGateway.start":
        "gateway accept loop (workers ride the bounded executor)",
    "tieredstorage_tpu/fleet/gossip.py:GossipAgent.start":
        "gossip membership daemon (one per fleet member, stopped via stop)",
    "tieredstorage_tpu/transform/batcher.py:WindowBatcher.start":
        "cross-request GCM flush daemon (one device queue per backend, "
        "stopped via stop)",
}


def check_bounded_concurrency(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for pf in project.files:
        for node in pf.walk():
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            qual = pf.qualname_of(node)
            site = f"{pf.rel_path}:{qual}"
            if name in ("threading.Thread", "Thread", "_thread.start_new_thread",
                        "multiprocessing.Process"):
                if site in SANCTIONED_THREAD_SPAWNS:
                    if not any(
                        kw.arg == "daemon"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in node.keywords
                    ):
                        findings.append(Finding(
                            checker="bounded-concurrency",
                            path=pf.rel_path, line=node.lineno, qualname=qual,
                            detail="thread-not-daemon",
                            message=(
                                "sanctioned daemon thread must pass daemon=True "
                                "(a wedged loop must not block interpreter exit)"
                            ),
                        ))
                    continue
                findings.append(Finding(
                    checker="bounded-concurrency",
                    path=pf.rel_path, line=node.lineno, qualname=qual,
                    detail="unsanctioned-thread",
                    message=(
                        "bare threading.Thread outside the sanctioned-daemon "
                        "registry; use a bounded executor, or register the "
                        "daemon in analysis/checkers.py:SANCTIONED_THREAD_SPAWNS"
                    ),
                ))
            elif name is not None and name.split(".")[-1] == "ThreadPoolExecutor":
                if not any(kw.arg == "max_workers" for kw in node.keywords) and not node.args:
                    findings.append(Finding(
                        checker="bounded-concurrency",
                        path=pf.rel_path, line=node.lineno, qualname=qual,
                        detail="unbounded-executor",
                        message=(
                            "ThreadPoolExecutor without max_workers (defaults "
                            "to cpu*5 threads); size the pool explicitly"
                        ),
                    ))
    return findings


def _call_name(call: ast.Call) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        parts = []
        node: ast.AST = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
    return None


# ------------------------------------------------------------ monotonic clock
def check_monotonic_clock(project: Project) -> list[Finding]:
    """``time.time()`` is wall clock: NTP steps make durations computed from
    it lie, so timeouts/intervals/latency math must use ``time.monotonic()``.
    The rare protocol-mandated wall-clock read (JWT iat/exp) carries a
    suppression with its justification."""
    findings: list[Finding] = []
    for pf in project.files:
        for node in pf.walk():
            if (
                isinstance(node, ast.Call)
                and _call_name(node) in ("time.time", "time.clock")
            ):
                qual = pf.qualname_of(node)
                findings.append(Finding(
                    checker="monotonic-clock",
                    path=pf.rel_path, line=node.lineno, qualname=qual,
                    detail="time.time",
                    message=(
                        "time.time() is wall clock (steps under NTP); use "
                        "time.monotonic() for durations/timeouts, or suppress "
                        "with a justification if wall time is protocol-required"
                    ),
                ))
    return findings


# --------------------------------------------------------- swallowed except
BROAD_EXCEPTION_NAMES = {"Exception", "BaseException"}


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:  # bare except
        return True
    types = (
        handler.type.elts if isinstance(handler.type, ast.Tuple) else [handler.type]
    )
    for t in types:
        name = t.id if isinstance(t, ast.Name) else (
            t.attr if isinstance(t, ast.Attribute) else None
        )
        if name in BROAD_EXCEPTION_NAMES:
            return True
    return False


def _is_empty_body(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


def check_swallowed_exceptions(project: Project) -> list[Finding]:
    """A broad ``except Exception: pass`` erases failures with no trace
    event, metric, or log — the scrubber arc (PR 3) exists because silent
    failure is the worst failure. Narrow catches (``except KeyError: pass``)
    are the deliberate-fallback idiom and stay legal; broad handlers must
    *do* something (counter bump, tracer event, log, re-raise)."""
    findings: list[Finding] = []
    for pf in project.files:
        for node in pf.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not (_is_broad_handler(node) and _is_empty_body(node.body)):
                continue
            qual = pf.qualname_of(node)
            caught = ast.unparse(node.type) if node.type else "<bare>"
            findings.append(Finding(
                checker="swallowed-exception",
                path=pf.rel_path, line=node.lineno, qualname=qual,
                detail=f"swallow:{caught}",
                message=(
                    f"broad 'except {caught}' with an empty body swallows "
                    "failures silently; record a metric/trace event/log (or "
                    "narrow the exception type)"
                ),
            ))
    return findings


__all__ = [
    "check_deadline_discipline",
    "check_bounded_concurrency",
    "check_monotonic_clock",
    "check_swallowed_exceptions",
    "SANCTIONED_THREAD_SPAWNS",
    "DAEMON_LOOP_FUNCTIONS",
    "REQUEST_PATH_PREFIXES",
]
