"""Config/metrics drift gates.

Two mechanically-checkable invariants tie code to docs:

1. **Declared reads.** Every config key the code reads (``..._values["k"]``
   subscripts and constant ``_props.get("k")`` lookups) must be declared as
   a ``ConfigKey`` somewhere in the tree. Dynamic key families
   (``encryption.key.pairs.<id>.*``, ``replication.replica.<name>.*``) are
   declared by prefix.

2. **Generated docs.** ``docs/configs.rst`` and ``docs/metrics.rst`` are
   GENERATED from the live ConfigDefs / metric registries (``make docs``);
   this checker re-generates both in-process and diffs them against the
   committed files, so a new key or metric cannot merge undocumented. When
   the generator imports are unavailable (e.g. a no-jax environment) the
   docs half degrades to a note in the JSON report — CI always has the
   dependencies, so the gate still binds where it matters.
"""

from __future__ import annotations

import ast

from tieredstorage_tpu.analysis.core import Finding, Project

#: Key families defined dynamically (two-phase define / reflective config).
DYNAMIC_KEY_PREFIXES = (
    "encryption.key.pairs.",
    "replication.replica.",
)

_GENERATED_DOCS = (
    ("docs/configs.rst", "tieredstorage_tpu.docs.configs_docs"),
    ("docs/metrics.rst", "tieredstorage_tpu.docs.metrics_docs"),
)


def _declared_keys(project: Project) -> set[str]:
    declared: set[str] = set()
    for pf in project.files:
        for node in pf.walk():
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = func.id if isinstance(func, ast.Name) else (
                func.attr if isinstance(func, ast.Attribute) else None
            )
            if name != "ConfigKey" or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                declared.add(first.value)
    return declared


def _read_keys(project: Project) -> list[tuple[str, int, str, str]]:
    """(rel_path, line, qualname, key) for every constant config read."""
    reads: list[tuple[str, int, str, str]] = []
    for pf in project.files:
        for node in pf.walk():
            key = None
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr.endswith("_values")
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
            ):
                key = node.slice.value
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr.endswith("_props")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                key = node.args[0].value
            if key is not None:
                reads.append((pf.rel_path, node.lineno, pf.qualname_of(node), key))
    return reads


def check_config_drift(project: Project) -> list:
    findings: list = []
    declared = _declared_keys(project)
    for rel_path, line, qual, key in _read_keys(project):
        if key in declared or key.startswith(DYNAMIC_KEY_PREFIXES):
            continue
        findings.append(Finding(
            checker="config-drift",
            path=rel_path, line=line, qualname=qual,
            detail=f"undeclared-key:{key}",
            message=(
                f"config key {key!r} is read here but not declared as a "
                "ConfigKey (config/rsm_config.py et al.)"
            ),
        ))

    # Declared-but-undocumented: every key of the central def must render in
    # the committed configs.rst (cheap text containment; the full diff below
    # is the authoritative gate when generators are importable).
    configs_rst = project.root / "docs" / "configs.rst"
    rst_text = configs_rst.read_text() if configs_rst.exists() else ""
    rsm_config = project.file("tieredstorage_tpu/config/rsm_config.py")
    if rsm_config is not None and rst_text:
        central = _declared_keys(Project(project.root, [rsm_config]))
        for key in sorted(central):
            if f"``{key}``" not in rst_text:
                findings.append(Finding(
                    checker="config-drift",
                    path="docs/configs.rst", line=1, qualname="<doc>",
                    detail=f"undocumented-key:{key}",
                    message=(
                        f"config key {key!r} is declared but missing from "
                        "docs/configs.rst - run `make docs`"
                    ),
                ))

    findings.extend(_check_generated_docs(project))
    return findings


def _check_generated_docs(project: Project) -> list:
    results: list = []
    if project.file("tieredstorage_tpu/config/rsm_config.py") is None:
        return results  # fixture tree, not the real repo: nothing to diff
    for rel, module_name in _GENERATED_DOCS:
        committed = project.root / rel
        if not committed.exists():
            results.append(Finding(
                checker="config-drift", path=rel, line=1, qualname="<doc>",
                detail="missing-doc",
                message=f"{rel} is missing - run `make docs`",
            ))
            continue
        try:
            import importlib

            module = importlib.import_module(module_name)
            generated = module.generate()
        except Exception as e:  # degrade to a note (no-jax environments)
            results.append(
                f"config-drift: {rel} not re-generated here "
                f"({type(e).__name__}: {e}); CI runs the full diff"
            )
            continue
        if generated != committed.read_text():
            results.append(Finding(
                checker="config-drift", path=rel, line=1, qualname="<doc>",
                detail="stale-generated-doc",
                message=(
                    f"{rel} does not match the output of {module_name} - "
                    "run `make docs` and commit the result"
                ),
            ))
    return results
