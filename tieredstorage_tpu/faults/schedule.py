"""Seedable, deterministic fault schedules.

A schedule is a list of rules, each written as

    op ":" action ["=" arg] ["@" trigger]

- op: ``upload`` | ``fetch`` | ``delete`` | ``list`` | ``*`` (any operation)
- action:
    - ``raise`` — raise FaultInjectedException (a StorageBackendException)
    - ``key-not-found`` — raise KeyNotFoundException for the requested key
    - ``delay`` — sleep ``arg`` milliseconds (default 10) before the call;
      a jittered range ``delay=10..250`` sleeps a value drawn uniformly
      from [10, 250] ms by the schedule's seeded RNG — realistic
      tail-latency distributions instead of fixed sleeps
    - ``truncate`` — keep only the first ``arg`` bytes of a fetched object
      (default: half); fetch only
    - ``corrupt`` — flip the fetched byte at offset ``arg`` (default 0,
      taken modulo the object size); fetch only
- trigger:
    - ``@N`` — fire on the Nth call of that op (1-based)
    - ``@every=K`` — fire on every Kth call of that op
    - ``@from=N`` — fire on EVERY call from the Nth onward (1-based): a
      hard failure that starts mid-run and never recovers, e.g. killing a
      replica partway through a workload (``fetch:raise@from=20``)
    - ``@p=P`` — fire with probability P, drawn from the schedule's seeded
      RNG (deterministic for a given seed and call sequence)
    - absent — fire on every call

Examples: ``upload:raise@3``, ``fetch:corrupt=7@1``, ``*:delay=5@every=2``,
``fetch:delay=10..250@p=0.2``, ``fetch:truncate@p=0.1``. Rules are combined
with ``,`` or ``;`` in the
string form (``fault.schedule`` config) or passed as a list.

Call counting is per op and thread-safe; every fired rule is recorded in
``FaultSchedule.injections`` so tests and soak runs can assert on what was
actually injected.
"""

from __future__ import annotations

import dataclasses
import random
import re
from collections import Counter
from typing import Iterable, Optional, Sequence, Union

from tieredstorage_tpu.storage.core import StorageBackendException
from tieredstorage_tpu.utils.locks import new_lock

OPS = ("upload", "fetch", "delete", "list")
ACTIONS = ("raise", "key-not-found", "delay", "truncate", "corrupt")
#: Actions that mutate fetched bytes instead of failing the call.
DATA_ACTIONS = ("truncate", "corrupt")


class FaultInjectedException(StorageBackendException):
    """Raised by an injected `raise` fault."""


_RULE_RE = re.compile(
    r"(?P<op>\*|upload|fetch|delete|list)\s*:\s*(?P<action>[a-z-]+)"
    r"(?:\s*=\s*(?P<arg>\d+(?:\s*\.\.\s*\d+)?))?(?:\s*@\s*(?P<trigger>[a-z0-9.=]+))?"
)


@dataclasses.dataclass(frozen=True)
class FaultRule:
    op: str  # "upload" | "fetch" | "delete" | "*"
    action: str
    arg: Optional[int] = None
    nth: Optional[int] = None
    every: Optional[int] = None
    #: Fire on every call from the Nth onward (permanent failure mid-run).
    from_nth: Optional[int] = None
    probability: Optional[float] = None
    #: Upper bound of a jittered ``delay=lo..hi`` range (delay only); the
    #: actual sleep is drawn per firing from the schedule's seeded RNG.
    arg_hi: Optional[int] = None

    def __post_init__(self) -> None:
        if self.op != "*" and self.op not in OPS:
            raise ValueError(f"Unknown fault op {self.op!r}; must be one of {OPS} or '*'")
        if self.action not in ACTIONS:
            raise ValueError(
                f"Unknown fault action {self.action!r}; must be one of {ACTIONS}"
            )
        if self.action in DATA_ACTIONS and self.op not in ("fetch", "*"):
            raise ValueError(f"Action {self.action!r} only applies to fetch")
        if self.every is not None and self.every < 1:
            raise ValueError("every must be >= 1")
        if self.nth is not None and self.nth < 1:
            raise ValueError("nth must be >= 1")
        if self.from_nth is not None and self.from_nth < 1:
            raise ValueError("from must be >= 1")
        if self.probability is not None and not (0.0 <= self.probability <= 1.0):
            raise ValueError("probability must be in [0, 1]")
        if self.arg_hi is not None:
            if self.action != "delay":
                raise ValueError("range args (lo..hi) only apply to delay")
            if self.arg is None or self.arg_hi < self.arg:
                raise ValueError(
                    f"delay range must be lo..hi with hi >= lo, "
                    f"got {self.arg}..{self.arg_hi}"
                )

    @staticmethod
    def parse(text: str) -> "FaultRule":
        m = _RULE_RE.fullmatch(text.strip())
        if m is None:
            raise ValueError(
                f"Invalid fault rule {text!r}; expected op:action[=arg][@trigger]"
            )
        nth = every = from_nth = None
        probability = None
        trigger = m.group("trigger")
        if trigger is not None:
            if trigger.isdigit():
                nth = int(trigger)
            elif trigger.startswith("every="):
                every = int(trigger[len("every="):])
            elif trigger.startswith("from="):
                from_nth = int(trigger[len("from="):])
            elif trigger.startswith("p="):
                probability = float(trigger[len("p="):])
            else:
                raise ValueError(
                    f"Invalid fault trigger {trigger!r}; expected N, every=K, "
                    "from=N, or p=P"
                )
        arg = m.group("arg")
        arg_lo = arg_hi = None
        if arg is not None:
            if ".." in arg:
                lo, _, hi = arg.partition("..")
                arg_lo, arg_hi = int(lo), int(hi)
            else:
                arg_lo = int(arg)
        return FaultRule(
            op=m.group("op"),
            action=m.group("action"),
            arg=arg_lo,
            nth=nth,
            every=every,
            from_nth=from_nth,
            probability=probability,
            arg_hi=arg_hi,
        )

    def matches_op(self, op: str) -> bool:
        return self.op == "*" or self.op == op


class FaultSchedule:
    """Evaluates rules against a per-op call counter; fully deterministic
    for a given seed and call sequence."""

    def __init__(self, rules: Iterable[FaultRule], *, seed: int = 0) -> None:
        self._rules = list(rules)
        self._rng = random.Random(seed)
        self._calls: Counter[str] = Counter()
        self._lock = new_lock("schedule.FaultSchedule._lock")
        #: Every fired rule as (op, action, key string), in order.
        self.injections: list[tuple[str, str, str]] = []

    @classmethod
    def parse(
        cls, spec: Union[str, Sequence[str], None], *, seed: int = 0
    ) -> "FaultSchedule":
        if spec is None:
            spec = []
        elif isinstance(spec, str):
            spec = [spec]
        # Config "list" values split on commas only; rules joined with ";"
        # arrive as one element, so re-split every element on both.
        parts = [q for p in spec for q in re.split(r"[;,]", str(p)) if q.strip()]
        return cls([FaultRule.parse(q) for q in parts], seed=seed)

    @property
    def rules(self) -> list[FaultRule]:
        return list(self._rules)

    def calls(self, op: str) -> int:
        with self._lock:
            return self._calls[op]

    def fired_rules(self, op: str, key: object) -> list[FaultRule]:
        """Count one `op` call and return the rules that fire on it."""
        with self._lock:
            self._calls[op] += 1
            call_no = self._calls[op]
            fired = [
                r for r in self._rules
                if r.matches_op(op) and self._fires_locked(r, call_no)
            ]
            for r in fired:
                self.injections.append((op, r.action, str(key)))
            return fired

    def delay_ms(self, rule: FaultRule) -> float:
        """Sleep duration for a fired `delay` rule: the fixed arg (default
        10 ms), or — for a jittered ``delay=lo..hi`` range — a uniform draw
        from the schedule's seeded RNG, so chaos runs get realistic
        tail-latency distributions that are still reproducible."""
        if rule.arg is None:
            return 10.0
        if rule.arg_hi is None:
            return float(rule.arg)
        with self._lock:
            return self._rng.uniform(rule.arg, rule.arg_hi)

    def _fires_locked(self, rule: FaultRule, call_no: int) -> bool:
        if rule.nth is not None:
            return call_no == rule.nth
        if rule.every is not None:
            return call_no % rule.every == 0
        if rule.from_nth is not None:
            return call_no >= rule.from_nth
        if rule.probability is not None:
            return self._rng.random() < rule.probability
        return True

    def __len__(self) -> int:
        return len(self._rules)
