"""Deterministic fault injection for storage backends.

`FaultInjectingBackend` wraps any `StorageBackend` and executes a seedable
`FaultSchedule` (raise on the Nth upload/fetch/delete, truncate or corrupt
fetched bytes, inject latency). Used by the chaos test suite directly and by
soak runs through the `fault.injection.enabled` RSM config flag.
"""

from tieredstorage_tpu.faults.backend import FaultInjectingBackend
from tieredstorage_tpu.faults.schedule import (
    FaultInjectedException,
    FaultRule,
    FaultSchedule,
)

__all__ = [
    "FaultInjectedException",
    "FaultInjectingBackend",
    "FaultRule",
    "FaultSchedule",
]
