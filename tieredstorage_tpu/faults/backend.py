"""FaultInjectingBackend: a StorageBackend decorator executing a FaultSchedule.

Transparent when the schedule is empty; otherwise each upload/fetch/delete is
counted against the schedule and any fired rule either fails the call
(`raise`, `key-not-found`), slows it (`delay`), or mutates fetched bytes
(`truncate`, `corrupt`). `delete_all` is inherited from ObjectDeleter's
per-key loop so multi-deletes see per-key faults too.

Two entry points:
- wrap programmatically: ``FaultInjectingBackend(delegate, schedule)`` —
  what the chaos tests do;
- configure reflectively as ``storage.backend.class`` with
  ``fault.delegate.class`` + ``fault.schedule`` (+ ``fault.seed``); every
  non-``fault.*`` key is passed through to the delegate — what soak stacks
  do. The RSM-level ``fault.injection.enabled`` flag wraps the configured
  backend the same way without touching storage configs.
"""

from __future__ import annotations

import io
import time
from typing import BinaryIO, Mapping, Optional

from tieredstorage_tpu.config.configdef import ConfigDef, ConfigKey
from tieredstorage_tpu.faults.schedule import (
    DATA_ACTIONS,
    FaultInjectedException,
    FaultRule,
    FaultSchedule,
)
from tieredstorage_tpu.storage.core import (
    BytesRange,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    load_backend_class,
)


def _definition() -> ConfigDef:
    d = ConfigDef()
    d.define(ConfigKey(
        "fault.delegate.class", "string", default=None, importance="low",
        doc="Backend class to wrap when FaultInjectingBackend is configured "
            "as storage.backend.class.",
    ))
    d.define(ConfigKey(
        "fault.schedule", "list", default=[], importance="low",
        doc="Fault rules 'op:action[=arg][@trigger]' (see faults/schedule.py).",
    ))
    d.define(ConfigKey(
        "fault.seed", "long", default=0, importance="low",
        doc="Seed for probabilistic fault triggers.",
    ))
    return d


class FaultInjectingBackend(StorageBackend):
    def __init__(
        self,
        delegate: Optional[StorageBackend] = None,
        schedule: Optional[FaultSchedule] = None,
    ) -> None:
        self._delegate = delegate
        self._schedule = schedule if schedule is not None else FaultSchedule([])

    @property
    def delegate(self) -> StorageBackend:
        return self._delegate

    @property
    def schedule(self) -> FaultSchedule:
        return self._schedule

    def configure(self, configs: Mapping[str, object]) -> None:
        values = _definition().parse(configs)
        if values["fault.schedule"]:
            self._schedule = FaultSchedule.parse(
                values["fault.schedule"], seed=values["fault.seed"]
            )
        if self._delegate is None:
            class_path = values["fault.delegate.class"]
            if class_path is None:
                raise ValueError(
                    "fault.delegate.class must be provided when "
                    "FaultInjectingBackend is constructed without a delegate"
                )
            self._delegate = load_backend_class(str(class_path))()
        passthrough = {
            k: v for k, v in configs.items() if not str(k).startswith("fault.")
        }
        self._delegate.configure(passthrough)

    # ------------------------------------------------------------- injection
    def _apply(self, op: str, key: ObjectKey) -> list[FaultRule]:
        """Execute fail/delay rules; return data-mutation rules for fetch."""
        data_rules: list[FaultRule] = []
        for rule in self._schedule.fired_rules(op, key):
            if rule.action == "delay":
                # Fixed arg, or a seeded uniform draw for `delay=lo..hi`.
                time.sleep(self._schedule.delay_ms(rule) / 1000.0)
            elif rule.action == "raise":
                raise FaultInjectedException(
                    f"Injected {op} fault for {key} "
                    f"(call #{self._schedule.calls(op)})"
                )
            elif rule.action == "key-not-found":
                raise KeyNotFoundException(self, key)
            elif rule.action in DATA_ACTIONS:
                data_rules.append(rule)
        return data_rules

    @staticmethod
    def _mutate(data: bytes, rules: list[FaultRule]) -> bytes:
        for rule in rules:
            if not data:
                continue
            if rule.action == "truncate":
                keep = rule.arg if rule.arg is not None else len(data) // 2
                data = data[:keep]
            elif rule.action == "corrupt":
                pos = (rule.arg if rule.arg is not None else 0) % len(data)
                data = data[:pos] + bytes([data[pos] ^ 0xFF]) + data[pos + 1:]
        return data

    # ------------------------------------------------------------- contract
    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        self._apply("upload", key)
        return self._delegate.upload(input_stream, key)

    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        data_rules = self._apply("fetch", key)
        stream = self._delegate.fetch(key, byte_range)
        if not data_rules:
            return stream
        with stream:
            data = stream.read()
        return io.BytesIO(self._mutate(data, data_rules))

    def delete(self, key: ObjectKey) -> None:
        self._apply("delete", key)
        self._delegate.delete(key)

    def list_objects(self, prefix: str = ""):
        # Listing faults fail/slow the whole enumeration; data actions are
        # fetch-only and cannot fire here (schedule-level validation).
        self._apply("list", ObjectKey(prefix))
        return self._delegate.list_objects(prefix)

    def __str__(self) -> str:
        return f"FaultInjectingBackend{{delegate={self._delegate}}}"
