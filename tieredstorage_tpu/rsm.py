"""RemoteStorageManager: the KIP-405-shaped orchestration layer (reference L1).

Reference: core/src/main/java/io/aiven/kafka/tieredstorage/RemoteStorageManager.java —
configure wires every component (:143-182), copyLogSegmentData uploads the
transformed segment + concatenated indexes + manifest triple (:212-278),
fetchLogSegment serves ranged reads through the chunk path (:539-576),
fetchIndex serves index slices (:594-622), deleteLogSegmentData removes the
triple (:673-697), with orphan cleanup on failed uploads (:258-267).

The transform itself runs through the batched TransformBackend seam instead of
the reference's per-chunk Enumeration chain.
"""

from __future__ import annotations

import contextlib
import io
import functools
import logging
import time
from pathlib import Path
from typing import BinaryIO, Mapping, Optional

from tieredstorage_tpu.config.rsm_config import RemoteStorageManagerConfig
from tieredstorage_tpu.custom_metadata import (
    SegmentCustomMetadataBuilder,
    SegmentCustomMetadataField,
    deserialize_custom_metadata,
    serialize_custom_metadata,
)
from tieredstorage_tpu.errors import RemoteResourceNotFoundException, RemoteStorageException
from tieredstorage_tpu.fetch.cache.chunk_cache import ChunkCache
from tieredstorage_tpu.fetch.chunk_manager import ChunkManager, DefaultChunkManager
from tieredstorage_tpu.fetch.factory import ChunkManagerFactory
from tieredstorage_tpu.fetch.enumeration import FetchChunkEnumeration
from tieredstorage_tpu.fetch.index_cache import MemorySegmentIndexesCache
from tieredstorage_tpu.fetch.manifest_cache import (
    ManifestLookahead,
    MemorySegmentManifestCache,
)
from tieredstorage_tpu.fetch.readahead import ReadaheadManager
from tieredstorage_tpu.kafka_records import InvalidRecordBatchException, segment_looks_compressed
from tieredstorage_tpu.manifest.encryption_metadata import SegmentEncryptionMetadataV1
from tieredstorage_tpu.manifest.segment_indexes import IndexType, SegmentIndexesV1Builder
from tieredstorage_tpu.manifest.segment_manifest import (
    SegmentManifestV1,
    manifest_from_json,
    manifest_to_json,
)
from tieredstorage_tpu.metadata import LogSegmentData, RemoteLogSegmentMetadata
from tieredstorage_tpu.metrics.cache_metrics import (
    DiskCacheMetrics,
    register_cache_metrics,
    register_thread_pool_metrics,
)
from tieredstorage_tpu.metrics.core import MetricConfig
from tieredstorage_tpu.metrics.rsm_metrics import (
    Metrics,
    register_resilience_metrics,
    register_tracer_metrics,
)
from tieredstorage_tpu.object_key import ObjectKeyFactory, Suffix
from tieredstorage_tpu.security.aes import AesEncryptionProvider, DataKeyAndAAD
from tieredstorage_tpu.security.rsa import RsaEncryptionProvider
from tieredstorage_tpu.storage.core import (
    BytesRange,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
)
from tieredstorage_tpu.fetch.hedge import HedgeBudget, Hedger
from tieredstorage_tpu.fleet import (
    FleetMetrics,
    FleetRouter,
    GossipAgent,
    PeerChunkCache,
    parse_instances,
    register_fleet_metrics,
)
from tieredstorage_tpu.storage.replicated import ReplicatedStorageBackend
from tieredstorage_tpu.storage.resilient import (
    CircuitBreaker,
    ResilientStorageBackend,
    RetryBudget,
)
from tieredstorage_tpu.transform.api import DetransformOptions, TransformOptions
from tieredstorage_tpu.transform.pipeline import SegmentTransformation
from tieredstorage_tpu.utils import deadline as deadline_util
from tieredstorage_tpu.utils.admission import AdmissionController
from tieredstorage_tpu.utils.deadline import (
    DeadlineExceededException,
    check_deadline,
    ensure_deadline,
)
from tieredstorage_tpu.utils import faults, flightrecorder as flight
from tieredstorage_tpu.metrics.timeline import NOOP_TIMELINE, TimelineRecorder
from tieredstorage_tpu.utils.flightrecorder import NOOP_RECORDER, FlightRecorder
from tieredstorage_tpu.utils.ratelimit import RateLimitedStream, TokenBucket
from tieredstorage_tpu.utils.tracing import NOOP_TRACER, Tracer
from tieredstorage_tpu.utils.streams import ClosableStreamHolder

log = logging.getLogger(__name__)


def _traced(name: str):
    """Span around an RSM operation, tagged with topic/partition (SURVEY §5:
    the reference only has SLF4J boundary logs; these spans also forward
    into jax.profiler timelines when tracing.jax.profiler.enabled).

    Also the deadline entry point: the operation adopts the ambient
    end-to-end Deadline (installed by the sidecar boundary from the caller's
    x-deadline-ms) or starts one from `deadline.default.ms`, and an
    already-expired budget fails fast here — before any storage work.

    The flight recorder (ISSUE 14) opens its per-request record here too,
    keyed by the span's trace id — reentrant, so when the HTTP gateway
    already opened one for the whole request (covering the streamed drain)
    this entry joins it instead of splitting the evidence."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, metadata, *args, **kwargs):
            tp = metadata.remote_log_segment_id.topic_id_partition.topic_partition
            with ensure_deadline(self.default_deadline_s), \
                    self.tracer.span(
                        name, topic=tp.topic, partition=tp.partition
                    ) as span, \
                    self.flight_recorder.request(
                        name, trace_id=span.trace_id if span else None
                    ):
                check_deadline(name)
                return fn(self, metadata, *args, **kwargs)

        return wrapper

    return deco


class RemoteStorageManager:
    """Configure once, then copy/fetch/delete segments concurrently."""

    def __init__(self) -> None:
        self._config: Optional[RemoteStorageManagerConfig] = None
        self._storage: Optional[StorageBackend] = None
        self._transform_backend = None
        self._object_key_factory: Optional[ObjectKeyFactory] = None
        self._rsa: Optional[RsaEncryptionProvider] = None
        self._rate_bucket: Optional[TokenBucket] = None
        self._chunk_manager: Optional[ChunkManager] = None
        #: Device hot-window tier (`cache.device.bytes`): retained decrypt
        #: windows served without further GCM dispatches.
        self._device_hot = None
        #: Predictive readahead tier (`readahead.enabled`): sequential
        #: streams get future windows speculated as background-class work.
        self._readahead: Optional[ReadaheadManager] = None
        self._manifest_cache: Optional[MemorySegmentManifestCache] = None
        #: Keyed single-flight manifest prefetch over the manifest cache:
        #: segment-boundary crossings join an in-flight resolution instead
        #: of stalling on a cold fetch+parse.
        self._manifest_lookahead: Optional[ManifestLookahead] = None
        self._indexes_cache: Optional[MemorySegmentIndexesCache] = None
        self._metrics = None
        self._breaker: Optional[CircuitBreaker] = None
        self._retry_budget: Optional[RetryBudget] = None
        self._hedger: Optional[Hedger] = None
        self._fault_schedule = None
        self._scrubber = None
        self._scrub_scheduler = None
        #: Crash-consistent lifecycle plane (`lifecycle.*`, ISSUE 20):
        #: upload intent journal + convergent recovery sweeper.
        self._lifecycle_journal = None
        self._sweeper = None
        self._sweep_scheduler = None
        self._replicated: Optional[ReplicatedStorageBackend] = None
        self._antientropy = None
        self._antientropy_scheduler = None
        self.tracer = NOOP_TRACER
        #: Per-request flight recorder (`flight.enabled`); gateway + RSM
        #: entries open records, the fetch tiers enrich them.
        self.flight_recorder: FlightRecorder = NOOP_RECORDER
        #: Device-scheduler timeline ring (`timeline.enabled`): merged-launch
        #: attribution served on GET /debug/timeline (metrics/timeline.py).
        self.timeline: TimelineRecorder = NOOP_TIMELINE
        #: SLO engine (`slo.enabled`): burn rates + verdicts on GET /slo.
        self._slo = None
        #: Fleet-wide telemetry aggregator (fleet mode).
        self._fleet_telemetry = None
        #: Entry-gate admission controller (`admission.enabled`); the sidecar
        #: boundaries (HTTP gateway + gRPC server) shed through this.
        self.admission: Optional[AdmissionController] = None
        #: Fleet mode (`fleet.*`): consistent-hash router + peer cache tier.
        self.fleet_router: Optional[FleetRouter] = None
        self._peer_cache: Optional[PeerChunkCache] = None
        self._fleet_metrics: Optional[FleetMetrics] = None
        self._gossip: Optional[GossipAgent] = None

    # ------------------------------------------------------------------ setup
    def configure(self, configs: Mapping[str, object]) -> None:
        config = RemoteStorageManagerConfig(configs)
        self._config = config

        self._metrics = Metrics(MetricConfig(
            num_samples=config.metrics_num_samples,
            sample_window_ms=config.metrics_sample_window_ms,
            recording_level=config.metrics_recording_level,
        ))

        self.tracer = Tracer(
            enabled=config.tracing_enabled,
            use_jax_profiler=config.tracing_jax_profiler_enabled,
            max_spans=config.tracing_max_spans,
        )

        self.flight_recorder = FlightRecorder(
            enabled=config.flight_enabled,
            ring_size=config.flight_ring_size,
        )

        storage = config.storage_backend_class()
        storage.configure(config.storage_configs())
        storage = self._wrap_storage_resilience(config, storage)
        self._storage = storage

        backend = config.transform_backend_class()
        backend.configure(config.transform_configs())
        backend.tracer = self.tracer
        self._transform_backend = backend

        self.timeline = TimelineRecorder(
            enabled=config.timeline_enabled,
            ring_size=config.timeline_ring_size,
        )
        batcher = getattr(backend, "batcher", None)
        if batcher is not None:
            batcher.timeline = self.timeline
            batcher.set_launch_retry(
                config.retry_launch_attempts,
                config.retry_launch_backoff_ms / 1000.0,
            )

        self._object_key_factory = ObjectKeyFactory(config.key_prefix, config.key_prefix_mask)

        if config.encryption_enabled:
            self._rsa = RsaEncryptionProvider.from_pem_files(
                config.encryption_key_pair_id, config.encryption_key_pair_paths
            )

        if config.upload_rate_limit is not None:
            self._rate_bucket = TokenBucket(config.upload_rate_limit)

        self._wire_fleet_router(config)
        self._chunk_manager = self._build_chunk_manager(backend)
        self._wire_fetch_observability()
        self._wire_tail_tolerance(config)

        self._manifest_cache = MemorySegmentManifestCache()
        self._manifest_cache.configure(config.fetch_manifest_cache_configs())
        self._manifest_lookahead = ManifestLookahead(self._manifest_cache)
        self._indexes_cache = MemorySegmentIndexesCache()
        self._indexes_cache.configure(config.fetch_indexes_cache_configs())
        self._register_cache_metrics()
        self._register_resilience_metrics()
        register_tracer_metrics(self._metrics.registry, self.tracer)
        self._wire_replication(config)
        self._wire_scrubber(config)
        self._wire_lifecycle(config)
        self._wire_slo(config)
        self._wire_fleet_telemetry(config)

    def _wire_replication(self, config: RemoteStorageManagerConfig) -> None:
        """When the configured storage backend is (or wraps) a
        ReplicatedStorageBackend: hand it the tracer and the failover-time
        histogram hook, export replication-metrics gauges, and start the
        anti-entropy repair daemon (`replication.antientropy.*`)."""
        self._replicated = self._find_replicated(self._storage)
        if self._replicated is None:
            return
        from tieredstorage_tpu.metrics.rsm_metrics import register_replication_metrics

        self._replicated.tracer = self.tracer
        record_failover = self._metrics.record_replica_failover

        def on_failover(ms: float) -> None:
            # Histogram + the ambient flight record (one failover hop of
            # THIS request) — the recorder helper is a no-op without one.
            record_failover(ms)
            flight.note("replica.failover_hops")

        self._replicated.on_failover = on_failover
        if config.replication_antientropy_enabled:
            from tieredstorage_tpu.scrub.antientropy import (
                AntiEntropyRepairer,
                AntiEntropyScheduler,
            )

            bucket = (
                TokenBucket(config.replication_antientropy_rate_bytes)
                if config.replication_antientropy_rate_bytes is not None
                else None
            )
            self._antientropy = AntiEntropyRepairer(
                self._replicated,
                prefix=config.key_prefix,
                rate_bucket=bucket,
                tracer=self.tracer,
            )
            self._antientropy_scheduler = AntiEntropyScheduler(
                self._antientropy,
                interval_ms=config.replication_antientropy_interval_ms,
            ).start()
            log.info(
                "Anti-entropy repair enabled: interval=%dms rate=%s",
                config.replication_antientropy_interval_ms,
                config.replication_antientropy_rate_bytes,
            )
        register_replication_metrics(
            self._metrics.registry,
            replicated=self._replicated,
            antientropy=self._antientropy,
        )

    @staticmethod
    def _find_replicated(storage) -> Optional[ReplicatedStorageBackend]:
        """Unwrap the resilience/fault decorators (each exposes `delegate`)
        down to a ReplicatedStorageBackend, if one is in the stack."""
        seen = 0
        while storage is not None and seen < 8:
            if isinstance(storage, ReplicatedStorageBackend):
                return storage
            storage = getattr(storage, "delegate", None)
            seen += 1
        return None

    @property
    def replicated_storage(self) -> Optional[ReplicatedStorageBackend]:
        return self._replicated

    @property
    def antientropy(self):
        return self._antientropy

    @property
    def antientropy_scheduler(self):
        return self._antientropy_scheduler

    def _wire_fleet_router(self, config: RemoteStorageManagerConfig) -> None:
        """Fleet mode (`fleet.*`, ISSUE 6): build the consistent-hash router
        BEFORE the chunk manager — `_build_chunk_manager` inserts the
        PeerChunkCache tier (route → forward-to-owner → local single-flight
        backend fetch) between the local chunk cache and the default
        manager. Static membership comes from `fleet.instances`; dynamic
        deployments call `set_fleet_peers` once gateway ports are known."""
        if not config.fleet_enabled:
            return
        self.fleet_router = FleetRouter(
            config.fleet_instance_id,
            vnodes=config.fleet_vnodes,
            tracer=self.tracer,
        )
        static = parse_instances(config.fleet_instances)
        if static:
            self.fleet_router.set_membership(static)
        if config.fleet_gossip_enabled:
            # Seeded from the static list (which becomes the SEED set only:
            # gossip owns membership from here). Started explicitly via
            # start_fleet_gossip once the gateway is up — probing peers
            # before this instance can answer them would just spread
            # suspicion of ourselves.
            self._gossip = GossipAgent(
                self.fleet_router,
                interval_s=config.fleet_gossip_interval_ms / 1000.0,
                probe_timeout_s=config.fleet_gossip_probe_timeout_ms / 1000.0,
                suspect_periods=config.fleet_gossip_suspect_periods,
                dead_periods=config.fleet_gossip_dead_periods,
                probe_retries=config.retry_gossip_probe_attempts - 1,
                breaker_threshold=config.breaker_gossip_failure_threshold,
                tracer=self.tracer,
            )
        self._fleet_metrics = FleetMetrics(self._metrics.registry)
        log.info(
            "Fleet mode enabled: instance=%s vnodes=%d replication=%d "
            "gossip=%s members=%s",
            config.fleet_instance_id, config.fleet_vnodes,
            config.fleet_replication_factor, config.fleet_gossip_enabled,
            sorted(self.fleet_router.peers) or [config.fleet_instance_id],
        )

    @property
    def peer_chunk_cache(self) -> Optional[PeerChunkCache]:
        return self._peer_cache

    @property
    def device_hot_cache(self):
        """The device hot-window tier, or None when `cache.device.bytes`
        is 0 (fetch/cache/device_hot.py)."""
        return self._device_hot

    @property
    def gossip_agent(self) -> Optional[GossipAgent]:
        return self._gossip

    def set_fleet_peers(self, peers: Mapping[str, Optional[str]]) -> None:
        """Replace fleet membership with {name: base_url|None} — the
        bootstrap hook for deployments whose gateway ports are only known
        after bind (tools/fleet_demo.py), and the demotion hook when a
        member is declared dead (bounded key movement: only the arcs of the
        changed instances move). Under gossip this reseeds the agent: the
        entries join the probe set, and membership is gossip's from there."""
        if self.fleet_router is None:
            raise RemoteStorageException("fleet mode is not enabled")
        self.fleet_router.set_membership(peers)
        if self._gossip is not None:
            self._gossip.seed(peers)

    def start_fleet_gossip(self) -> Optional[GossipAgent]:
        """Start the gossip membership daemon (`fleet.gossip.enabled`).
        Called once the HTTP gateway is bound — the sidecar CLI does this
        after SIDECAR_READY so inbound /fleet/gossip probes can be
        answered from the first period."""
        if self._gossip is not None:
            self._gossip.start()
        return self._gossip

    def fleet_gossip(self, payload: Mapping) -> dict:
        """Serve one inbound gossip exchange (the gateway's POST
        /fleet/gossip): merge the sender's view, answer with ours."""
        if self._gossip is None:
            raise RemoteStorageException("fleet gossip is not enabled")
        return self._gossip.on_gossip(payload)

    def fleet_ping(self, *, include_witness: bool = False) -> dict:
        """Liveness/status body for the gateway's GET /fleet/ping: ring
        state, the gossip view, peer-tier counters, and (on request) the
        runtime lock/race witness verdicts — the observability surface the
        multi-process soak (tools/fleet_soak.py) drives its convergence
        and zero-violation gates through."""
        if self.fleet_router is None:
            raise RemoteStorageException("fleet mode is not enabled")
        router = self.fleet_router
        status: dict = {
            "instance": router.instance_id,
            "generation": router.generation,
            "view_epoch": router.view_epoch,
            "ring_instances": sorted(router.instances),
        }
        if self._gossip is not None:
            status["gossip"] = {
                "epoch": self._gossip.epoch,
                "periods": self._gossip.periods,
                "members": {
                    name: {"status": m.status, "incarnation": m.incarnation}
                    for name, m in self._gossip.members().items()
                },
            }
        if self._peer_cache is not None:
            cache = self._peer_cache
            status["peer_cache"] = {
                "replication": cache.replication,
                "forwards": cache.forwards,
                "peer_hits": cache.peer_hits,
                "peer_misses": cache.peer_misses,
                "forward_failures": cache.forward_failures,
                "failover_hits": cache.failover_hits,
            }
        if self._fault_schedule is not None:
            status["storage_fetch_calls"] = self._fault_schedule.calls("fetch")
        if include_witness:
            from tieredstorage_tpu.analysis import races
            from tieredstorage_tpu.utils.locks import witness, witness_enabled

            crosscheck = races.runtime_crosscheck()
            status["witness"] = {
                "enabled": witness_enabled(),
                "lock_violations": list(witness().violations),
                "race_violations": crosscheck["violations"],
                "race_sites_observed": crosscheck["validated"],
            }
        return status

    def fleet_fetch_chunks(
        self, object_key_value: str, first: int, last: int
    ) -> list[bytes]:
        """Serve a window of plaintext chunks of a locally-owned segment to
        a fleet sibling (the gateway's GET /chunk route). Runs through this
        instance's FULL chunk path — local cache hit, else single-flight
        backend fetch — with the key pinned local so a forwarded request is
        never re-forwarded, even under transient ring disagreement."""
        if self.fleet_router is None:
            raise RemoteStorageException("fleet mode is not enabled")
        base, _, suffix = object_key_value.rpartition(".")
        if not base or suffix != Suffix.LOG.value:
            raise ValueError(
                f"peer chunk reads serve .log objects only, got {object_key_value!r}"
            )
        if first < 0 or last < first:
            raise ValueError(f"invalid chunk window {first}-{last}")
        manifest_key = ObjectKey(f"{base}.{Suffix.MANIFEST.value}")
        with ensure_deadline(self.default_deadline_s):
            check_deadline("fleet chunk serve")
            self._check_not_quarantined(manifest_key)
            manifest = self._manifest_lookahead.get(
                manifest_key, lambda: self._fetch_manifest_by_key(manifest_key)
            )
            if last >= manifest.chunk_index.chunk_count:
                raise ValueError(
                    f"chunk window {first}-{last} beyond "
                    f"{manifest.chunk_index.chunk_count} chunks"
                )
            pin = (
                self._peer_cache.serving_locally(object_key_value)
                if self._peer_cache is not None
                else contextlib.nullcontext()
            )
            with pin:
                return self._chunk_manager.get_chunks(
                    ObjectKey(object_key_value), manifest,
                    list(range(first, last + 1)),
                )

    def _wire_scrubber(self, config: RemoteStorageManagerConfig) -> None:
        """Background integrity scrubbing (scrub/): enumerate + verify +
        quarantine/repair on a jittered period, throttled so it never
        starves foreground fetches. `scrub.rate.bytes` paces BOTH halves
        of a pass: the host TokenBucket throttles its storage-IO walks,
        and — when the transform backend runs the cross-request window
        batcher — the same rate becomes the device scheduler's background
        admission class, replacing any host-side throttle on device GCM
        work (the scrubber's verification decrypts submit under
        `work_class_scope(BACKGROUND)`)."""
        if not config.scrub_enabled:
            return
        from tieredstorage_tpu.scrub import ScrubMetrics, ScrubScheduler, Scrubber
        from tieredstorage_tpu.scrub.metrics import register_scrub_metrics

        bucket = (
            TokenBucket(config.scrub_rate_bytes)
            if config.scrub_rate_bytes is not None
            else None
        )
        if config.scrub_rate_bytes is not None:
            batcher = getattr(self._transform_backend, "batcher", None)
            if batcher is not None:
                from tieredstorage_tpu.transform.scheduler import BACKGROUND

                batcher.set_class_rate(BACKGROUND, config.scrub_rate_bytes)
        inner = self._innermost_chunk_manager(self._chunk_manager)
        quarantine = inner.quarantine if inner is not None else None
        self._scrubber = Scrubber(
            self._storage,
            prefix=config.key_prefix,
            transform_backend=self._transform_backend,
            data_key_decoder=self._rsa.data_key_decoder if self._rsa else None,
            rate_bucket=bucket,
            repair_enabled=config.scrub_repair_enabled,
            quarantine=quarantine,
            tracer=self.tracer,
            metrics=ScrubMetrics(self._metrics.registry),
        )
        self._scrub_scheduler = ScrubScheduler(
            self._scrubber, interval_ms=config.scrub_interval_ms
        )
        register_scrub_metrics(
            self._metrics.registry, self._scrubber, self._scrub_scheduler
        )
        self._scrub_scheduler.start()
        log.info(
            "Integrity scrubber enabled: interval=%dms rate=%s repair=%s",
            config.scrub_interval_ms, config.scrub_rate_bytes,
            config.scrub_repair_enabled,
        )

    def _wire_lifecycle(self, config: RemoteStorageManagerConfig) -> None:
        """Crash-consistent lifecycle plane (`lifecycle.*`, ISSUE 20): the
        upload intent journal names what a crash may strand BEFORE the
        first uploaded byte; the recovery sweeper reconciles journal +
        store listing against manifest reachability — synchronously once
        at startup (the crash-recovery path), then on a paced period.
        Manifest-last upload stays the sole commit point; the sweeper may
        only ever delete manifest-UNreachable objects."""
        if not config.lifecycle_enabled:
            return
        from tieredstorage_tpu.config.configdef import ConfigException
        from tieredstorage_tpu.metrics.lifecycle_metrics import (
            register_lifecycle_metrics,
        )
        from tieredstorage_tpu.scrub.sweeper import RecoverySweeper, SweepScheduler
        from tieredstorage_tpu.storage.lifecycle import UploadIntentJournal

        if not config.lifecycle_journal_path:
            raise ConfigException(
                "lifecycle.enabled requires lifecycle.journal.path"
            )
        self._lifecycle_journal = UploadIntentJournal(
            Path(config.lifecycle_journal_path)
        )
        if config.lifecycle_grace_ms < 600_000:
            # The grace window is the ONLY protection for a fleet peer's
            # in-progress upload on the shared prefix (this process's own
            # are exempt via in-flight tracking); below the slowest
            # end-to-end segment upload it becomes cross-process data loss.
            log.warning(
                "lifecycle.grace.ms=%d is under 10 minutes: any fleet "
                "peer's segment upload outlasting it can have its "
                "uncommitted objects swept mid-upload. Size it above the "
                "slowest end-to-end upload (default 4h).",
                config.lifecycle_grace_ms,
            )

        def load_manifest(manifest_key: str) -> SegmentManifestV1:
            return self._fetch_manifest_raw(ObjectKey(manifest_key))

        self._sweeper = RecoverySweeper(
            self._storage,
            self._lifecycle_journal,
            prefix=config.key_prefix,
            grace_s=config.lifecycle_grace_ms / 1000.0,
            manifest_loader=load_manifest,
            tracer=self.tracer,
        )
        if config.lifecycle_sweep_on_start:
            try:
                report = self._sweeper.sweep_once()
                if report.orphans_deleted or report.quarantined:
                    log.info(
                        "Startup recovery sweep: %d orphan(s) deleted, "
                        "%d manifest(s) quarantined",
                        len(report.orphans_deleted), len(report.quarantined),
                    )
            except Exception:  # noqa: BLE001 — recovery must not block startup
                log.warning("Startup recovery sweep failed; the paced "
                            "scheduler will retry", exc_info=True)
        self._sweep_scheduler = SweepScheduler(
            self._sweeper, interval_ms=config.lifecycle_sweep_interval_ms
        ).start()
        register_lifecycle_metrics(
            self._metrics.registry, self._lifecycle_journal, self._sweeper,
            self._sweep_scheduler,
        )
        log.info(
            "Lifecycle plane enabled: journal=%s sweep_interval=%dms "
            "grace=%dms",
            config.lifecycle_journal_path, config.lifecycle_sweep_interval_ms,
            config.lifecycle_grace_ms,
        )

    @property
    def lifecycle_journal(self):
        return self._lifecycle_journal

    @property
    def recovery_sweeper(self):
        return self._sweeper

    @property
    def sweep_scheduler(self):
        return self._sweep_scheduler

    def lifecycle_status(self) -> dict:
        """JSON-shaped lifecycle plane status (journal + sweeper)."""
        if self._lifecycle_journal is None:
            raise RemoteStorageException("lifecycle plane is not enabled")
        out = {"journal": self._lifecycle_journal.status()}
        if self._sweep_scheduler is not None:
            out["sweeper"] = self._sweep_scheduler.status()
        return out

    def _wire_slo(self, config: RemoteStorageManagerConfig) -> None:
        """SLO engine (`slo.*`, ISSUE 14): declarative objectives over the
        histograms and counters the earlier wiring just built — fetch
        latency vs the deadline budget, request-visible error rate, the
        admission shed rate, and (opt-in) a chunk-cache hit floor. Gauges
        land in the slo-metrics group; GET /slo serves the verdicts."""
        if not config.slo_enabled:
            return
        from tieredstorage_tpu.metrics.slo import (
            HistogramLatencySource,
            RatioSource,
            SloEngine,
            SloSpec,
        )

        metrics = self._metrics
        specs: list = []
        threshold_ms = config.slo_fetch_latency_threshold_ms
        if threshold_ms is None:
            threshold_ms = config.deadline_default_ms
        if threshold_ms is not None:
            objective = config.slo_fetch_latency_objective_percent / 100.0
            specs.append(SloSpec(
                name="fetch-latency",
                description=(
                    f"p{config.slo_fetch_latency_objective_percent} chunk "
                    f"fetch within {threshold_ms} ms (the deadline budget)"
                ),
                objective=objective,
                source=HistogramLatencySource(
                    metrics, "chunk-fetch-time", float(threshold_ms)
                ),
            ))
        inner = self._innermost_chunk_manager(self._chunk_manager)

        def fetch_errors() -> float:
            bad = float(deadline_util.exceeded_total())
            if inner is not None:
                bad += float(inner.corruptions)
            return bad

        def fetch_events() -> float:
            return float(
                metrics.histogram_count("chunk-fetch-time")
            ) + fetch_errors()

        specs.append(SloSpec(
            name="fetch-errors",
            description=(
                "chunk fetches without a request-visible failure "
                "(detransform corruption, deadline expiry)"
            ),
            objective=config.slo_error_rate_objective_percent / 100.0,
            source=RatioSource(
                good=lambda: fetch_events() - fetch_errors(),
                total=fetch_events,
            ),
        ))
        if self.admission is not None:
            admission = self.admission
            specs.append(SloSpec(
                name="shed-rate",
                description=(
                    f"requests admitted past the entry gate (sheds bounded "
                    f"at {config.slo_shed_rate_max_percent}%)"
                ),
                objective=1.0 - config.slo_shed_rate_max_percent / 100.0,
                source=RatioSource(
                    good=lambda: float(admission.admitted_total),
                    total=lambda: float(
                        admission.admitted_total + admission.shed_total
                    ),
                ),
            ))
        floor = config.slo_cache_hit_floor_percent
        chunk_cache = self._chunk_cache_tier(self._chunk_manager)
        if floor > 0 and chunk_cache is not None:
            stats = chunk_cache.stats
            specs.append(SloSpec(
                name="cache-hit",
                description=f"chunk-cache hit rate floor ({floor}%)",
                objective=floor / 100.0,
                source=RatioSource(
                    good=lambda: float(stats.hits),
                    total=lambda: float(stats.hits + stats.misses),
                ),
            ))
        if self._readahead is not None:
            readahead = self._readahead
            bound = readahead.misprediction_max_ratio
            specs.append(SloSpec(
                name="readahead-misprediction",
                description=(
                    "speculated decrypt bytes later consumed by the stream "
                    f"(wasted bytes bounded at {bound:.0%} — "
                    "readahead.misprediction.max.ratio)"
                ),
                objective=1.0 - bound,
                source=RatioSource(
                    good=lambda: float(
                        readahead.bytes_speculated - readahead.wasted_bytes
                    ),
                    total=lambda: float(readahead.bytes_speculated),
                ),
            ))
        self._slo = SloEngine(
            specs,
            short_window_s=config.slo_window_short_ms / 1000.0,
            long_window_s=config.slo_window_long_ms / 1000.0,
        )
        self._slo.register_gauges(self._metrics.registry)
        log.info(
            "SLO engine enabled: specs=%s windows=%d/%dms",
            [s.name for s in specs], config.slo_window_short_ms,
            config.slo_window_long_ms,
        )

    @property
    def slo_engine(self):
        return self._slo

    def slo_status(self) -> dict:
        """Verdict payload for the gateway's GET /slo (evaluates: every
        read is also a burn-rate window tick, the Prometheus model)."""
        if self._slo is None:
            raise RemoteStorageException("SLO engine is not enabled")
        return {"enabled": True, **self._slo.evaluate()}

    def flight_status(
        self,
        *,
        limit: Optional[int] = None,
        trace: Optional[str] = None,
        slowest: Optional[int] = None,
    ) -> dict:
        """Payload for the gateway's GET /debug/requests: slowest-first
        retained flight records plus the failure ring. ``trace`` filters to
        one trace id's records and raises not-found (the gateway's 404)
        when nothing retained carries it; ``slowest`` returns just the N
        slowest completed records."""
        if not self.flight_recorder.enabled:
            raise RemoteStorageException("flight recorder is not enabled")
        if trace is not None and not self.flight_recorder.find_all(trace):
            raise RemoteResourceNotFoundException(
                f"no retained flight record for trace {trace!r}"
            )
        return self.flight_recorder.dump(
            limit=limit, trace=trace, slowest=slowest
        )

    def timeline_status(self) -> dict:
        """Payload for the gateway's GET /debug/timeline: the scheduler
        ring's counters, epoch pin, and retained events."""
        if not self.timeline.enabled:
            raise RemoteStorageException("timeline recorder is not enabled")
        return self.timeline.status()

    def _wire_fleet_telemetry(self, config: RemoteStorageManagerConfig) -> None:
        """Fleet-wide telemetry (fleet/telemetry.py): this member serves
        its metric samples on GET /fleet/telemetry and can aggregate the
        whole membership view into one scrape (?aggregate=1)."""
        if self.fleet_router is None:
            return
        from tieredstorage_tpu.fleet.telemetry import FleetTelemetry

        self._fleet_telemetry = FleetTelemetry(
            [self._metrics.registry],
            instance_id=config.fleet_instance_id,
            router=self.fleet_router,
            ping=self.fleet_ping,
            timeout_s=config.fleet_forward_timeout_ms / 1000.0,
            flight_recorder=self.flight_recorder,
            timeline=self.timeline,
        )

    @property
    def fleet_telemetry(self):
        return self._fleet_telemetry

    def fleet_telemetry_payload(self, *, aggregate: bool = False) -> dict:
        """The gateway's GET /fleet/telemetry body: this member's samples,
        or the merged fleet-wide scrape when ``aggregate`` is set."""
        if self._fleet_telemetry is None:
            raise RemoteStorageException("fleet mode is not enabled")
        if aggregate:
            return self._fleet_telemetry.scrape()
        return self._fleet_telemetry.local_payload()

    @property
    def scrubber(self):
        return self._scrubber

    @property
    def scrub_scheduler(self):
        return self._scrub_scheduler

    def scrub_status(self) -> dict:
        """Status payload for the sidecar gateway's GET /scrub."""
        if self._scrub_scheduler is None:
            return {"enabled": False}
        return {"enabled": True, **self._scrub_scheduler.status()}

    def _wire_tail_tolerance(self, config: RemoteStorageManagerConfig) -> None:
        """Hedged chunk fetches (`hedge.*`) and entry admission control
        (`admission.*`) — the tail-at-scale pair: hedge the stragglers,
        shed the overload (Dean & Barroso 2013; DAGOR, SOSP 2018)."""
        if config.hedge_enabled:
            static_s = config.hedge_delay_ms / 1000.0
            min_samples = config.hedge_delay_min_samples
            metrics = self._metrics

            def hedge_delay_s() -> float:
                # Observed p95 of the chunk-fetch histogram (PR 2) once it
                # holds enough samples; the static config value until then.
                if metrics.histogram_count("chunk-fetch-time") >= min_samples:
                    p95_ms = metrics.latency_quantile("chunk-fetch-time", 0.95)
                    if p95_ms is not None:
                        return p95_ms / 1000.0
                return static_s

            self._hedger = Hedger(
                hedge_delay_s,
                HedgeBudget(config.hedge_budget_percent),
                tracer=self.tracer,
                on_win=self._metrics.record_hedge_win,
            )
            inner = self._innermost_chunk_manager(self._chunk_manager)
            if inner is not None:
                inner.hedger = self._hedger
        if config.admission_enabled:
            self.admission = AdmissionController(
                config.admission_max_concurrent,
                config.admission_max_queue,
                queue_timeout_s=config.admission_queue_timeout_ms / 1000.0,
                retry_after_s=config.admission_retry_after_ms / 1000.0,
                on_wait=self._metrics.record_admission_wait,
            )

    @property
    def default_deadline_s(self) -> Optional[float]:
        """`deadline.default.ms` in seconds; the sidecar boundaries and the
        _traced entry points install this when the caller sent no deadline."""
        if self._config is None or self._config.deadline_default_ms is None:
            return None
        return self._config.deadline_default_ms / 1000.0

    @property
    def sidecar_grpc_max_workers(self) -> int:
        """`sidecar.grpc.max.workers` (SidecarServer reads this when no
        explicit max_workers is passed)."""
        return (
            self._config.sidecar_grpc_max_workers if self._config is not None else 8
        )

    @property
    def sidecar_http_max_workers(self) -> int:
        """`sidecar.http.max.workers` (SidecarHttpGateway reads this when no
        explicit max_workers is passed)."""
        return (
            self._config.sidecar_http_max_workers if self._config is not None else 32
        )

    @property
    def hedger(self) -> Optional[Hedger]:
        return self._hedger

    @property
    def retry_budget(self) -> Optional[RetryBudget]:
        return self._retry_budget

    def _wire_fetch_observability(self) -> None:
        """Hand the configured tracer + latency hooks to the fetch tier so
        chunk-fetch/detransform/cache-get land in traces and histograms."""
        cm = self._chunk_manager
        inner = self._innermost_chunk_manager(cm)
        if inner is not None:
            inner.tracer = self.tracer
            inner.on_fetch = self._metrics.record_chunk_fetch
        cache = self._chunk_cache_tier(cm)
        if cache is not None:
            cache.tracer = self.tracer
            cache.on_get = self._metrics.record_cache_get
            # Pool-side prefetch loads open synthetic flight records
            # (attributable background flows on /debug/timeline).
            cache.flight_recorder = self.flight_recorder
        if self._device_hot is not None:
            self._device_hot.tracer = self.tracer
        if self._readahead is not None:
            self._readahead.tracer = self.tracer
            self._readahead.flight_recorder = self.flight_recorder

    def _wrap_storage_resilience(
        self, config: RemoteStorageManagerConfig, storage: StorageBackend
    ) -> StorageBackend:
        """Layering (innermost first): backend → fault injection (soak runs
        only) → circuit breaker + retry budget, so injected faults exercise
        the breaker and the budgeted retries the same way real outages do."""
        if config.fault_injection_enabled:
            from tieredstorage_tpu.faults import FaultInjectingBackend, FaultSchedule

            self._fault_schedule = FaultSchedule.parse(
                config.fault_schedule, seed=config.fault_seed
            )
            storage = FaultInjectingBackend(storage, self._fault_schedule)
            log.warning(
                "Fault injection ENABLED with %d rule(s); storage calls will "
                "be deliberately failed/corrupted/slowed", len(self._fault_schedule),
            )
        if config.faults_spec:
            # The process-wide fault plane (utils/faults.py): named injection
            # points across EVERY I/O seam — storage read/write, peer
            # forwards, gossip probes, device launches — not just the
            # storage-backend decorator above. Same arming as TSTPU_FAULTS.
            plane = faults.FaultPlane.parse(
                config.faults_spec, seed=config.faults_seed
            )
            faults.install(plane)
            log.warning(
                "Fault plane ENABLED with %d rule(s) across the I/O seams; "
                "calls will be deliberately failed/torn/slowed",
                len(plane.rules),
            )
        if config.breaker_enabled:
            self._breaker = CircuitBreaker(
                failure_threshold=config.breaker_failure_threshold,
                cooldown_s=config.breaker_cooldown_ms / 1000.0,
                on_transition=lambda old, new: self.tracer.event(
                    "storage.breaker.transition", from_state=old.name, to_state=new.name
                ),
            )
        if config.retry_budget_enabled:
            self._retry_budget = RetryBudget(
                config.retry_budget_percent,
                capacity=float(config.retry_budget_capacity),
            )
        if self._breaker is not None or self._retry_budget is not None:
            storage = ResilientStorageBackend(
                storage,
                self._breaker,
                retry_budget=self._retry_budget,
                max_attempts=config.retry_budget_max_attempts,
                backoff_s=config.retry_budget_backoff_ms / 1000.0,
                tracer=self.tracer,
            )
        return storage

    def _register_resilience_metrics(self) -> None:
        chunk_cache = self._chunk_cache_tier(self._chunk_manager)
        register_resilience_metrics(
            self._metrics.registry,
            breaker=self._breaker,
            fault_schedule=self._fault_schedule,
            chunk_cache=chunk_cache,
            chunk_manager=self._innermost_chunk_manager(self._chunk_manager),
            hedger=self._hedger,
            retry_budget=self._retry_budget,
            admission=self.admission,
            deadline_exceeded_supplier=deadline_util.exceeded_total,
        )
        if self.fleet_router is not None:
            register_fleet_metrics(
                self._metrics.registry,
                router=self.fleet_router,
                peer_cache=self._peer_cache,
                gossip=self._gossip,
            )
        from tieredstorage_tpu.metrics.retry_metrics import register_retry_metrics

        boards = {}
        if self._peer_cache is not None:
            boards["peer"] = self._peer_cache.breakers
        if self._gossip is not None:
            boards["gossip"] = self._gossip.breakers
        register_retry_metrics(
            self._metrics.registry,
            breakers={"storage": self._breaker} if self._breaker is not None else None,
            boards=boards,
        )

    def _register_cache_metrics(self) -> None:
        registry = self._metrics.registry
        register_cache_metrics(
            registry, "segment-manifest-cache", self._manifest_cache.stats,
            size_supplier=lambda: self._manifest_cache.size,
        )
        register_cache_metrics(
            registry, "segment-indexes-cache", self._indexes_cache.stats,
            size_supplier=lambda: self._indexes_cache.size,
            weight_supplier=lambda: self._indexes_cache.total_weight,
        )
        chunk_cache = self._chunk_cache_tier(self._chunk_manager)
        if chunk_cache is not None and hasattr(chunk_cache, "stats"):
            register_cache_metrics(
                registry, "chunk-cache", chunk_cache.stats,
                size_supplier=lambda: chunk_cache.size,
                weight_supplier=lambda: chunk_cache.total_weight,
            )
            register_thread_pool_metrics(
                registry, "chunk-cache-pool", chunk_cache.executor
            )
            from tieredstorage_tpu.fetch.cache.disk import DiskChunkCache

            if isinstance(chunk_cache, DiskChunkCache):
                chunk_cache.set_metrics_recorder(DiskCacheMetrics(registry))
        if self._device_hot is not None:
            from tieredstorage_tpu.metrics.cache_metrics import (
                register_hot_cache_metrics,
            )

            register_hot_cache_metrics(registry, self._device_hot)
        if self._readahead is not None:
            from tieredstorage_tpu.metrics.cache_metrics import (
                register_readahead_metrics,
            )

            register_readahead_metrics(registry, self._readahead)
        if self._manifest_lookahead is not None:
            from tieredstorage_tpu.metrics.cache_metrics import (
                register_manifest_lookahead_metrics,
            )

            register_manifest_lookahead_metrics(
                registry, self._manifest_lookahead
            )
        batcher = getattr(self._transform_backend, "batcher", None)
        if batcher is not None:
            from tieredstorage_tpu.metrics.batch_metrics import (
                register_batch_metrics,
            )

            register_batch_metrics(registry, batcher)
        from tieredstorage_tpu.metrics.timeline import (
            register_timeline_metrics,
        )

        register_timeline_metrics(registry, self.timeline)

    def _build_chunk_manager(self, backend) -> ChunkManager:
        factory = ChunkManagerFactory()
        factory.configure(self._config.raw_props())
        wrapper = None
        if self.fleet_router is not None:
            config = self._config

            def wrapper(default):
                self._peer_cache = PeerChunkCache(
                    default,
                    self.fleet_router,
                    replication=config.fleet_replication_factor,
                    forward_timeout_s=config.fleet_forward_timeout_ms / 1000.0,
                    down_cooldown_s=config.fleet_peer_down_cooldown_ms / 1000.0,
                    breaker_threshold=config.breaker_peer_failure_threshold,
                    tracer=self.tracer,
                    on_forward=self._fleet_metrics.record_forward,
                )
                return self._peer_cache

        manager = factory.init_chunk_manager(self._storage, backend, wrapper)
        self._device_hot = factory.device_hot_cache
        self._readahead = factory.readahead_manager
        return manager

    @staticmethod
    def _chunk_cache_tier(cm) -> Optional[ChunkCache]:
        """The ChunkCache tier of the fetch chain, seen through the optional
        readahead wrapper (which sits OUTERMOST so its detector observes
        cache hits too)."""
        if isinstance(cm, ReadaheadManager):
            cm = cm._delegate
        return cm if isinstance(cm, ChunkCache) else None

    @property
    def readahead_manager(self) -> Optional[ReadaheadManager]:
        """The readahead tier (None unless ``readahead.enabled``)."""
        return self._readahead

    @property
    def manifest_lookahead(self) -> Optional[ManifestLookahead]:
        return self._manifest_lookahead

    def set_segment_successor(self, successor) -> None:
        """Teach the readahead tier segment replay order: ``successor`` maps
        a segment's ``ObjectKey`` to the NEXT segment's key (or None at the
        log head). Segment ordering is broker-side knowledge (base offsets),
        so the embedding harness/broker wires it; the resolved manifest
        loads ride the keyed single-flight manifest lookahead, so N streams
        crossing one boundary resolve the next manifest once."""
        if self._readahead is None:
            raise RemoteStorageException("readahead is not enabled")
        lookahead = self._manifest_lookahead

        def resolver(key: ObjectKey):
            next_key = successor(key)
            if next_key is None:
                return None
            manifest_key = ObjectKey(
                f"{next_key.value.rsplit('.', 1)[0]}.{Suffix.MANIFEST.value}"
            )
            loader = lambda: self._fetch_manifest_by_key(manifest_key)
            # Start resolving immediately; the returned thunk joins it.
            lookahead.prefetch(manifest_key, loader)
            return next_key, lambda: lookahead.get(manifest_key, loader)

        self._readahead.next_segment_resolver = resolver

    @staticmethod
    def _innermost_chunk_manager(cm) -> Optional[DefaultChunkManager]:
        """Unwrap the chunk-manager decorators (ChunkCache → PeerChunkCache
        → DefaultChunkManager; each exposes `_delegate`) down to the
        backend-fetching manager the hedger/tracer/quarantine hooks live on."""
        seen = 0
        while cm is not None and not isinstance(cm, DefaultChunkManager) and seen < 8:
            cm = getattr(cm, "_delegate", None)
            seen += 1
        return cm if isinstance(cm, DefaultChunkManager) else None

    @property
    def metrics(self) -> Metrics:
        return self._metrics

    def _require_configured(self) -> RemoteStorageManagerConfig:
        if self._config is None:
            raise RemoteStorageException("RemoteStorageManager is not configured")
        return self._config

    # ----------------------------------------------------------------- upload
    @_traced("rsm.copy_log_segment_data")
    def copy_log_segment_data(
        self, metadata: RemoteLogSegmentMetadata, segment_data: LogSegmentData
    ) -> Optional[bytes]:
        """Uploads `.log`, `.indexes`, `.rsm-manifest`; returns custom metadata
        bytes (or None if no fields configured)."""
        config = self._require_configured()
        start = time.monotonic()
        log.debug("Copying log segment data: %s", metadata)

        requires_compression = self._requires_compression(segment_data)
        data_key: Optional[DataKeyAndAAD] = None
        if config.encryption_enabled:
            data_key = AesEncryptionProvider.create_data_key_and_aad()

        include = [
            SegmentCustomMetadataField[name]
            for name in config.custom_metadata_fields_include
        ]
        custom_builder = SegmentCustomMetadataBuilder(
            include, self._object_key_factory.prefix, metadata
        )

        uploaded_keys: list[ObjectKey] = []
        # Intent BEFORE the first uploaded byte: a kill -9 anywhere past
        # this line leaves a journal entry naming exactly the keys the
        # recovery sweeper may find stranded.  Manifest-last stays the sole
        # commit point — the journal only names, it never commits.
        txn = self._journal_begin_upload(metadata)
        try:
            chunk_index, chunk_checksums = self._upload_segment_log(
                metadata, segment_data, requires_compression, data_key,
                custom_builder, uploaded_keys,
            )
            self._journal_stage(txn, "log-uploaded")
            segment_indexes = self._upload_indexes(
                metadata, segment_data, data_key, custom_builder, uploaded_keys
            )
            self._journal_stage(txn, "indexes-uploaded")
            self._upload_manifest(
                metadata, chunk_index, segment_indexes, requires_compression,
                data_key, custom_builder, uploaded_keys,
                chunk_checksums=chunk_checksums,
            )
            self._journal_commit(txn)
        except Exception as e:
            # Orphan cleanup: a failed copy must not leave partial objects
            # (reference :258-267); the broker will retry the whole copy.
            if uploaded_keys:
                topic, partition = self._topic_partition(metadata)
                self._metrics.record_upload_rollback(topic, partition)
                self.tracer.event(
                    "rsm.upload_rollback", topic=topic, partition=partition,
                    keys=len(uploaded_keys),
                )
                try:
                    self._delete_keys(uploaded_keys)
                    self._journal_rollback(txn)
                except Exception:
                    # Cleanup failure is visible, not just logged (the PR 14
                    # "no invisible swallows" rule): counted per scope,
                    # noted on the ambient flight record, and the journal
                    # entry stays PENDING so the recovery sweeper converges
                    # the stranded objects on its next pass.
                    self._metrics.record_upload_rollback_cleanup_failure(
                        topic, partition
                    )
                    flight.note("upload.rollback_cleanup_failures")
                    log.warning(
                        "Failed to clean up partial upload for %s", metadata, exc_info=True
                    )
            else:
                self._journal_rollback(txn)
            if isinstance(e, (RemoteStorageException, DeadlineExceededException)):
                # DeadlineExceededException stays distinct end to end so the
                # boundaries map it to 504 / DEADLINE_EXCEEDED.
                raise
            raise RemoteStorageException(f"Failed to copy segment {metadata}") from e
        finally:
            # This copy is no longer in flight (committed, rolled back, or
            # left pending by a failed cleanup): release the txn so the
            # recovery sweeper may converge whatever it left behind.  While
            # in flight the sweeper must not touch the txn's keys — a paced
            # sweep racing this upload would otherwise delete objects whose
            # manifest is about to land.
            self._journal_release(txn)

        elapsed = time.monotonic() - start
        topic, partition = self._topic_partition(metadata)
        self._metrics.record_segment_copy_time(topic, partition, elapsed * 1000.0)
        log.debug("Copied %s in %.3fs", metadata, elapsed)
        if not include:
            return None
        return serialize_custom_metadata(custom_builder.build())

    @staticmethod
    def _topic_partition(metadata: RemoteLogSegmentMetadata) -> tuple[str, int]:
        tp = metadata.remote_log_segment_id.topic_id_partition.topic_partition
        return tp.topic, tp.partition

    def _record_upload(self, metadata, suffix: Suffix, n_bytes: int) -> None:
        topic, partition = self._topic_partition(metadata)
        self._metrics.record_object_upload(topic, partition, suffix.value, n_bytes)

    def _requires_compression(self, segment_data: LogSegmentData) -> bool:
        config = self._require_configured()
        if not config.compression_enabled:
            return False
        if not config.compression_heuristic_enabled:
            return True
        try:
            return not segment_looks_compressed(segment_data.log_segment)
        except InvalidRecordBatchException:
            log.warning(
                "Failed to check compression on log segment: %s", segment_data.log_segment,
                exc_info=True,
            )
            return False

    def _transform_opts(
        self, requires_compression: bool, data_key: Optional[DataKeyAndAAD]
    ) -> TransformOptions:
        config = self._require_configured()
        return TransformOptions(
            compression=requires_compression,
            compression_codec=config.compression_codec,
            encryption=data_key,
        )

    # ------------------------------------------------- lifecycle journal hooks
    def _journal_begin_upload(self, metadata) -> Optional[int]:
        """Record upload intent (`lifecycle.enabled`); None when disabled.
        A failed intent append fails the copy while the store is still
        clean — the store must never hold state the journal cannot name."""
        if self._lifecycle_journal is None:
            return None
        from tieredstorage_tpu.storage.lifecycle import JournalAppendError

        keys = [
            self._object_key_factory.key(metadata, suffix).value
            for suffix in Suffix
        ]
        segment = str(metadata.remote_log_segment_id.id)
        try:
            return self._lifecycle_journal.begin_upload(segment, keys)
        except JournalAppendError as e:
            raise RemoteStorageException(
                f"Upload intent journal append failed for {metadata}"
            ) from e

    def _journal_stage(self, txn: Optional[int], stage: str) -> None:
        if txn is not None and self._lifecycle_journal is not None:
            self._lifecycle_journal.stage(txn, stage)

    def _journal_commit(self, txn: Optional[int]) -> None:
        if txn is not None and self._lifecycle_journal is not None:
            self._lifecycle_journal.commit(txn)

    def _journal_rollback(self, txn: Optional[int]) -> None:
        if txn is not None and self._lifecycle_journal is not None:
            self._lifecycle_journal.rollback(txn)

    def _journal_release(self, txn: Optional[int]) -> None:
        """Mark ``txn`` no longer in flight (the owning copy/delete has
        returned); the sweeper may then act on anything still pending."""
        if txn is not None and self._lifecycle_journal is not None:
            self._lifecycle_journal.release(txn)

    def _storage_upload(self, stream: BinaryIO, key) -> int:
        """Segment-object upload chokepoint: the ``storage.write`` injection
        seam (utils/faults.py) sits here, before the stream is consumed, so a
        chaos run can fail/stall writes without corrupting partially-consumed
        uploads."""
        faults.fire("storage.write", str(key))
        return self._storage.upload(stream, key)

    def _upload_segment_log(
        self, metadata, segment_data, requires_compression, data_key,
        custom_builder, uploaded_keys,
    ):
        config = self._config
        key = self._object_key_factory.key(metadata, Suffix.LOG)
        file_size = Path(segment_data.log_segment).stat().st_size
        with self.tracer.span(
            "rsm.upload.segment", bytes=file_size, key=key.value,
        ) as span, open(segment_data.log_segment, "rb") as source:
            transformation = SegmentTransformation(
                source, file_size, config.chunk_size,
                self._transform_backend,
                self._transform_opts(requires_compression, data_key),
                collect_checksums=config.scrub_checksums_enabled,
            )
            stream: BinaryIO = transformation.stream()
            if self._rate_bucket is not None:
                stream = RateLimitedStream(stream, self._rate_bucket)
            uploaded_keys.append(key)
            uploaded = self._storage_upload(stream, key)
            if span is not None:
                span.attributes["bytes_uploaded"] = uploaded
        custom_builder.add_upload_result(Suffix.LOG, uploaded)
        self._record_upload(metadata, Suffix.LOG, uploaded)
        log.debug("Uploaded segment log for %s, size: %d", metadata, uploaded)
        return transformation.chunk_index, transformation.chunk_checksums

    def _upload_indexes(
        self, metadata, segment_data: LogSegmentData, data_key, custom_builder, uploaded_keys
    ):
        """Each index is transformed as a single chunk (encrypt-only), then all
        are concatenated into one `.indexes` object (reference :287-354,
        transformIndex :455-490; empty indexes record size 0 and upload no
        bytes)."""
        with self.tracer.span("rsm.upload.indexes"):
            return self._upload_indexes_traced(
                metadata, segment_data, data_key, custom_builder, uploaded_keys
            )

    def _upload_indexes_traced(
        self, metadata, segment_data: LogSegmentData, data_key, custom_builder, uploaded_keys
    ):
        builder = SegmentIndexesV1Builder()
        parts: list[bytes] = []

        def transform_one(index_type: IndexType, stream: BinaryIO, size: int) -> None:
            if size > 0:
                tr = SegmentTransformation(
                    stream, size, self._config.chunk_size,
                    self._transform_backend,
                    self._transform_opts(False, data_key),
                    chunking_disabled=True,
                )
                blob = tr.stream().read()
                parts.append(blob)
                builder.add(index_type, len(blob))
            else:
                builder.add(index_type, 0)

        with ClosableStreamHolder() as holder:
            for index_type, path in (
                (IndexType.OFFSET, segment_data.offset_index),
                (IndexType.TIMESTAMP, segment_data.time_index),
                (IndexType.PRODUCER_SNAPSHOT, segment_data.producer_snapshot_index),
            ):
                size = Path(path).stat().st_size
                transform_one(index_type, holder.add(open(path, "rb")), size)
            transform_one(
                IndexType.LEADER_EPOCH,
                io.BytesIO(segment_data.leader_epoch_index),
                len(segment_data.leader_epoch_index),
            )
            if segment_data.transaction_index is not None:
                size = Path(segment_data.transaction_index).stat().st_size
                transform_one(
                    IndexType.TRANSACTION,
                    holder.add(open(segment_data.transaction_index, "rb")),
                    size,
                )

        key = self._object_key_factory.key(metadata, Suffix.INDEXES)
        uploaded_keys.append(key)
        uploaded = self._storage_upload(io.BytesIO(b"".join(parts)), key)
        custom_builder.add_upload_result(Suffix.INDEXES, uploaded)
        self._record_upload(metadata, Suffix.INDEXES, uploaded)
        log.debug("Uploaded indexes file for %s, size: %d", metadata, uploaded)
        return builder.build()

    def _upload_manifest(
        self, metadata, chunk_index, segment_indexes, requires_compression,
        data_key, custom_builder, uploaded_keys, chunk_checksums=None,
    ) -> None:
        config = self._config
        encryption_metadata = None
        encoder = None
        if data_key is not None:
            encryption_metadata = SegmentEncryptionMetadataV1(data_key.data_key, data_key.aad)
            encoder = self._rsa.data_key_encoder
        manifest = SegmentManifestV1(
            chunk_index=chunk_index,
            segment_indexes=segment_indexes,
            compression=requires_compression,
            encryption=encryption_metadata,
            remote_log_segment_metadata=metadata,
            compression_codec=config.compression_codec if requires_compression else None,
            chunk_checksums=chunk_checksums,
        )
        text = manifest_to_json(manifest, data_key_encoder=encoder)
        key = self._object_key_factory.key(metadata, Suffix.MANIFEST)
        uploaded_keys.append(key)
        with self.tracer.span("rsm.upload.manifest", bytes=len(text)):
            uploaded = self._storage_upload(io.BytesIO(text.encode("utf-8")), key)
        custom_builder.add_upload_result(Suffix.MANIFEST, uploaded)
        self._record_upload(metadata, Suffix.MANIFEST, uploaded)
        log.debug("Uploaded segment manifest for %s, size: %d", metadata, uploaded)

    # ------------------------------------------------------------------ fetch
    def _object_key(self, metadata: RemoteLogSegmentMetadata, suffix: Suffix) -> ObjectKey:
        """Custom metadata (if stored) overrides prefix/key so fetches survive
        `key.prefix` changes (reference :654-665)."""
        fields = deserialize_custom_metadata(metadata.custom_metadata)
        if fields:
            return self._object_key_factory.key_from_fields(fields, metadata, suffix)
        return self._object_key_factory.key(metadata, suffix)

    def fetch_segment_manifest(self, metadata: RemoteLogSegmentMetadata) -> SegmentManifestV1:
        key = self._object_key(metadata, Suffix.MANIFEST)
        # Request-thread span: covers the cache hit or the wait on the
        # cache's loader pool (the storage GET itself runs on that pool and
        # records its own storage.fetch_manifest root span).
        with self.tracer.span("rsm.fetch_manifest", key=key.value):
            # Quarantine gate BEFORE the cache: a manifest cached while
            # healthy stops being served the moment the sweeper flags it.
            self._check_not_quarantined(key)
            # Through the lookahead: a boundary crossing whose manifest a
            # readahead continuation already started resolving JOINS that
            # flight instead of stalling on a second fetch+parse.
            return self._manifest_lookahead.get(
                key, lambda: self._fetch_manifest_by_key(key)
            )

    def _fetch_manifest_by_key(self, key: ObjectKey) -> SegmentManifestV1:
        self._check_not_quarantined(key)
        return self._fetch_manifest_raw(key)

    def _fetch_manifest_raw(self, key: ObjectKey) -> SegmentManifestV1:
        """Fetch + parse WITHOUT the quarantine gate — the recovery
        sweeper's loader: quarantine is recomputed from readability every
        sweep, so a healed manifest must be loadable to un-quarantine."""
        try:
            with self.tracer.span("storage.fetch_manifest", key=key.value), \
                    self._storage.fetch(key) as stream:
                text = stream.read()
        except KeyNotFoundException as e:
            raise RemoteResourceNotFoundException(str(e)) from e
        decoder = self._rsa.data_key_decoder if self._rsa is not None else None
        return manifest_from_json(text, data_key_decoder=decoder)

    def _check_not_quarantined(self, key: ObjectKey) -> None:
        """Quarantined manifests (unreadable, or referencing missing
        objects — see scrub/sweeper.py) are NEVER served: a half-present
        segment must fail fast and loud, not half-serve.  Checked on the
        cache path too, so a manifest cached before its quarantine stops
        being served the moment the sweeper flags it."""
        if self._sweeper is not None and self._sweeper.is_quarantined(key.value):
            raise RemoteStorageException(
                f"Manifest {key.value} is quarantined by the recovery "
                "sweeper (incomplete or unreadable segment); refusing to "
                "serve it"
            )

    @_traced("rsm.fetch_log_segment")
    def fetch_log_segment(
        self,
        metadata: RemoteLogSegmentMetadata,
        start_position: int,
        end_position: Optional[int] = None,
    ) -> BinaryIO:
        """Ranged read of the original segment bytes as a lazy stream.

        Cancellation note: the reference special-cases Java thread
        interrupts mid-fetch and returns an empty stream instead of erroring
        (RemoteStorageManager.java:563-592), because Kafka's fetch threads
        cancel in-flight reads routinely. This runtime gets the same
        property structurally: the returned stream is lazy
        (FetchChunkEnumeration fetches chunk N+1 only when the consumer
        reads past chunk N, and close() stops the enumeration early), so an
        abandoned read costs nothing and raises nothing; over the gRPC
        sidecar boundary a cancelled RPC simply stops draining the stream.
        """
        config = self._require_configured()
        if start_position < 0:
            raise ValueError(f"startPosition must be non-negative, {start_position} given")
        if end_position is not None and end_position < start_position:
            raise ValueError(
                f"endPosition {end_position} must be >= startPosition {start_position}"
            )
        start = time.monotonic()
        try:
            manifest = self.fetch_segment_manifest(metadata)
            file_size = manifest.chunk_index.original_file_size
            if start_position >= file_size:
                raise InvalidStartPosition(
                    f"Start position {start_position} is outside segment of size {file_size}"
                )
            effective_end = min(
                end_position if end_position is not None else file_size - 1,
                file_size - 1,
            )
            byte_range = BytesRange.of(start_position, effective_end)
            topic, partition = self._topic_partition(metadata)
            self._metrics.record_segment_fetch_requested_bytes(
                topic, partition, byte_range.size
            )
            key = self._object_key(metadata, Suffix.LOG)
            stream = FetchChunkEnumeration(
                self._chunk_manager, key, manifest, byte_range
            ).to_stream()
            # Latency of the synchronous request path (manifest + range
            # mapping); the lazy chunk transfer lands in chunk-fetch-time.
            self._metrics.record_segment_fetch_time(
                topic, partition, (time.monotonic() - start) * 1000.0
            )
            return stream
        except (RemoteStorageException, InvalidStartPosition,
                DeadlineExceededException):
            raise
        except KeyNotFoundException as e:
            raise RemoteResourceNotFoundException(str(e)) from e
        except StorageBackendException as e:
            raise RemoteStorageException(str(e)) from e

    @_traced("rsm.fetch_index")
    def fetch_index(self, metadata: RemoteLogSegmentMetadata, index_type: IndexType) -> BinaryIO:
        self._require_configured()
        try:
            manifest = self.fetch_segment_manifest(metadata)
            segment_index = manifest.segment_indexes.segment_index(index_type)
            if segment_index is None:
                raise RemoteResourceNotFoundException(
                    f"Index {index_type.name} not found on {self._object_key(metadata, Suffix.INDEXES)}"
                )
            if segment_index.size == 0:
                return io.BytesIO(b"")
            key = self._object_key(metadata, Suffix.INDEXES)
            return io.BytesIO(
                self._indexes_cache.get(
                    key,
                    index_type,
                    lambda: self._fetch_index_bytes(key, segment_index.range(), manifest),
                )
            )
        except DeadlineExceededException:
            raise
        except KeyNotFoundException as e:
            raise RemoteResourceNotFoundException(str(e)) from e
        except StorageBackendException as e:
            raise RemoteStorageException(str(e)) from e

    def _fetch_index_bytes(
        self, key: ObjectKey, byte_range: BytesRange, manifest: SegmentManifestV1
    ) -> bytes:
        # Same `storage.read` injection seam as the chunk path
        # (chunk_manager._fetch_stored): `error` propagates as a backend
        # failure, `partial` tears the bytes so the encrypted detransform's
        # tag check must refuse them instead of serving a torn index.
        torn = faults.fire("storage.read", key.value)
        with self._storage.fetch(key, byte_range) as stream:
            blob = stream.read()
        if torn:
            blob = faults.mutate(blob, torn)
        opts = DetransformOptions(
            compression=False,
            encryption=(
                DataKeyAndAAD(manifest.encryption.data_key, manifest.encryption.aad)
                if manifest.encryption is not None
                else None
            ),
        )
        return self._transform_backend.detransform([blob], opts)[0]

    # ----------------------------------------------------------------- delete
    @_traced("rsm.delete_log_segment_data")
    def delete_log_segment_data(self, metadata: RemoteLogSegmentMetadata) -> None:
        self._require_configured()
        log.debug("Deleting log segment data for %s", metadata)
        topic, partition = self._topic_partition(metadata)
        self._metrics.record_segment_delete(
            topic, partition, metadata.segment_size_in_bytes
        )
        start = time.monotonic()
        txn: Optional[int] = None
        try:
            keys = [self._object_key(metadata, s) for s in Suffix]
            # Tombstone BEFORE the first delete (`lifecycle.enabled`): a
            # crash-interrupted delete converges because the recovery
            # sweeper finishes what the tombstone names.  Then the manifest
            # goes FIRST: every crash point past it leaves only
            # manifest-UNreachable leftovers, which keeps the sweeper's
            # one-sidedness license sufficient to finish the job.
            txn = self._journal_begin_delete(metadata, keys)
            manifest_keys = [k for k in keys if k.value.endswith(Suffix.MANIFEST.value)]
            data_keys = [k for k in keys if not k.value.endswith(Suffix.MANIFEST.value)]
            self._delete_keys(manifest_keys, total=len(keys))
            self._delete_keys(data_keys, total=len(keys))
            self._journal_commit_delete(txn)
        except RemoteStorageException:
            self._metrics.record_segment_delete_error(topic, partition)
            raise
        except StorageBackendException as e:
            self._metrics.record_segment_delete_error(topic, partition)
            raise RemoteStorageException(f"Failed to delete {metadata}") from e
        finally:
            # The delete is no longer in flight; a tombstone left pending
            # by a partial failure is now the sweeper's to finish.
            self._journal_release(txn)
        self._metrics.record_segment_delete_time(
            topic, partition, (time.monotonic() - start) * 1000.0
        )

    def _journal_begin_delete(self, metadata, keys: list[ObjectKey]) -> Optional[int]:
        """Record delete intent; a failed tombstone append fails the delete
        before any object is removed (the broker retries)."""
        if self._lifecycle_journal is None:
            return None
        from tieredstorage_tpu.storage.lifecycle import JournalAppendError

        segment = str(metadata.remote_log_segment_id.id)
        try:
            return self._lifecycle_journal.begin_delete(
                segment, [k.value for k in keys]
            )
        except JournalAppendError as e:
            raise RemoteStorageException(
                f"Delete tombstone append failed for {metadata}"
            ) from e

    def _journal_commit_delete(self, txn: Optional[int]) -> None:
        if txn is not None and self._lifecycle_journal is not None:
            self._lifecycle_journal.commit_delete(txn)

    def _delete_keys(
        self, keys: list[ObjectKey], *, total: Optional[int] = None
    ) -> None:
        """Idempotent multi-delete: bulk fast path, then a per-key sweep on
        failure — missing keys (KeyNotFoundException) are fine (a retried
        delete or a partially-failed bulk call must converge), every other
        per-key failure is collected and surfaced as ONE
        RemoteStorageException after the sweep finishes.  ``total`` is the
        size of the logical delete set when the caller splits it across
        phases (manifest-first), so the aggregate message counts failures
        against the whole segment, not one phase."""
        if self._storage is None or not keys:
            return
        with self.tracer.span("storage.delete_keys", keys=len(keys)):
            self._delete_keys_traced(keys, len(keys) if total is None else total)

    def _delete_keys_traced(self, keys: list[ObjectKey], total: int) -> None:
        try:
            self._storage.delete_all(keys)
            return
        except StorageBackendException:
            log.debug("Bulk delete failed; sweeping per key", exc_info=True)
        failures: list[tuple[ObjectKey, StorageBackendException]] = []
        for key in keys:
            try:
                self._storage.delete(key)
            except KeyNotFoundException:
                continue  # already gone — deletion is idempotent
            except StorageBackendException as e:
                failures.append((key, e))
        if failures:
            detail = "; ".join(f"{key}: {e}" for key, e in failures)
            raise RemoteStorageException(
                f"Failed to delete {len(failures)}/{total} keys: {detail}"
            ) from failures[0][1]

    def close(self) -> None:
        if self._fleet_telemetry is not None:
            self._fleet_telemetry.close()
        if self._gossip is not None:
            self._gossip.stop()
        if self._antientropy_scheduler is not None:
            self._antientropy_scheduler.stop()
        if self._scrub_scheduler is not None:
            self._scrub_scheduler.stop()
        if self._sweep_scheduler is not None:
            self._sweep_scheduler.stop()
        if self._lifecycle_journal is not None:
            self._lifecycle_journal.close()
        if self._replicated is not None:
            self._replicated.close()
        if self._hedger is not None:
            self._hedger.close()
        if self._config is not None and self._config.tracing_export_path:
            try:
                self.tracer.write_chrome_trace(self._config.tracing_export_path)
            except OSError:
                log.warning(
                    "Failed to export Chrome trace to %s",
                    self._config.tracing_export_path, exc_info=True,
                )
        if self._chunk_manager is not None and hasattr(self._chunk_manager, "close"):
            self._chunk_manager.close()
        if self._peer_cache is not None:
            self._peer_cache.close()
        if self._manifest_lookahead is not None:
            self._manifest_lookahead.close()
        if self._manifest_cache is not None:
            self._manifest_cache.close()
        if self._indexes_cache is not None:
            self._indexes_cache.close()
        if self._transform_backend is not None:
            self._transform_backend.close()


class InvalidStartPosition(RemoteStorageException):
    """Requested fetch start beyond segment size."""
