"""The sidecar server: a RemoteStorageManager behind gRPC.

Runs the full TPU transform/storage runtime in its own process; brokers
(or the Python SidecarRsmClient) drive copy/fetch/fetch-index/delete over
the RemoteStorageSidecar service. RSM error types map onto gRPC status
codes so clients can distinguish missing segments (NOT_FOUND) from bad
requests (INVALID_ARGUMENT) and runtime failures (INTERNAL).

Start standalone:  python -m tieredstorage_tpu.sidecar --config cfg.json
(`--port 0` picks a free port; the bound port is printed as
`SIDECAR_READY port=<n>` for supervising processes to scrape.)
"""

from __future__ import annotations

import contextlib
import json
import pathlib
import tempfile
from concurrent import futures
from typing import Optional

import grpc

from tieredstorage_tpu.errors import RemoteResourceNotFoundException
from tieredstorage_tpu.manifest.segment_indexes import IndexType
from tieredstorage_tpu.metadata import LogSegmentData
from tieredstorage_tpu.sidecar import rpc
from tieredstorage_tpu.sidecar import sidecar_pb2 as pb
from tieredstorage_tpu.utils.admission import AdmissionRejectedException
from tieredstorage_tpu.utils.deadline import (
    DeadlineExceededException,
    deadline_scope,
    ensure_deadline,
    parse_deadline_ms,
)
from tieredstorage_tpu.utils.flightrecorder import NOOP_RECORDER
from tieredstorage_tpu.utils.tracing import NOOP_TRACER


class SidecarServer:
    def __init__(
        self, rsm, *, port: int = 0, host: str = "127.0.0.1",
        max_workers: Optional[int] = None,
    ):
        self._rsm = rsm
        self._tracer = getattr(rsm, "tracer", NOOP_TRACER)
        if max_workers is None:
            # `sidecar.grpc.max.workers` (config/rsm_config.py); 8 matches
            # the previously hardcoded pool for unconfigured RSM doubles.
            max_workers = getattr(rsm, "sidecar_grpc_max_workers", 8)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=rpc.channel_options(),
        )
        self._server.add_generic_rpc_handlers((self._handler(),))
        # Loopback by default (tests, co-located brokers); containers pass
        # --host 0.0.0.0 so the published port actually answers.
        self.port = self._server.add_insecure_port(f"{host}:{port}")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "SidecarServer":
        self._server.start()
        return self

    def stop(self, grace: Optional[float] = 1.0) -> None:
        self._server.stop(grace).wait()
        self._rsm.close()

    # ------------------------------------------------------------- handlers
    def _handler(self):
        impls = {
            "Copy": self._copy,
            "Fetch": self._fetch,
            "FetchIndex": self._fetch_index,
            "Delete": self._delete,
            "Health": lambda req, ctx: pb.Empty(),
        }
        handlers = {}
        for name, method in rpc.METHODS.items():
            make = (
                grpc.unary_stream_rpc_method_handler
                if method.server_streaming
                else grpc.unary_unary_rpc_method_handler
            )
            handlers[name] = make(
                self._guard(impls[name], name=name,
                            streaming=method.server_streaming),
                request_deserializer=method.request.FromString,
                response_serializer=method.response.SerializeToString,
            )
        return grpc.method_handlers_generic_handler(rpc.SERVICE, handlers)

    def _guard(self, fn, *, name: str, streaming: bool):
        """Map RSM exceptions to gRPC status codes (also mid-stream), join
        the caller's trace (`traceparent` invocation metadata parents the
        server-side span under the client's), adopt the caller's deadline
        (`x-deadline-ms` metadata, remaining budget — falling back to the
        RSM's `deadline.default.ms`), and gate every RPC through the RSM's
        AdmissionController: excess load is shed with RESOURCE_EXHAUSTED +
        a `retry-after` trailer before any storage work happens."""
        tracer = self._tracer
        rsm = self._rsm

        def classify(exc: Exception):
            if isinstance(exc, DeadlineExceededException):
                return grpc.StatusCode.DEADLINE_EXCEEDED
            if isinstance(exc, RemoteResourceNotFoundException):
                return grpc.StatusCode.NOT_FOUND
            if isinstance(exc, (ValueError, KeyError)):
                return grpc.StatusCode.INVALID_ARGUMENT
            return grpc.StatusCode.INTERNAL

        def metadata_value(context, wanted_key):
            for key, value in context.invocation_metadata() or ():
                if key == wanted_key:
                    return value
            return None

        def admit(context):
            """Admission slot, or None after aborting with RESOURCE_EXHAUSTED."""
            admission = getattr(rsm, "admission", None)
            if admission is None:
                return lambda: None
            try:
                admission.acquire(name)
            except AdmissionRejectedException as exc:
                tracer.event("admission.shed", method=name)
                context.set_trailing_metadata(
                    (("retry-after", str(max(1, round(exc.retry_after_s)))),)
                )
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"{type(exc).__name__}: {exc}",
                )
            return admission.release

        if streaming:
            def wrapped(request, context):
                release = admit(context)
                recorder = getattr(rsm, "flight_recorder", NOOP_RECORDER)
                try:
                    # The flight record spans the streamed drain (the
                    # generator body), like the span and deadline scopes.
                    with deadline_scope(
                            parse_deadline_ms(
                                metadata_value(context, rpc.DEADLINE_KEY))), \
                            ensure_deadline(
                                getattr(rsm, "default_deadline_s", None)), \
                            tracer.continue_trace(
                                metadata_value(context, rpc.TRACEPARENT_KEY)), \
                            tracer.span(f"sidecar.{name}") as span, \
                            recorder.request(
                                f"sidecar.{name}",
                                trace_id=span.trace_id if span else None,
                            ):
                        try:
                            yield from fn(request, context)
                        except Exception as exc:  # noqa: BLE001 — boundary translation
                            context.abort(classify(exc), f"{type(exc).__name__}: {exc}")
                finally:
                    release()

        else:
            def wrapped(request, context):
                release = admit(context)
                recorder = getattr(rsm, "flight_recorder", NOOP_RECORDER)
                try:
                    with deadline_scope(
                            parse_deadline_ms(
                                metadata_value(context, rpc.DEADLINE_KEY))), \
                            ensure_deadline(
                                getattr(rsm, "default_deadline_s", None)), \
                            tracer.continue_trace(
                                metadata_value(context, rpc.TRACEPARENT_KEY)), \
                            tracer.span(f"sidecar.{name}") as span, \
                            recorder.request(
                                f"sidecar.{name}",
                                trace_id=span.trace_id if span else None,
                            ):
                        try:
                            return fn(request, context)
                        except Exception as exc:  # noqa: BLE001 — boundary translation
                            context.abort(classify(exc), f"{type(exc).__name__}: {exc}")
                finally:
                    release()

        return wrapped

    def _copy(self, request: pb.CopyRequest, context) -> pb.CopyResponse:
        md = rpc.metadata_from_proto(request.metadata)
        # LogSegmentData carries paths; materialize the shipped bytes in a
        # scratch dir for the duration of the copy.
        with tempfile.TemporaryDirectory(prefix="sidecar-copy-") as tmp:
            base = pathlib.Path(tmp) / "segment"
            files = {
                "log": request.log_segment,
                "index": request.offset_index,
                "timeindex": request.time_index,
                "snapshot": request.producer_snapshot,
            }
            paths = {}
            for suffix, blob in files.items():
                p = base.with_suffix("." + suffix)
                p.write_bytes(blob)
                paths[suffix] = p
            txn = None
            if request.has_transaction_index:
                txn = base.with_suffix(".txnindex")
                txn.write_bytes(request.transaction_index)
            data = LogSegmentData(
                log_segment=paths["log"],
                offset_index=paths["index"],
                time_index=paths["timeindex"],
                producer_snapshot_index=paths["snapshot"],
                transaction_index=txn,
                leader_epoch_index=bytes(request.leader_epoch_index),
            )
            custom = self._rsm.copy_log_segment_data(md, data)
        return pb.CopyResponse(custom_metadata=custom or b"")

    def _fetch(self, request: pb.FetchRequest, context):
        md = rpc.metadata_from_proto(request.metadata)
        end = request.end_position if request.has_end else None
        with contextlib.closing(
            self._rsm.fetch_log_segment(md, request.start_position, end)
        ) as stream:
            while True:
                block = stream.read(rpc.STREAM_CHUNK_BYTES)
                if not block:
                    return
                yield pb.FetchChunk(data=block)

    def _fetch_index(self, request: pb.FetchIndexRequest, context):
        md = rpc.metadata_from_proto(request.metadata)
        index_type = IndexType[request.index_type]
        with contextlib.closing(self._rsm.fetch_index(md, index_type)) as stream:
            while True:
                block = stream.read(rpc.STREAM_CHUNK_BYTES)
                if not block:
                    return
                yield pb.FetchChunk(data=block)

    def _delete(self, request: pb.DeleteRequest, context) -> pb.Empty:
        self._rsm.delete_log_segment_data(rpc.metadata_from_proto(request.metadata))
        return pb.Empty()


def main(argv: Optional[list[str]] = None) -> None:
    import argparse
    import signal
    import sys
    import threading

    parser = argparse.ArgumentParser(description="tieredstorage_tpu gRPC sidecar")
    parser.add_argument("--config", required=True, help="JSON file of RSM configs")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--metrics-port", type=int, default=None,
        help="Serve Prometheus /metrics for the RSM registry on this port "
             "(the compose demo stack's scrape target).",
    )
    parser.add_argument(
        "--http-port", type=int, default=None,
        help="Also serve the shim-wire HTTP gateway (the boundary the "
             "dependency-free JVM broker shim in kafka-shim/ speaks) on "
             "this port; 0 picks a free port.",
    )
    parser.add_argument(
        "--fleet-peers", default=None, metavar="NAME=URL,...",
        help="Fleet membership override applied after configure(): "
             "comma-separated 'name=http://host:port' entries (bare 'name' "
             "for address-less members). Replaces fleet.instances — for "
             "deployments whose gateway ports are only known at launch. "
             "Requires fleet.enabled in the config.",
    )
    parser.add_argument(
        "--virtual-cpu-devices", type=int, default=None, metavar="N",
        help="Pin JAX to the host platform with N virtual CPU devices before "
             "serving (host-only deployments / environments where the "
             "accelerator platform would be acquired implicitly).",
    )
    args = parser.parse_args(argv)

    if args.virtual_cpu_devices is not None:
        from tieredstorage_tpu.utils.platforms import pin_virtual_cpu

        pin_virtual_cpu(args.virtual_cpu_devices)

    from tieredstorage_tpu.rsm import RemoteStorageManager

    rsm = RemoteStorageManager()
    rsm.configure(json.loads(pathlib.Path(args.config).read_text()))
    if args.fleet_peers:
        from tieredstorage_tpu.fleet import parse_instances

        rsm.set_fleet_peers(parse_instances(args.fleet_peers.split(",")))
    exporter = None
    if args.metrics_port is not None:
        from tieredstorage_tpu.metrics.prometheus import PrometheusExporter

        # Bind the exporter to the same interface as the gRPC side: a
        # loopback-only sidecar must not expose metrics network-wide.
        # The RSM's tracer rides along so /varz serves the span summary
        # (p50/p95/p99 per name) next to /metrics and /healthz; the flight
        # recorder adds the per-request `flight` section (ISSUE 14).
        exporter = PrometheusExporter(
            [rsm.metrics.registry], port=args.metrics_port, host=args.host,
            tracer=rsm.tracer, flight_recorder=rsm.flight_recorder,
        ).start()
    gateway = None
    if args.http_port is not None:
        from tieredstorage_tpu.sidecar.http_gateway import SidecarHttpGateway

        gateway = SidecarHttpGateway(rsm, port=args.http_port, host=args.host).start()
    # Gossip membership starts only once the gateway can answer inbound
    # /fleet/gossip probes (fleet.gossip.enabled is a no-op otherwise).
    if gateway is not None:
        rsm.start_fleet_gossip()
    server = SidecarServer(rsm, port=args.port, host=args.host).start()
    print(
        f"SIDECAR_READY port={server.port}"
        + (f" metrics_port={exporter.port}" if exporter else "")
        + (f" http_port={gateway.port}" if gateway else ""),
        flush=True,
    )

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    if exporter is not None:
        exporter.stop()
    if gateway is not None:
        gateway.stop()
    server.stop()
    sys.exit(0)
