"""Client side of the sidecar boundary.

`SidecarRsmClient` exposes the RemoteStorageManager method surface
(copy/fetch/fetch_index/delete/close) over gRPC, so callers — the broker
sim, tests, a JVM shim's Python twin — are drop-in independent of whether
the RSM runs in-process or behind the wire.

`FailoverRemoteStorageManager` implements the timeout→CPU-fallback
semantics (SURVEY §7 step 9): each call goes to the sidecar with a
deadline; DEADLINE_EXCEEDED/UNAVAILABLE reroutes that call to a local
in-process RSM (typically configured with the CPU transform backend), so
a wedged accelerator process degrades to host-path service instead of
failing reads/writes.
"""

from __future__ import annotations

import io
from typing import BinaryIO, Optional

import grpc

from tieredstorage_tpu.errors import (
    RemoteResourceNotFoundException,
    RemoteStorageException,
)
from tieredstorage_tpu.manifest.segment_indexes import IndexType
from tieredstorage_tpu.metadata import LogSegmentData, RemoteLogSegmentMetadata
from tieredstorage_tpu.sidecar import rpc
from tieredstorage_tpu.sidecar import sidecar_pb2 as pb
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

#: gRPC codes that mean "the sidecar can't serve right now" — the failover
#: triggers; anything else is a real answer and must propagate.
FAILOVER_CODES = (
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.UNAVAILABLE,
)


class SidecarUnavailableError(RemoteStorageException):
    """Deadline/connectivity failure — the failover wrapper's trigger."""


def _raise_mapped(err: grpc.RpcError):
    code = err.code()
    detail = err.details() or str(code)
    if code in FAILOVER_CODES:
        raise SidecarUnavailableError(detail) from None
    if code == grpc.StatusCode.NOT_FOUND:
        raise RemoteResourceNotFoundException(detail) from None
    if code == grpc.StatusCode.INVALID_ARGUMENT:
        raise ValueError(detail) from None
    raise RemoteStorageException(detail) from None


class SidecarRsmClient:
    def __init__(self, target: str, *, timeout: Optional[float] = None,
                 tracer=None):
        self._channel = grpc.insecure_channel(target, options=rpc.channel_options())
        self._timeout = timeout
        # Client-side spans + traceparent metadata: a fetch through the
        # sidecar shows up as ONE tree (client.fetch → sidecar.Fetch →
        # rsm.fetch_log_segment → storage.*) instead of two disjoint traces.
        self._tracer = tracer if tracer is not None else NOOP_TRACER
        self._stubs = {}
        for name, m in rpc.METHODS.items():
            make = (
                self._channel.unary_stream
                if m.server_streaming
                else self._channel.unary_unary
            )
            self._stubs[name] = make(
                m.path,
                request_serializer=m.request.SerializeToString,
                response_deserializer=m.response.FromString,
            )

    def _effective_timeout(self, timeout: Optional[float]) -> Optional[float]:
        """Per-call gRPC timeout clamped to the ambient Deadline's remaining
        budget, so a late call in a deadlined request can't take a full
        fresh timeout (cross-layer deadline semantics)."""
        from tieredstorage_tpu.utils.deadline import remaining_s

        candidates = [t for t in (timeout or self._timeout, remaining_s())
                      if t is not None]
        return max(0.001, min(candidates)) if candidates else None

    def _invoke(self, name: str, req, timeout: Optional[float] = None):
        """Unary call inside a client span; traceparent + deadline metadata
        attached (computed INSIDE the span so the server parents under it)."""
        with self._tracer.span(f"client.{name}"):
            return self._stubs[name](
                req, timeout=self._effective_timeout(timeout),
                metadata=rpc.invocation_metadata(self._tracer),
            )

    # ------------------------------------------------------------- surface
    def health(self, timeout: Optional[float] = None) -> None:
        self._invoke("Health", pb.Empty(), timeout=timeout)

    def copy_log_segment_data(
        self, metadata: RemoteLogSegmentMetadata, data: LogSegmentData
    ) -> bytes:
        req = pb.CopyRequest(
            metadata=rpc.metadata_to_proto(metadata),
            log_segment=data.log_segment.read_bytes(),
            offset_index=data.offset_index.read_bytes(),
            time_index=data.time_index.read_bytes(),
            producer_snapshot=data.producer_snapshot_index.read_bytes(),
            leader_epoch_index=bytes(data.leader_epoch_index),
        )
        if data.transaction_index is not None:
            req.transaction_index = data.transaction_index.read_bytes()
            req.has_transaction_index = True
        try:
            resp = self._invoke("Copy", req)
        except grpc.RpcError as err:
            _raise_mapped(err)
        return bytes(resp.custom_metadata)

    def fetch_log_segment(
        self,
        metadata: RemoteLogSegmentMetadata,
        start_position: int,
        end_position: Optional[int] = None,
    ) -> BinaryIO:
        req = pb.FetchRequest(
            metadata=rpc.metadata_to_proto(metadata),
            start_position=start_position,
            end_position=end_position if end_position is not None else 0,
            has_end=end_position is not None,
        )
        return self._drain("Fetch", req)

    def fetch_index(
        self, metadata: RemoteLogSegmentMetadata, index_type: IndexType
    ) -> BinaryIO:
        req = pb.FetchIndexRequest(
            metadata=rpc.metadata_to_proto(metadata), index_type=index_type.name
        )
        return self._drain("FetchIndex", req)

    def delete_log_segment_data(self, metadata: RemoteLogSegmentMetadata) -> None:
        try:
            self._invoke(
                "Delete", pb.DeleteRequest(metadata=rpc.metadata_to_proto(metadata))
            )
        except grpc.RpcError as err:
            _raise_mapped(err)

    def close(self) -> None:
        self._channel.close()

    # ------------------------------------------------------------ internals
    def _drain(self, name: str, req) -> BinaryIO:
        buf = io.BytesIO()
        try:
            with self._tracer.span(f"client.{name}") as span:
                for chunk in self._stubs[name](
                    req, timeout=self._effective_timeout(None),
                    metadata=rpc.invocation_metadata(self._tracer),
                ):
                    buf.write(chunk.data)
                if span is not None:
                    span.attributes["bytes"] = buf.tell()
        except grpc.RpcError as err:
            _raise_mapped(err)
        buf.seek(0)
        return buf


class FailoverRemoteStorageManager:
    """Sidecar-first RSM: per-call deadline, local-RSM fallback.

    `fallback` is any object with the RSM surface — typically a
    RemoteStorageManager configured with the CPU transform backend against
    the same storage, so data written by either path is readable by both
    (same wire format; SURVEY §7 step 9's degradation mode)."""

    def __init__(self, client: SidecarRsmClient, fallback, *, timeout: float):
        self._client = client
        self._fallback = fallback
        self._timeout = timeout
        client._timeout = timeout
        self.fallback_calls = 0

    def _route(self, method: str, *args):
        try:
            return getattr(self._client, method)(*args)
        except SidecarUnavailableError:
            self.fallback_calls += 1
            return getattr(self._fallback, method)(*args)

    def copy_log_segment_data(self, metadata, data):
        return self._route("copy_log_segment_data", metadata, data)

    def fetch_log_segment(self, metadata, start_position, end_position=None):
        return self._route("fetch_log_segment", metadata, start_position, end_position)

    def fetch_index(self, metadata, index_type):
        return self._route("fetch_index", metadata, index_type)

    def delete_log_segment_data(self, metadata):
        return self._route("delete_log_segment_data", metadata)

    def close(self) -> None:
        self._client.close()
        self._fallback.close()
