"""Shared method table for the hand-bound gRPC service.

grpc_tools (the protoc gRPC plugin) is not in this image, so the service
is registered from this table on both sides: the server via
`grpc.method_handlers_generic_handler`, the client via
`channel.unary_unary`/`unary_stream` with the generated message classes'
serializers. protoc itself generates sidecar_pb2 (see sidecar.proto).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from tieredstorage_tpu.sidecar import sidecar_pb2 as pb

SERVICE = "tieredstorage.sidecar.v1.RemoteStorageSidecar"


@dataclasses.dataclass(frozen=True)
class Method:
    name: str
    request: type
    response: type
    server_streaming: bool = False

    @property
    def path(self) -> str:
        return f"/{SERVICE}/{self.name}"


METHODS = {
    m.name: m
    for m in (
        Method("Copy", pb.CopyRequest, pb.CopyResponse),
        Method("Fetch", pb.FetchRequest, pb.FetchChunk, server_streaming=True),
        Method(
            "FetchIndex", pb.FetchIndexRequest, pb.FetchChunk, server_streaming=True
        ),
        Method("Delete", pb.DeleteRequest, pb.Empty),
        Method("Health", pb.Empty, pb.Empty),
    )
}

#: gRPC invocation-metadata key carrying the W3C trace context — the gRPC
#: twin of the HTTP gateway's `traceparent` header (shimwire.TRACEPARENT_HEADER).
TRACEPARENT_KEY = "traceparent"

#: gRPC invocation-metadata key carrying the remaining end-to-end budget in
#: integer milliseconds — the gRPC twin of shimwire.DEADLINE_HEADER.
DEADLINE_KEY = "x-deadline-ms"


def trace_metadata(tracer) -> Optional[tuple[tuple[str, str], ...]]:
    """Invocation metadata joining a call to the active trace, or None when
    there is nothing to propagate (tracing disabled / no active span)."""
    traceparent = tracer.current_traceparent() if tracer is not None else None
    return ((TRACEPARENT_KEY, traceparent),) if traceparent else None


def invocation_metadata(tracer) -> Optional[tuple[tuple[str, str], ...]]:
    """Trace + deadline invocation metadata for an outgoing sidecar call;
    None when neither is active."""
    from tieredstorage_tpu.utils.deadline import current_deadline

    out = list(trace_metadata(tracer) or ())
    deadline = current_deadline()
    if deadline is not None:
        out.append((DEADLINE_KEY, deadline.header_value()))
    return tuple(out) or None


#: Per-message ceiling for unary payloads (whole segments ride CopyRequest).
MAX_MESSAGE_BYTES = 512 << 20

#: Fetch/FetchIndex stream frame size.
STREAM_CHUNK_BYTES = 1 << 20


def channel_options() -> list[tuple[str, int]]:
    return [
        ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
        ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
    ]


def metadata_to_proto(md, *, include_custom: bool = True) -> pb.SegmentMetadata:
    """RemoteLogSegmentMetadata -> proto."""
    rid = md.remote_log_segment_id
    tip = rid.topic_id_partition
    out = pb.SegmentMetadata(
        id=pb.SegmentId(
            topic_id=bytes(tip.topic_id.raw),
            topic=tip.topic_partition.topic,
            partition=tip.topic_partition.partition,
            segment_id=bytes(rid.id.raw),
        ),
        start_offset=md.start_offset,
        end_offset=md.end_offset,
        max_timestamp_ms=md.max_timestamp_ms,
        broker_id=md.broker_id,
        event_timestamp_ms=md.event_timestamp_ms,
        segment_size_bytes=md.segment_size_in_bytes,
    )
    for epoch, offset in md.segment_leader_epochs.items():
        out.leader_epochs[int(epoch)] = int(offset)
    if include_custom and md.custom_metadata is not None:
        out.custom_metadata = bytes(md.custom_metadata)
        out.has_custom_metadata = True
    return out


def metadata_from_proto(msg: pb.SegmentMetadata):
    from tieredstorage_tpu.metadata import (
        KafkaUuid,
        RemoteLogSegmentId,
        RemoteLogSegmentMetadata,
        TopicIdPartition,
        TopicPartition,
    )

    return RemoteLogSegmentMetadata(
        remote_log_segment_id=RemoteLogSegmentId(
            TopicIdPartition(
                KafkaUuid(bytes(msg.id.topic_id)),
                TopicPartition(msg.id.topic, msg.id.partition),
            ),
            KafkaUuid(bytes(msg.id.segment_id)),
        ),
        start_offset=msg.start_offset,
        end_offset=msg.end_offset,
        max_timestamp_ms=msg.max_timestamp_ms,
        broker_id=msg.broker_id,
        event_timestamp_ms=msg.event_timestamp_ms,
        segment_leader_epochs=dict(msg.leader_epochs),
        segment_size_in_bytes=msg.segment_size_bytes,
        custom_metadata=(
            bytes(msg.custom_metadata) if msg.has_custom_metadata else None
        ),
    )
