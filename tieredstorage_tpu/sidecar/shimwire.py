"""Shim wire format v1 — the JDK-only encoding of the sidecar boundary.

The broker-side JVM shim (`kafka-shim/`) must be deployable with ZERO
third-party jars: a broker operator drops one class (plus
`kafka-storage-api`, already on the broker classpath) next to the broker
and points it at the sidecar. grpc-java + protobuf-java + netty would be a
shaded-jar dependency train, and `java.net.http` cannot read the HTTP/2
trailers gRPC carries its status in — so the sidecar exposes this second,
deliberately boring boundary for the shim: HTTP/1.1 + a fixed big-endian
binary framing that `java.io.DataOutputStream` writes naturally. The gRPC
service (sidecar/server.py) remains the boundary for Python clients; both
front the same RemoteStorageManager in the same process.

All integers big-endian (Java DataOutput order). The metadata block mirrors
KIP-405 RemoteLogSegmentMetadata (reference:
storage/api/.../RemoteLogSegmentMetadata semantics via
core/.../RemoteStorageManager.java:106):

    u8   version (1)
    16B  topic_id          (Kafka Uuid, msb||lsb)
    16B  segment_id
    u16  topic_len | utf8 topic
    i32  partition
    i64  start_offset | i64 end_offset | i64 max_timestamp_ms
    i32  broker_id | i64 event_timestamp_ms
    i32  n_epochs | n x (i32 leader_epoch, i64 start_offset)
    i64  segment_size_bytes
    u8   has_custom | [u32 len | bytes]

Requests (POST bodies; responses are raw bytes or empty):

    /v1/copy         metadata + 6 sections (log, offset_index, time_index,
                     producer_snapshot, transaction_index,
                     leader_epoch_index), each u8 present | u64 len | bytes
                     -> 200 custom-metadata bytes | 204 none
    /v1/fetch        metadata + i64 start + u8 has_end + i64 end
                     -> 200 raw segment byte stream
    /v1/fetch-index  metadata + u16 len | utf8 IndexType name
                     -> 200 raw index byte stream
    /v1/delete       metadata -> 204
    GET /v1/health   -> 200

Errors: 404 = RemoteResourceNotFoundException, 400 = invalid argument,
500 = anything else; the body is a UTF-8 message. The Java shim maps these
back onto the KIP-405 exception types.

Trace context deliberately rides the standard W3C ``traceparent`` HTTP
header, NOT the binary frame: wire version 1 stays byte-stable, and the JVM
shim can join broker-side traces with one `setHeader` (java.net.http passes
unknown headers through untouched, so older shims interoperate unchanged).
"""

from __future__ import annotations

import io
import struct
from typing import BinaryIO, Optional

from tieredstorage_tpu.metadata import (
    KafkaUuid,
    RemoteLogSegmentId,
    RemoteLogSegmentMetadata,
    TopicIdPartition,
    TopicPartition,
)

VERSION = 1

#: W3C trace-context header joining shim requests to the caller's trace
#: (see module docstring: headers, not frame bytes, carry trace identity).
TRACEPARENT_HEADER = "traceparent"

#: Remaining end-to-end budget in integer milliseconds — the deadline twin
#: of the traceparent header (utils/deadline.py). Like trace context, it
#: rides a header rather than the binary frame so wire version 1 stays
#: byte-stable and older shims interoperate unchanged.
DEADLINE_HEADER = "x-deadline-ms"


def trace_headers(tracer) -> dict[str, str]:
    """Headers a shim-wire client should attach to join the active trace;
    empty when there is nothing to propagate (tracing disabled / no span)."""
    traceparent = tracer.current_traceparent() if tracer is not None else None
    return {TRACEPARENT_HEADER: traceparent} if traceparent else {}


def deadline_headers() -> dict[str, str]:
    """Header propagating the ambient Deadline's remaining budget; empty
    when the calling context is unconstrained."""
    from tieredstorage_tpu.utils.deadline import current_deadline

    deadline = current_deadline()
    return {DEADLINE_HEADER: deadline.header_value()} if deadline else {}


def request_headers(tracer) -> dict[str, str]:
    """Everything a shim-wire client should attach: trace + deadline."""
    return {**trace_headers(tracer), **deadline_headers()}


COPY_SECTIONS = (
    "log_segment",
    "offset_index",
    "time_index",
    "producer_snapshot",
    "transaction_index",
    "leader_epoch_index",
)


class ShimWireError(ValueError):
    """Malformed shim-wire payload."""


def _read(buf: BinaryIO, n: int) -> bytes:
    data = buf.read(n)
    if len(data) != n:
        raise ShimWireError(f"truncated payload: wanted {n} bytes, got {len(data)}")
    return data


def encode_metadata(md: RemoteLogSegmentMetadata) -> bytes:
    rid = md.remote_log_segment_id
    topic = rid.topic_id_partition.topic_partition.topic.encode("utf-8")
    out = io.BytesIO()
    out.write(struct.pack(">B", VERSION))
    out.write(rid.topic_id_partition.topic_id.raw)
    out.write(rid.id.raw)
    out.write(struct.pack(">H", len(topic)))
    out.write(topic)
    out.write(
        struct.pack(
            ">iqqqiq",
            rid.topic_id_partition.topic_partition.partition,
            md.start_offset,
            md.end_offset,
            md.max_timestamp_ms,
            md.broker_id,
            md.event_timestamp_ms,
        )
    )
    epochs = sorted(md.segment_leader_epochs.items())
    out.write(struct.pack(">i", len(epochs)))
    for epoch, offset in epochs:
        out.write(struct.pack(">iq", epoch, offset))
    out.write(struct.pack(">q", md.segment_size_in_bytes))
    if md.custom_metadata is None:
        out.write(b"\x00")
    else:
        out.write(struct.pack(">BI", 1, len(md.custom_metadata)))
        out.write(md.custom_metadata)
    return out.getvalue()


def decode_metadata(buf: BinaryIO) -> RemoteLogSegmentMetadata:
    (version,) = struct.unpack(">B", _read(buf, 1))
    if version != VERSION:
        raise ShimWireError(f"unsupported shim wire version {version}")
    topic_id = KafkaUuid(_read(buf, 16))
    segment_id = KafkaUuid(_read(buf, 16))
    (topic_len,) = struct.unpack(">H", _read(buf, 2))
    topic = _read(buf, topic_len).decode("utf-8")
    partition, start, end, max_ts, broker, event_ts = struct.unpack(
        ">iqqqiq", _read(buf, 4 + 8 * 3 + 4 + 8)
    )
    (n_epochs,) = struct.unpack(">i", _read(buf, 4))
    if n_epochs < 0 or n_epochs > 1 << 20:
        raise ShimWireError(f"implausible epoch count {n_epochs}")
    epochs = {}
    for _ in range(n_epochs):
        epoch, offset = struct.unpack(">iq", _read(buf, 12))
        epochs[epoch] = offset
    (size,) = struct.unpack(">q", _read(buf, 8))
    (has_custom,) = struct.unpack(">B", _read(buf, 1))
    custom: Optional[bytes] = None
    if has_custom:
        (clen,) = struct.unpack(">I", _read(buf, 4))
        custom = _read(buf, clen)
    return RemoteLogSegmentMetadata(
        remote_log_segment_id=RemoteLogSegmentId(
            TopicIdPartition(topic_id, TopicPartition(topic, partition)), segment_id
        ),
        start_offset=start,
        end_offset=end,
        max_timestamp_ms=max_ts,
        broker_id=broker,
        event_timestamp_ms=event_ts,
        segment_leader_epochs=epochs,
        segment_size_in_bytes=size,
        custom_metadata=custom,
    )


def encode_sections(sections: dict) -> bytes:
    """COPY_SECTIONS name -> Optional[bytes], in wire order (the Python-side
    encoder mirror of the Java shim's copyBody; symmetry-pinned against the
    independent test encoder in tests/test_sidecar_http_gateway.py)."""
    out = io.BytesIO()
    for name in COPY_SECTIONS:
        blob = sections.get(name)
        if blob is None:
            out.write(b"\x00")
        else:
            out.write(struct.pack(">BQ", 1, len(blob)))
            out.write(blob)
    return out.getvalue()


def decode_sections_to_dir(
    buf: BinaryIO, directory, *, max_section: int = 2 << 30
) -> dict:
    """Like decode_sections, but streams each present section straight into
    `directory`/<name> so a whole segment never has to sit in sidecar RAM.
    Returns COPY_SECTIONS name -> Optional[pathlib.Path]."""
    import pathlib
    import shutil

    directory = pathlib.Path(directory)
    sections: dict = {}
    for name in COPY_SECTIONS:
        (present,) = struct.unpack(">B", _read(buf, 1))
        if not present:
            sections[name] = None
            continue
        (length,) = struct.unpack(">Q", _read(buf, 8))
        if length > max_section:
            raise ShimWireError(f"section {name} of {length} bytes over the cap")
        path = directory / name
        with open(path, "wb") as out:
            shutil.copyfileobj(io.BytesIO(_read(buf, length)) if length < (1 << 20)
                               else _SectionReader(buf, length), out)
        if path.stat().st_size != length:
            raise ShimWireError(f"section {name} truncated")
        sections[name] = path
    return sections


class _SectionReader(io.RawIOBase):
    """Bounded view over `buf` for streaming one section to disk."""

    def __init__(self, buf: BinaryIO, length: int):
        self._buf = buf
        self._remaining = length

    def readable(self) -> bool:
        return True

    def read(self, size: int = -1) -> bytes:
        if self._remaining == 0:
            return b""
        if size is None or size < 0:
            size = self._remaining
        data = self._buf.read(min(size, self._remaining))
        if not data:
            raise ShimWireError("truncated section payload")
        self._remaining -= len(data)
        return data


def encode_fetch_tail(start: int, end: Optional[int]) -> bytes:
    return struct.pack(
        ">qBq", start, 1 if end is not None else 0, end if end is not None else 0
    )


def decode_fetch_tail(buf: BinaryIO) -> tuple[int, Optional[int]]:
    start, has_end, end = struct.unpack(">qBq", _read(buf, 17))
    return start, end if has_end else None


def encode_index_type(name: str) -> bytes:
    raw = name.encode("utf-8")
    return struct.pack(">H", len(raw)) + raw


def decode_index_type(buf: BinaryIO) -> str:
    (length,) = struct.unpack(">H", _read(buf, 2))
    return _read(buf, length).decode("utf-8")
