"""gRPC sidecar: the broker↔accelerator process boundary (SURVEY §7 step 9).

The reference runs as an in-process JVM plugin; this framework keeps the
TPU runtime in its own process. `server` hosts a configured
RemoteStorageManager behind the RemoteStorageSidecar service; `client`
offers the same Python RSM surface over the wire plus timeout-based
failover to a local CPU-path RSM.
"""

from tieredstorage_tpu.sidecar.client import (  # noqa: F401
    FailoverRemoteStorageManager,
    SidecarRsmClient,
)
from tieredstorage_tpu.sidecar.server import SidecarServer  # noqa: F401
