from tieredstorage_tpu.sidecar.server import main

main()
