"""HTTP/1.1 gateway for the broker-side JVM shim (shim wire format v1).

Serves the same five operations as the gRPC service (sidecar/server.py)
against the same RemoteStorageManager, over the dependency-free framing in
sidecar/shimwire.py, so the Java shim (`kafka-shim/`) needs nothing but the
JDK. Runs inside the sidecar process; `python -m tieredstorage_tpu.sidecar
--http-port N` starts it next to the gRPC listener.

Error mapping (the shim translates back to KIP-405 exception types):
404 RemoteResourceNotFoundException, 400 invalid argument,
429 + Retry-After admission shed, 504 deadline exceeded, 500 the rest.

Tail tolerance at this boundary (ISSUE 4): the ``x-deadline-ms`` header is
adopted as the request's end-to-end Deadline (falling back to the RSM's
``deadline.default.ms``), and every POST passes the RSM's
AdmissionController — shedding happens BEFORE the request body is read, so
an overloaded sidecar refuses cheaply instead of buffering segment uploads
it will never serve. Requests carrying an ``x-tenant`` header are
additionally subject to the controller's per-tenant fair share at
saturation (429 when a greedy tenant exceeds its split).

Fleet mode (ISSUE 6) adds two things at this boundary:

- ``GET /chunk?key=<object key>&chunks=<lo>-<hi>`` — the peer-cache route:
  a sibling instance asks the OWNER of a segment for a window of plaintext
  chunks (framed u32 count + per-chunk u32 len|bytes). Served through the
  owner's full chunk path (cache, then single-flight backend fetch), with
  the caller's ``x-deadline-ms`` and ``traceparent`` honored; deliberately
  NOT admission-gated — a client request already holds a slot while it
  forwards, so gating the peer hop could deadlock the fleet at saturation
  (the bounded worker pool is the backstop).
- a bounded worker pool (``sidecar.http.max.workers``): connections are
  handled by a fixed executor instead of one unbounded thread each, so a
  fleet instance under fan-in keeps a bounded thread count and excess
  connections queue instead of multiplying stacks.

Gossip membership (ISSUE 11) adds the SWIM exchange pair:

- ``POST /fleet/gossip`` — one membership exchange: the sender's JSON view
  is merged (fleet/gossip.py precedence rules) and this member's full view
  is the response. NOT admission-gated: gossip is the failure detector, and
  shedding it under load would make overload read as mass death.
- ``GET /fleet/ping[?witness=1]`` — liveness + status: ring generation and
  epoch, the gossip view, peer-tier counters; ``witness=1`` adds the
  runtime lock/race witness verdicts (the multi-process soak's gate).

The observability plane (ISSUE 14) adds three read-only routes:

- ``GET /slo`` — the SLO engine's verdicts (``slo.enabled``): per-spec
  compliance, error-budget remaining, and two-window burn rates computed
  from the live latency histograms; 404 while the engine is disabled.
- ``GET /debug/requests[?n=K|?slowest=K|?trace=<id>]`` — the flight
  recorder's retained evidence (``flight.enabled``): the K slowest and
  the failed requests with per-tier chunk counts, hedge/failover
  activity, GCM window accounting, and deadline budget at each stage;
  ``trace`` filters to one trace id's records (404 when none retained —
  the fleet stitcher's per-member query), ``slowest`` returns just the K
  slowest completed records; 404 while disabled, 400 on a bad count.
  Every POST request and peer-chunk serve records through the recorder,
  covering the streamed response drain.
- ``GET /debug/timeline`` (ISSUE 17) — the device-scheduler timeline ring
  (``timeline.enabled``): every merged GCM launch's scheduler context
  (work class, bucket shape, occupancy, queue depths, waiter trace ids)
  plus the clock-epoch pin the fleet stitcher uses to land peers on one
  Perfetto time axis; 404 while disabled.
- ``GET /fleet/telemetry[?aggregate=1]`` — this member's metric samples
  (fleet mode), or with ``aggregate=1`` the whole membership view merged
  into one fleet-wide scrape (sum/max/histogram-merge per stat).
"""

from __future__ import annotations

import contextlib
import math
import pathlib
import tempfile
import threading
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, unquote, urlsplit

from tieredstorage_tpu.errors import RemoteResourceNotFoundException
from tieredstorage_tpu.manifest.segment_indexes import IndexType
from tieredstorage_tpu.metadata import LogSegmentData
from tieredstorage_tpu.sidecar import shimwire
from tieredstorage_tpu.utils.admission import AdmissionRejectedException
from tieredstorage_tpu.utils.deadline import (
    DeadlineExceededException,
    deadline_scope,
    ensure_deadline,
    parse_deadline_ms,
)
from tieredstorage_tpu.utils.flightrecorder import NOOP_RECORDER
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

_STREAM_BLOCK = 1 << 20
#: Spool request bodies to disk past this (copy uploads are whole segments).
_SPOOL_BYTES = 64 << 20
#: Reject request bodies past this — matches the gRPC boundary's
#: max-message ceiling so a runaway client can't OOM the sidecar.
MAX_BODY_BYTES = 2 << 30


class _BodyTooLarge(Exception):
    pass


class _StreamAborted(Exception):
    """A fetch stream failed after the 200 was committed: the chunked
    framing is unrecoverable, so the connection is aborted instead of a
    second response being written into the body."""


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    rsm = None  # set per-server subclass

    def log_message(self, fmt, *args):  # quiet; the RSM has its own tracing
        pass

    # ------------------------------------------------------------- plumbing
    def _body(self):
        """Request body as a seekable file, disk-spooled past _SPOOL_BYTES
        and capped at MAX_BODY_BYTES (a copy request holds a whole segment,
        which must not be required to fit in sidecar RAM).

        Copy uploads touch disk twice (spooled body, then the decoded
        section files) — accepted: decoding straight off the socket would
        tie chunked-transfer framing into the section parser, and a segment
        copy is a once-per-segment operation whose cost is dominated by the
        transform, not local disk."""
        out = tempfile.SpooledTemporaryFile(max_size=_SPOOL_BYTES)
        total = 0

        def take(n: int) -> None:
            nonlocal total
            remaining = n
            while remaining:
                block = self.rfile.read(min(remaining, _STREAM_BLOCK))
                if not block:
                    raise shimwire.ShimWireError("request body truncated")
                total += len(block)
                if total > MAX_BODY_BYTES:
                    raise _BodyTooLarge()
                out.write(block)
                remaining -= len(block)

        if self.headers.get("Transfer-Encoding", "").lower() == "chunked":
            # java.net.http streams unknown-length bodies (the shim's copy
            # path wraps file streams) as chunked; BaseHTTPRequestHandler
            # doesn't decode it, so do it here.
            while True:
                raw_line = self.rfile.readline(1024)
                if not raw_line.endswith(b"\n"):
                    # Truncation here would silently shift the remainder of
                    # the size line into the chunk data.
                    raise shimwire.ShimWireError("chunk size line too long")
                size_line = raw_line.strip()
                # Strict RFC 7230 chunk-size grammar (1*HEXDIG). int(_, 16)
                # alone also accepts "-5"/"+5"/"0x1f"/"1_0" — the negative
                # forms would make take(n<0) spin reading to EOF, and the
                # non-canonical ones are request-smuggling surface against
                # stricter intermediaries.
                # BWS before the chunk-ext ';' is valid per RFC 7230 §3.2.3
                # (recipients MUST parse and remove) — strip it before the
                # strict 1*HEXDIG check.
                size_field = size_line.split(b";")[0].strip()
                if not size_field or not all(
                    c in b"0123456789abcdefABCDEF" for c in size_field
                ):
                    raise shimwire.ShimWireError(
                        f"bad chunk size line {size_line!r}"
                    )
                size = int(size_field, 16)
                if size == 0:
                    # Consume the trailer section up to the final CRLF.
                    while self.rfile.readline(1024).strip():
                        pass
                    break
                take(size)
                self.rfile.read(2)  # chunk-terminating CRLF
        else:
            raw_len = self.headers.get("Content-Length", "0").strip()
            # Same strict grammar rationale as chunk sizes: bare int()
            # accepts '+5'/'1_0'/'-7', all desync surface ('-7' would also
            # spin take() to EOF). str.isdigit() is NOT the right gate — it
            # accepts non-ASCII digits (e.g. '٥', '５') that int() happily
            # parses, so hold the same explicit ASCII allowlist as the
            # chunk-size arm.
            if not raw_len or not all(c in "0123456789" for c in raw_len):
                raise shimwire.ShimWireError(
                    f"bad Content-Length {raw_len!r}"
                )
            length = int(raw_len)
            if length > MAX_BODY_BYTES:
                raise _BodyTooLarge()
            take(length)
        out.seek(0)
        return out

    def _reply(self, status: int, body: bytes = b"", headers=None) -> None:
        self.send_response(status)
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _reply_stream(self, stream) -> None:
        """200 + chunked transfer of a file-like's contents.

        The RSM's fetch streams are lazy: the manifest fetch (and its 404)
        happens on the first read. Pull that block BEFORE committing the
        status line so not-found maps to a clean 404 instead of a
        truncated 200. A failure later mid-stream can only abort the
        connection (the shim surfaces that as a transport error, the same
        way a gRPC mid-stream abort lands)."""
        with contextlib.closing(stream):
            first = stream.read(_STREAM_BLOCK)
            self.send_response(200)
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                block = first
                while block:
                    self.wfile.write(b"%x\r\n" % len(block) + block + b"\r\n")
                    block = stream.read(_STREAM_BLOCK)
                self.wfile.write(b"0\r\n\r\n")
            except Exception as exc:
                raise _StreamAborted() from exc

    def _fail(self, exc: Exception) -> None:
        headers = None
        if isinstance(exc, AdmissionRejectedException):
            status = 429
            headers = {"Retry-After": str(max(1, math.ceil(exc.retry_after_s)))}
        elif isinstance(exc, DeadlineExceededException):
            status = 504
        elif isinstance(exc, RemoteResourceNotFoundException):
            status = 404
        elif isinstance(exc, (ValueError, KeyError)):
            status = 400
        else:
            status = 500
        self._reply(status, f"{type(exc).__name__}: {exc}".encode("utf-8"),
                    headers=headers)

    # ------------------------------------------------------------- handlers
    def do_GET(self) -> None:
        parts = urlsplit(self.path)
        if parts.path == "/v1/health":
            self._reply(200)
        elif parts.path in ("/chunk", "/v1/chunk"):
            self._peer_chunk(parts.query)
        elif parts.path in ("/fleet/ping", "/v1/fleet/ping"):
            self._fleet_ping(parts.query)
        elif parts.path in ("/fleet/telemetry", "/v1/fleet/telemetry"):
            self._fleet_telemetry(parts.query)
        elif parts.path in ("/slo", "/v1/slo"):
            self._slo()
        elif parts.path in ("/debug/requests", "/v1/debug/requests"):
            self._debug_requests(parts.query)
        elif parts.path in ("/debug/timeline", "/v1/debug/timeline"):
            self._debug_timeline()
        elif self.path in ("/scrub", "/v1/scrub"):
            # Integrity-scrubber status: scheduler state, cumulative
            # counters, and the last pass summary ({"enabled": false} when
            # scrub.enabled is off).
            import json

            status = (
                self.rsm.scrub_status()
                if hasattr(self.rsm, "scrub_status")
                else {"enabled": False}
            )
            self._reply(200, json.dumps(status, indent=1).encode("utf-8"))
        else:
            self._reply(404, b"no such endpoint")

    def _peer_chunk(self, query: str) -> None:
        """Fleet peer-cache route: serve a window of plaintext chunks of a
        locally-owned segment to a sibling instance (fleet/peer_cache.py).
        The serving path pins the key local, so a forwarded request can
        never be re-forwarded even under transient ring disagreement."""
        serve = getattr(self.rsm, "fleet_fetch_chunks", None)
        if serve is None or getattr(self.rsm, "fleet_router", None) is None:
            self._reply(404, b"fleet mode disabled")
            return
        tracer = getattr(self.rsm, "tracer", NOOP_TRACER)
        try:
            params = parse_qs(query, keep_blank_values=False, strict_parsing=False)
            key = unquote(params["key"][0])
            window = params["chunks"][0]
            first_s, _, last_s = window.partition("-")
            first, last = int(first_s), int(last_s or first_s)
        except (KeyError, IndexError, ValueError):
            self._reply(400, b"expected ?key=<object key>&chunks=<lo>-<hi>")
            return
        wire_deadline = parse_deadline_ms(self.headers.get(shimwire.DEADLINE_HEADER))
        recorder = getattr(self.rsm, "flight_recorder", NOOP_RECORDER)
        try:
            with deadline_scope(wire_deadline), \
                    ensure_deadline(getattr(self.rsm, "default_deadline_s", None)), \
                    tracer.continue_trace(
                        self.headers.get(shimwire.TRACEPARENT_HEADER)), \
                    tracer.span(
                        "gateway.chunk", key=key, chunks=last - first + 1
                    ) as span, \
                    recorder.request(
                        "gateway.chunk",
                        trace_id=span.trace_id if span else None,
                    ):
                chunks = serve(key, first, last)
        except Exception as exc:  # noqa: BLE001 — boundary translation
            self._fail(exc)
            return
        from tieredstorage_tpu.fleet.peer_cache import encode_chunk_frames

        self._reply(200, encode_chunk_frames(chunks))

    def _fleet_ping(self, query: str) -> None:
        """Fleet liveness/status: ring + gossip view (+ witness verdicts on
        ``?witness=1`` — a full static-vs-runtime crosscheck, so only drills
        like tools/fleet_soak.py ask for it)."""
        import json

        ping = getattr(self.rsm, "fleet_ping", None)
        if ping is None or getattr(self.rsm, "fleet_router", None) is None:
            self._reply(404, b"fleet mode disabled")
            return
        params = parse_qs(query, keep_blank_values=False, strict_parsing=False)
        include_witness = params.get("witness", ["0"])[0] in ("1", "true")
        try:
            status = ping(include_witness=include_witness)
        except Exception as exc:  # noqa: BLE001 — boundary translation
            self._fail(exc)
            return
        self._reply(200, json.dumps(status, indent=1).encode("utf-8"))

    def _slo(self) -> None:
        """SLO verdicts (metrics/slo.py): compliance, error budget, and
        two-window burn rates per declared objective. 404 while
        ``slo.enabled`` is off — an absent engine must read as "not
        configured", never as "everything within budget"."""
        import json

        if getattr(self.rsm, "slo_engine", None) is None:
            self._reply(404, b"slo engine disabled")
            return
        try:
            status = self.rsm.slo_status()
        except Exception as exc:  # noqa: BLE001 — boundary translation
            self._fail(exc)
            return
        self._reply(200, json.dumps(status, indent=1).encode("utf-8"))

    def _debug_requests(self, query: str) -> None:
        """Flight-recorder evidence dump (utils/flightrecorder.py): the
        slowest and the failed requests with tier/hedge/failover/GCM
        accounting. ``?n=K`` bounds both lists, ``?slowest=K`` returns
        just the K slowest completed records, ``?trace=<id>`` filters to
        one trace's records (404 when nothing retained carries it — the
        fleet stitcher's per-member query); 400 on a malformed count, 404
        while ``flight.enabled`` is off."""
        import json

        recorder = getattr(self.rsm, "flight_recorder", None)
        if recorder is None or not recorder.enabled:
            self._reply(404, b"flight recorder disabled")
            return
        # keep_blank_values: an explicit empty ?n= is a malformed request
        # (400), not an absent parameter.
        params = parse_qs(query, keep_blank_values=True, strict_parsing=False)

        def count_of(name: str):
            if name not in params:
                return None
            raw = params[name][0]
            # Strict ASCII-digit grammar (the Content-Length precedent).
            if not raw or not all(c in "0123456789" for c in raw) or int(raw) < 1:
                raise ValueError(f"expected ?{name}=<positive integer>")
            return int(raw)

        try:
            limit = count_of("n")
            slowest = count_of("slowest")
            trace = params["trace"][0] if "trace" in params else None
            if trace is not None and not trace:
                raise ValueError("expected ?trace=<trace id>")
            status = self.rsm.flight_status(
                limit=limit, trace=trace, slowest=slowest
            )
        except Exception as exc:  # noqa: BLE001 — boundary translation
            self._fail(exc)
            return
        self._reply(200, json.dumps(status, indent=1).encode("utf-8"))

    def _debug_timeline(self) -> None:
        """Device-scheduler timeline ring (metrics/timeline.py): merged
        launches with full scheduler context, the clock-epoch pin, and
        counters. 404 while ``timeline.enabled`` is off — an absent ring
        must read as "not armed", never as "the device was idle"."""
        import json

        timeline = getattr(self.rsm, "timeline", None)
        if timeline is None or not timeline.enabled:
            self._reply(404, b"timeline recorder disabled")
            return
        try:
            status = self.rsm.timeline_status()
        except Exception as exc:  # noqa: BLE001 — boundary translation
            self._fail(exc)
            return
        self._reply(200, json.dumps(status, indent=1).encode("utf-8"))

    def _fleet_telemetry(self, query: str) -> None:
        """Fleet telemetry (fleet/telemetry.py): this member's metric
        samples, or — with ``?aggregate=1`` — the whole membership view
        merged into one fleet-wide scrape."""
        import json

        if getattr(self.rsm, "fleet_telemetry", None) is None:
            self._reply(404, b"fleet mode disabled")
            return
        params = parse_qs(query, keep_blank_values=False, strict_parsing=False)
        aggregate = params.get("aggregate", ["0"])[0] in ("1", "true")
        try:
            payload = self.rsm.fleet_telemetry_payload(aggregate=aggregate)
        except Exception as exc:  # noqa: BLE001 — boundary translation
            self._fail(exc)
            return
        self._reply(200, json.dumps(payload, indent=1).encode("utf-8"))

    def _fleet_gossip(self) -> None:
        """One SWIM membership exchange: merge the sender's JSON view,
        answer with ours. Not admission-gated (see module docstring)."""
        import json

        serve = getattr(self.rsm, "fleet_gossip", None)
        if serve is None or getattr(self.rsm, "gossip_agent", None) is None:
            self._reply(404, b"fleet gossip disabled")
            return
        try:
            body = self._body()
        except Exception as exc:  # noqa: BLE001 — body-framing failure
            self._fail(exc)
            self.close_connection = True
            return
        try:
            with contextlib.closing(body):
                payload = json.loads(body.read())
                if not isinstance(payload, dict):
                    raise ValueError("gossip payload must be a JSON object")
                view = serve(payload)
        except Exception as exc:  # noqa: BLE001 — boundary translation
            self._fail(exc)
            return
        self._reply(200, json.dumps(view).encode("utf-8"))

    def do_POST(self) -> None:
        if self.path in ("/fleet/gossip", "/v1/fleet/gossip"):
            self._fleet_gossip()
            return
        routes = {
            "/v1/copy": self._copy,
            "/v1/fetch": self._fetch,
            "/v1/fetch-index": self._fetch_index,
            "/v1/delete": self._delete,
        }
        handler = routes.get(self.path)
        if handler is None:
            self._reply(404, b"no such endpoint")
            return
        # Admission gate FIRST — an overloaded sidecar sheds before reading
        # (and spooling) the request body. The unread body desyncs the
        # keep-alive framing, so a shed reply also drops the connection.
        admission = getattr(self.rsm, "admission", None)
        tracer = getattr(self.rsm, "tracer", NOOP_TRACER)
        # Optional tenant identity: engages the controller's per-tenant
        # fair share at saturation (absent header = legacy behavior).
        tenant = self.headers.get("x-tenant") or None
        if admission is not None:
            try:
                admission.acquire(self.path, tenant=tenant)
            except AdmissionRejectedException as exc:
                tracer.event("admission.shed", path=self.path, tenant=tenant or "")
                self._fail(exc)
                self.close_connection = True
                return
        try:
            self._handle_admitted(handler, tracer)
        finally:
            if admission is not None:
                admission.release(tenant=tenant)

    def _handle_admitted(self, handler, tracer) -> None:
        try:
            body = self._body()
        except _BodyTooLarge:
            self._reply(413, b"request body exceeds MAX_BODY_BYTES")
            self.close_connection = True  # unread body left on the socket
            return
        except Exception as exc:  # noqa: BLE001 — body-framing failure
            # The request body was only partially consumed: the remaining
            # bytes would be parsed as the next request line, desyncing the
            # keep-alive connection. Answer, then drop the connection.
            self._fail(exc)
            self.close_connection = True
            return
        # Join the caller's trace (W3C traceparent header, sent by the JVM
        # shim or a Python client) and record the gateway leg as one span —
        # the span covers the streamed response too, so time-to-last-byte of
        # a fetch is the gateway span's extent. The caller's deadline
        # (x-deadline-ms, remaining budget) is adopted the same way; absent
        # one, the RSM's configured default applies. The scope covers the
        # streamed drain, so chunk fetches during the response also honor it.
        wire_deadline = parse_deadline_ms(self.headers.get(shimwire.DEADLINE_HEADER))
        recorder = getattr(self.rsm, "flight_recorder", NOOP_RECORDER)
        try:
            # The flight record spans the streamed drain too (like the span
            # and the deadline scope), so chunk-tier outcomes during the
            # response land on THIS request's record.
            with contextlib.closing(body), \
                    deadline_scope(wire_deadline), \
                    ensure_deadline(getattr(self.rsm, "default_deadline_s", None)) as deadline, \
                    tracer.continue_trace(
                        self.headers.get(shimwire.TRACEPARENT_HEADER)), \
                    tracer.span(
                        "gateway" + self.path.replace("/v1/", "."),
                        **(
                            {"deadline_ms": round(deadline.remaining_s() * 1000.0, 1)}
                            if deadline is not None else {}
                        ),
                    ) as span, \
                    recorder.request(
                        "gateway" + self.path.replace("/v1/", "."),
                        trace_id=span.trace_id if span else None,
                    ):
                handler(body)
        except _StreamAborted:
            # Response already committed; the only safe move is dropping
            # the connection so the client sees a truncated stream (the
            # shim maps that to RemoteStorageException).
            self.close_connection = True
        except Exception as exc:  # noqa: BLE001 — boundary translation
            self._fail(exc)

    def _copy(self, body) -> None:
        md = shimwire.decode_metadata(body)
        with tempfile.TemporaryDirectory(prefix="sidecar-http-copy-") as tmp:
            # Sections stream straight to files — a multi-GiB segment never
            # has to fit in sidecar RAM on top of the spooled request body.
            paths = shimwire.decode_sections_to_dir(body, tmp)
            for required in ("log_segment", "offset_index", "time_index",
                             "leader_epoch_index"):
                if paths[required] is None:
                    raise shimwire.ShimWireError(
                        f"missing required section {required}"
                    )
            if paths["producer_snapshot"] is None:
                # KIP-405 requires the snapshot; tolerate shims for older
                # brokers by materializing an empty one, like the reference
                # e2e fixtures do.
                p = pathlib.Path(tmp) / "producer_snapshot"
                p.write_bytes(b"")
                paths["producer_snapshot"] = p
            data = LogSegmentData(
                log_segment=paths["log_segment"],
                offset_index=paths["offset_index"],
                time_index=paths["time_index"],
                producer_snapshot_index=paths["producer_snapshot"],
                transaction_index=paths["transaction_index"],
                leader_epoch_index=paths["leader_epoch_index"].read_bytes(),
            )
            custom = self.rsm.copy_log_segment_data(md, data)
        if custom:
            self._reply(200, bytes(custom))
        else:
            self._reply(204)

    def _fetch(self, body) -> None:
        md = shimwire.decode_metadata(body)
        start, end = shimwire.decode_fetch_tail(body)
        self._reply_stream(self.rsm.fetch_log_segment(md, start, end))

    def _fetch_index(self, body) -> None:
        md = shimwire.decode_metadata(body)
        name = shimwire.decode_index_type(body)
        try:
            index_type = IndexType[name]
        except KeyError:
            raise shimwire.ShimWireError(f"unknown index type {name!r}") from None
        self._reply_stream(self.rsm.fetch_index(md, index_type))

    def _delete(self, body) -> None:
        self.rsm.delete_log_segment_data(shimwire.decode_metadata(body))
        self._reply(204)


class _BoundedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer handling connections on a BOUNDED worker pool.

    The stock server spawns one unbounded thread per connection, so a fleet
    instance under fan-in (brokers + peer forwards) multiplies stacks
    without limit. Here connections are accepted eagerly (cheap) and handed
    to a fixed executor (`sidecar.http.max.workers`); excess connections
    queue in the executor until a worker frees up — bounded memory, and the
    admission controller still sheds the work itself."""

    def __init__(self, server_address, handler_class, max_workers: int):
        super().__init__(server_address, handler_class)
        self.max_workers = max_workers
        self._executor = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="sidecar-http"
        )

    def process_request(self, request, client_address):
        try:
            self._executor.submit(
                self.process_request_thread, request, client_address
            )
        except RuntimeError:  # executor shut down mid-accept
            self.shutdown_request(request)

    def server_close(self):
        try:
            super().server_close()
        finally:
            self._executor.shutdown(wait=False, cancel_futures=True)


class SidecarHttpGateway:
    def __init__(
        self,
        rsm,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        max_workers: Optional[int] = None,
    ):
        handler = type("BoundHandler", (_Handler,), {"rsm": rsm})
        if max_workers is None:
            max_workers = getattr(rsm, "sidecar_http_max_workers", 32)
        self._server = _BoundedThreadingHTTPServer((host, port), handler, max_workers)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def max_workers(self) -> int:
        return self._server.max_workers

    def start(self) -> "SidecarHttpGateway":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sidecar-http", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
