"""Per-segment encryption metadata stored in the manifest.

Reference: core/.../manifest/SegmentEncryptionMetadataV1.java (IV_SIZE = 12 at
:30; fields `dataKey` — the AES-256 DEK, RSA-enveloped in JSON — and `aad`).
"""

from __future__ import annotations

import dataclasses

IV_SIZE = 12


@dataclasses.dataclass(frozen=True)
class SegmentEncryptionMetadataV1:
    data_key: bytes  # raw AES-256 key bytes (32)
    aad: bytes

    @property
    def iv_size(self) -> int:
        return IV_SIZE
