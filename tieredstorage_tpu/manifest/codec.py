"""Compact binary codec for lists of transformed chunk sizes.

Wire-compatible with the reference's encoding
(core/.../manifest/index/serde/ChunkSizesBinaryCodec.java:98-203; layout doc
:63-96): big-endian `[count:4][base:4][bytesPerValue:1][(count-1)*bpv][last:4]`,
where base = min over all-but-last values and each stored value is (v - base)
in bytesPerValue bytes. Zero values -> count only; one value -> count + value.

Implemented vectorized with numpy (the reference loops per value): the de-based
value array is rendered to its big-endian byte matrix in one shot.
"""

from __future__ import annotations

import struct
from typing import Sequence

import numpy as np


def encode_chunk_sizes(values: Sequence[int]) -> bytes:
    count = len(values)
    if count == 0:
        return struct.pack(">i", 0)
    last = int(values[-1])
    if last < 0:
        raise ValueError("Values cannot be negative")
    if count == 1:
        return struct.pack(">ii", 1, last)

    body = np.asarray(values[:-1], dtype=np.int64)
    if (body < 0).any():
        raise ValueError("Values cannot be negative")
    if (body > 0x7FFFFFFF).any() or last > 0x7FFFFFFF:
        raise ValueError("Values must fit in a signed 32-bit int")
    base = int(body.min())
    debased = (body - base).astype(np.uint32)
    max_debased = int(debased.max())
    bytes_per_value = next(b for b in (1, 2, 3, 4) if max_debased <= (1 << (8 * b)) - 1)

    # Big-endian byte matrix of all de-based values, then keep the low
    # `bytes_per_value` columns.
    byte_matrix = debased[:, None] >> np.array([24, 16, 8, 0], dtype=np.uint32)[None, :]
    byte_matrix = (byte_matrix & 0xFF).astype(np.uint8)[:, 4 - bytes_per_value :]

    return (
        struct.pack(">iiB", count, base, bytes_per_value)
        + byte_matrix.tobytes()
        + struct.pack(">i", last)
    )


def decode_chunk_sizes(data: bytes) -> list[int]:
    (count,) = struct.unpack_from(">i", data, 0)
    if count == 0:
        return []
    if count == 1:
        (value,) = struct.unpack_from(">i", data, 4)
        return [value]

    base, bytes_per_value = struct.unpack_from(">iB", data, 4)
    offset = 4 + 4 + 1
    n_body = count - 1
    raw = np.frombuffer(data, dtype=np.uint8, count=n_body * bytes_per_value, offset=offset)
    byte_matrix = raw.reshape(n_body, bytes_per_value).astype(np.uint32)
    shifts = np.arange(bytes_per_value - 1, -1, -1, dtype=np.uint32) * 8
    body = (byte_matrix << shifts[None, :]).sum(axis=1, dtype=np.uint32).astype(np.int64) + base
    (last,) = struct.unpack_from(">i", data, offset + n_body * bytes_per_value)
    return [int(v) for v in body] + [last]
