"""Positions and sizes of Kafka index files inside the concatenated `.indexes` blob.

Reference: core/.../manifest/{SegmentIndexes.java:23-32, SegmentIndexesV1.java:27-130,
SegmentIndexesV1Builder.java:28-63, SegmentIndexV1.java:26-76}. The five index
types are OFFSET, TIMESTAMP, PRODUCER_SNAPSHOT, LEADER_EPOCH, TRANSACTION;
transaction is optional, the other four are mandatory.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from tieredstorage_tpu.storage.core import BytesRange


class IndexType(enum.Enum):
    """Mirror of the KIP-405 RemoteStorageManager.IndexType enum."""

    OFFSET = "offset"
    TIMESTAMP = "timestamp"
    PRODUCER_SNAPSHOT = "producerSnapshot"
    LEADER_EPOCH = "leaderEpoch"
    TRANSACTION = "transaction"


MANDATORY_INDEX_TYPES = (
    IndexType.OFFSET,
    IndexType.TIMESTAMP,
    IndexType.PRODUCER_SNAPSHOT,
    IndexType.LEADER_EPOCH,
)


@dataclasses.dataclass(frozen=True)
class SegmentIndexV1:
    position: int
    size: int

    def range(self) -> BytesRange:
        return BytesRange.of_from_position_and_size(self.position, self.size)


@dataclasses.dataclass(frozen=True)
class SegmentIndexesV1:
    offset: SegmentIndexV1
    timestamp: SegmentIndexV1
    producer_snapshot: SegmentIndexV1
    leader_epoch: SegmentIndexV1
    transaction: Optional[SegmentIndexV1]

    def segment_index(self, index_type: IndexType) -> Optional[SegmentIndexV1]:
        return {
            IndexType.OFFSET: self.offset,
            IndexType.TIMESTAMP: self.timestamp,
            IndexType.PRODUCER_SNAPSHOT: self.producer_snapshot,
            IndexType.LEADER_EPOCH: self.leader_epoch,
            IndexType.TRANSACTION: self.transaction,
        }[index_type]

    def all_indexes(self) -> tuple[Optional[SegmentIndexV1], ...]:
        """Every slot in wire order (transaction may be None); the scrubber
        sums sizes over this to know the expected `.indexes` object size."""
        return (
            self.offset, self.timestamp, self.producer_snapshot,
            self.leader_epoch, self.transaction,
        )

    @property
    def total_size(self) -> int:
        return sum(si.size for si in self.all_indexes() if si is not None)

    def to_json(self) -> dict:
        def one(si: Optional[SegmentIndexV1]):
            return None if si is None else {"position": si.position, "size": si.size}

        return {
            "offset": one(self.offset),
            "timestamp": one(self.timestamp),
            "producerSnapshot": one(self.producer_snapshot),
            "leaderEpoch": one(self.leader_epoch),
            "transaction": one(self.transaction),
        }

    @staticmethod
    def from_json(obj: dict) -> "SegmentIndexesV1":
        def one(v) -> Optional[SegmentIndexV1]:
            return None if v is None else SegmentIndexV1(v["position"], v["size"])

        return SegmentIndexesV1(
            offset=one(obj["offset"]),
            timestamp=one(obj["timestamp"]),
            producer_snapshot=one(obj["producerSnapshot"]),
            leader_epoch=one(obj["leaderEpoch"]),
            transaction=one(obj.get("transaction")),
        )


class SegmentIndexesV1Builder:
    """Accumulates indexes in upload order, tracking the running position.

    Reference: core/.../manifest/SegmentIndexesV1Builder.java:28-63 (requires
    the 4 mandatory types at build()).
    """

    def __init__(self) -> None:
        self._position = 0
        self._indexes: dict[IndexType, SegmentIndexV1] = {}

    def add(self, index_type: IndexType, size: int) -> "SegmentIndexesV1Builder":
        if index_type in self._indexes:
            raise ValueError(f"Index {index_type.name} already added")
        if size < 0:
            raise ValueError(f"Index size must be non-negative, {size} given")
        self._indexes[index_type] = SegmentIndexV1(self._position, size)
        self._position += size
        return self

    @property
    def total_size(self) -> int:
        return self._position

    def build(self) -> SegmentIndexesV1:
        missing = [t.name for t in MANDATORY_INDEX_TYPES if t not in self._indexes]
        if missing:
            raise ValueError(f"Missing mandatory index types: {missing}")
        return SegmentIndexesV1(
            offset=self._indexes[IndexType.OFFSET],
            timestamp=self._indexes[IndexType.TIMESTAMP],
            producer_snapshot=self._indexes[IndexType.PRODUCER_SNAPSHOT],
            leader_epoch=self._indexes[IndexType.LEADER_EPOCH],
            transaction=self._indexes.get(IndexType.TRANSACTION),
        )
