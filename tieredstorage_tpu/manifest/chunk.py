"""Chunk value object.

Reference: core/src/main/java/io/aiven/kafka/tieredstorage/Chunk.java
(`id, originalPosition, originalSize, transformedPosition, transformedSize`;
`range()` returns the transformed-side BytesRange, Chunk.java:62-64).
"""

from __future__ import annotations

import dataclasses

from tieredstorage_tpu.storage.core import BytesRange


@dataclasses.dataclass(frozen=True)
class Chunk:
    id: int
    original_position: int
    original_size: int
    transformed_position: int
    transformed_size: int

    def range(self) -> BytesRange:
        """Byte range of this chunk on the transformed (stored) side."""
        return BytesRange.of_from_position_and_size(self.transformed_position, self.transformed_size)

    def original_range(self) -> BytesRange:
        """Byte range of this chunk on the original (plaintext) side."""
        return BytesRange.of_from_position_and_size(self.original_position, self.original_size)
