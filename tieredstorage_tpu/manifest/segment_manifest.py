"""Segment manifest V1 + JSON serde, wire-compatible with the reference.

JSON shape (reference: core/.../manifest/SegmentManifest.java:33-36 — version
discriminator "1"; SegmentManifestV1.java:31-130):

    {
      "version": "1",
      "chunkIndex": {"type": "fixed"|"variable", ...},
      "segmentIndexes": {"offset": {...}, ..., "transaction": null},
      "compression": bool,
      "encryption": {"dataKey": "<keyId>:<b64>", "aad": "<b64>"},   # optional
      "remoteLogSegmentMetadata": {...}                             # write-only
    }

The DEK in `encryption.dataKey` is RSA-enveloped during serialization
(reference: core/.../manifest/serde/{EncryptionSerdeModule,DataKeySerializer,
DataKeyDeserializer}.java) — callers pass encoder/decoder hooks so this module
stays crypto-free.
"""

from __future__ import annotations

import base64
import dataclasses
import json
from typing import Callable, Optional

from tieredstorage_tpu.manifest.chunk_index import (
    ChunkIndex,
    chunk_index_from_json,
    chunk_index_to_json,
)
from tieredstorage_tpu.manifest.encryption_metadata import SegmentEncryptionMetadataV1
from tieredstorage_tpu.manifest.segment_indexes import SegmentIndexesV1
from tieredstorage_tpu.metadata import RemoteLogSegmentMetadata

# Hook signatures: encode raw DEK bytes -> "keyId:base64" string and back.
DataKeyEncoder = Callable[[bytes], str]
DataKeyDecoder = Callable[[str], bytes]


@dataclasses.dataclass(frozen=True)
class SegmentManifestV1:
    chunk_index: ChunkIndex
    segment_indexes: SegmentIndexesV1
    compression: bool
    encryption: Optional[SegmentEncryptionMetadataV1] = None
    remote_log_segment_metadata: Optional[RemoteLogSegmentMetadata] = None
    # Extension over the reference schema: identifies which codec produced the
    # compressed chunks ("zstd" = reference-compatible; TPU-native codecs add
    # their own ids). Absent/None means zstd, so reference manifests parse
    # unchanged and manifests this framework writes with zstd stay readable
    # by the reference.
    compression_codec: Optional[str] = None
    # Extension: CRC32C of each stored (transformed) chunk, aligned with the
    # chunk index, written when `scrub.checksums.enabled` — the background
    # scrubber's at-rest integrity ground truth. Absent on reference
    # manifests (they rely on the object store's checksums alone).
    chunk_checksums: Optional[list[int]] = None


def manifest_to_json(
    manifest: SegmentManifestV1,
    data_key_encoder: Optional[DataKeyEncoder] = None,
) -> str:
    obj: dict = {
        "version": "1",
        "chunkIndex": chunk_index_to_json(manifest.chunk_index),
        "segmentIndexes": manifest.segment_indexes.to_json(),
        "compression": manifest.compression,
    }
    if manifest.compression_codec and manifest.compression_codec != "zstd":
        obj["compressionCodec"] = manifest.compression_codec
    if manifest.chunk_checksums is not None:
        obj["chunkChecksums"] = base64.b64encode(
            b"".join(c.to_bytes(4, "big") for c in manifest.chunk_checksums)
        ).decode("ascii")
    if manifest.encryption is not None:
        if data_key_encoder is None:
            raise ValueError("Manifest has encryption metadata but no data key encoder given")
        obj["encryption"] = {
            "dataKey": data_key_encoder(manifest.encryption.data_key),
            "aad": base64.b64encode(manifest.encryption.aad).decode("ascii"),
        }
    if manifest.remote_log_segment_metadata is not None:
        obj["remoteLogSegmentMetadata"] = manifest.remote_log_segment_metadata.to_json()
    return json.dumps(obj)


def manifest_from_json(
    data: str | bytes,
    data_key_decoder: Optional[DataKeyDecoder] = None,
) -> SegmentManifestV1:
    obj = json.loads(data)
    version = obj.get("version")
    if version != "1":
        raise ValueError(f"Unsupported manifest version: {version!r}")
    encryption = None
    if obj.get("encryption") is not None:
        enc = obj["encryption"]
        if data_key_decoder is None:
            raise ValueError("Manifest has encryption metadata but no data key decoder given")
        encryption = SegmentEncryptionMetadataV1(
            data_key=data_key_decoder(enc["dataKey"]),
            aad=base64.b64decode(enc["aad"]),
        )
    checksums = None
    if obj.get("chunkChecksums") is not None:
        raw = base64.b64decode(obj["chunkChecksums"])
        if len(raw) % 4:
            raise ValueError(f"chunkChecksums length {len(raw)} is not a multiple of 4")
        checksums = [
            int.from_bytes(raw[i : i + 4], "big") for i in range(0, len(raw), 4)
        ]
    return SegmentManifestV1(
        chunk_index=chunk_index_from_json(obj["chunkIndex"]),
        segment_indexes=SegmentIndexesV1.from_json(obj["segmentIndexes"]),
        compression=bool(obj["compression"]),
        encryption=encryption,
        remote_log_segment_metadata=None,  # write-only field, like the reference
        compression_codec=obj.get("compressionCodec"),
        chunk_checksums=checksums,
    )
