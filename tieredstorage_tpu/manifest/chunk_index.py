"""Chunk indexes: map original-byte offsets to stored (transformed) chunks.

Behavior parity with the reference's ChunkIndex family
(core/.../manifest/index/ChunkIndex.java:28-54, AbstractChunkIndex.java,
FixedSizeChunkIndex.java:26-56, VariableSizeChunkIndex.java:29-53, and the
streaming builders), with the same JSON shape (`type` discriminator
"fixed"/"variable", `transformedChunks` as base64 of the binary codec).

Redesigned lookup: the reference linear-scans chunks per offset
(AbstractChunkIndex.findChunkForOriginalOffset:75-108). Here original
positions are arithmetic (`i * original_chunk_size`) so offset->chunk id is
O(1), and transformed positions come from a numpy prefix sum computed once —
the same array doubles as the device-side offset table for batched TPU
detransforms.
"""

from __future__ import annotations

import abc
import base64
from typing import Sequence

import numpy as np

from tieredstorage_tpu.manifest.chunk import Chunk
from tieredstorage_tpu.manifest.codec import decode_chunk_sizes, encode_chunk_sizes
from tieredstorage_tpu.storage.core import BytesRange

_INT_MAX = 0x7FFFFFFF


def _check_positive(value: int, name: str) -> None:
    if value <= 0:
        raise ValueError(f"{name} must be positive, {value} given")


def _check_non_negative(value: int, name: str) -> None:
    if value < 0:
        raise ValueError(f"{name} must be non-negative, {value} given")


class ChunkIndex(abc.ABC):
    """Common offset math for fixed/variable indexes.

    Semantics (same as reference): original chunks are `original_chunk_size`
    bytes except the final one; an empty file still materializes one zero
    chunk; offsets at/after `original_file_size` map to None.
    """

    def __init__(self, original_chunk_size: int, original_file_size: int, chunk_count: int):
        _check_positive(original_chunk_size, "Original chunk size")
        _check_non_negative(original_file_size, "Original file size")
        self.original_chunk_size = original_chunk_size
        self.original_file_size = original_file_size
        self.chunk_count = chunk_count
        # Transformed start offsets: prefix sum over transformed sizes.
        sizes = self.transformed_chunk_sizes()
        self._transformed_starts = np.concatenate(
            ([0], np.cumsum(np.asarray(sizes, dtype=np.int64)))
        )

    # --- subclass surface ---
    @abc.abstractmethod
    def transformed_chunk_sizes(self) -> np.ndarray:
        """int64[chunk_count] of transformed sizes."""

    # --- common math ---
    def _original_size_of(self, chunk_id: int) -> int:
        if chunk_id == self.chunk_count - 1:
            return self.original_file_size - (self.chunk_count - 1) * self.original_chunk_size
        return self.original_chunk_size

    def _chunk_at(self, chunk_id: int) -> Chunk:
        return Chunk(
            id=chunk_id,
            original_position=chunk_id * self.original_chunk_size,
            original_size=self._original_size_of(chunk_id),
            transformed_position=int(self._transformed_starts[chunk_id]),
            transformed_size=int(
                self._transformed_starts[chunk_id + 1] - self._transformed_starts[chunk_id]
            ),
        )

    def find_chunk_for_original_offset(self, offset: int) -> Chunk | None:
        _check_non_negative(offset, "Offset")
        if offset >= self.original_file_size:  # also covers empty files
            return None
        return self._chunk_at(offset // self.original_chunk_size)

    def chunks_for_range(self, bytes_range: BytesRange) -> list[Chunk]:
        if self.original_file_size == 0 or bytes_range.from_position >= self.original_file_size:
            return []
        first = bytes_range.from_position // self.original_chunk_size
        last_offset = min(bytes_range.to_position, self.original_file_size - 1)
        last = last_offset // self.original_chunk_size
        return [self._chunk_at(i) for i in range(first, last + 1)]

    def chunks(self) -> list[Chunk]:
        if self.chunk_count == 0:
            return [Chunk(0, 0, 0, 0, 0)]
        return [self._chunk_at(i) for i in range(self.chunk_count)]

    @property
    def total_transformed_size(self) -> int:
        return int(self._transformed_starts[-1])

    def transformed_start_offsets(self) -> np.ndarray:
        """int64[chunk_count+1] prefix-sum table (device-shippable)."""
        return self._transformed_starts


class FixedSizeChunkIndex(ChunkIndex):
    """All transformed chunks share one size except the final one.

    Produced when no compression runs (identity or encryption-only transforms).
    Reference: core/.../manifest/index/FixedSizeChunkIndex.java:26-56.
    """

    def __init__(
        self,
        original_chunk_size: int,
        original_file_size: int,
        transformed_chunk_size: int,
        final_transformed_chunk_size: int,
    ):
        _check_positive(original_chunk_size, "Original chunk size")
        _check_non_negative(original_file_size, "Original file size")
        _check_non_negative(transformed_chunk_size, "Transformed chunk size")
        _check_non_negative(final_transformed_chunk_size, "Final transformed chunk size")
        self.transformed_chunk_size = transformed_chunk_size
        self.final_transformed_chunk_size = final_transformed_chunk_size
        count = -(-original_file_size // original_chunk_size)  # ceil
        self._count = count
        super().__init__(original_chunk_size, original_file_size, count)

    def transformed_chunk_sizes(self) -> np.ndarray:
        sizes = np.full(self._count, self.transformed_chunk_size, dtype=np.int64)
        if self._count:
            sizes[-1] = self.final_transformed_chunk_size
        return sizes

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, FixedSizeChunkIndex)
            and self.original_chunk_size == other.original_chunk_size
            and self.original_file_size == other.original_file_size
            and self.transformed_chunk_size == other.transformed_chunk_size
            and self.final_transformed_chunk_size == other.final_transformed_chunk_size
        )

    def __repr__(self) -> str:
        return (
            f"FixedSizeChunkIndex(originalChunkSize={self.original_chunk_size}, "
            f"originalFileSize={self.original_file_size}, "
            f"transformedChunkSize={self.transformed_chunk_size}, "
            f"finalTransformedChunkSize={self.final_transformed_chunk_size})"
        )


class VariableSizeChunkIndex(ChunkIndex):
    """Transformed chunk sizes vary (compression); stored via the binary codec.

    Reference: core/.../manifest/index/VariableSizeChunkIndex.java:29-53.
    """

    def __init__(
        self,
        original_chunk_size: int,
        original_file_size: int,
        transformed_chunks: Sequence[int],
    ):
        if not transformed_chunks:
            raise ValueError("transformedChunks cannot be empty")
        self.transformed_chunks = [int(v) for v in transformed_chunks]
        super().__init__(original_chunk_size, original_file_size, len(self.transformed_chunks))

    def transformed_chunk_sizes(self) -> np.ndarray:
        return np.asarray(self.transformed_chunks, dtype=np.int64)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, VariableSizeChunkIndex)
            and self.original_chunk_size == other.original_chunk_size
            and self.original_file_size == other.original_file_size
            and self.transformed_chunks == other.transformed_chunks
        )

    def __repr__(self) -> str:
        return (
            f"VariableSizeChunkIndex(originalChunkSize={self.original_chunk_size}, "
            f"originalFileSize={self.original_file_size}, "
            f"transformedChunks={len(self.transformed_chunks)} values)"
        )


# --- JSON serde (wire-compatible with Jackson's output) ---

def chunk_index_to_json(index: ChunkIndex) -> dict:
    if isinstance(index, FixedSizeChunkIndex):
        return {
            "type": "fixed",
            "originalChunkSize": index.original_chunk_size,
            "originalFileSize": index.original_file_size,
            "transformedChunkSize": index.transformed_chunk_size,
            "finalTransformedChunkSize": index.final_transformed_chunk_size,
        }
    if isinstance(index, VariableSizeChunkIndex):
        return {
            "type": "variable",
            "originalChunkSize": index.original_chunk_size,
            "originalFileSize": index.original_file_size,
            "transformedChunks": base64.b64encode(
                encode_chunk_sizes(index.transformed_chunks)
            ).decode("ascii"),
        }
    raise TypeError(f"Unknown chunk index type: {type(index)!r}")


def chunk_index_from_json(obj: dict) -> ChunkIndex:
    kind = obj.get("type")
    if kind == "fixed":
        return FixedSizeChunkIndex(
            obj["originalChunkSize"],
            obj["originalFileSize"],
            obj["transformedChunkSize"],
            obj["finalTransformedChunkSize"],
        )
    if kind == "variable":
        sizes = decode_chunk_sizes(base64.b64decode(obj["transformedChunks"]))
        return VariableSizeChunkIndex(obj["originalChunkSize"], obj["originalFileSize"], sizes)
    raise ValueError(f"Unknown chunk index type id: {kind!r}")


# --- streaming builders ---

class _ChunkIndexBuilder(abc.ABC):
    """Streaming add/finish protocol used by the transform finisher.

    Reference: core/.../manifest/index/AbstractChunkIndexBuilder.java:39-77 —
    non-final chunks must be exactly `original_chunk_size` original bytes;
    `finish` seals the index with the final (possibly short) chunk.
    """

    def __init__(self, original_chunk_size: int, original_file_size: int):
        _check_positive(original_chunk_size, "Original chunk size")
        _check_non_negative(original_file_size, "Original file size")
        self.original_chunk_size = original_chunk_size
        self.original_file_size = original_file_size
        self._non_final_expected = max(0, -(-original_file_size // original_chunk_size) - 1)
        self._added = 0
        self._finished = False

    def add_chunk(self, transformed_size: int) -> None:
        if self._finished:
            raise RuntimeError("Index already finished")
        if self._added >= self._non_final_expected:
            raise RuntimeError(
                f"Cannot add more chunks: {self._non_final_expected} non-final chunks expected"
            )
        _check_non_negative(transformed_size, "Transformed chunk size")
        self._add(transformed_size)
        self._added += 1

    def finish(self, final_transformed_size: int) -> ChunkIndex:
        if self._finished:
            raise RuntimeError("Index already finished")
        if self._added != self._non_final_expected:
            raise RuntimeError(
                f"Expected {self._non_final_expected} non-final chunks, got {self._added}"
            )
        _check_non_negative(final_transformed_size, "Final transformed chunk size")
        self._finished = True
        return self._finish(final_transformed_size)

    @abc.abstractmethod
    def _add(self, transformed_size: int) -> None: ...

    @abc.abstractmethod
    def _finish(self, final_transformed_size: int) -> ChunkIndex: ...


class FixedSizeChunkIndexBuilder(_ChunkIndexBuilder):
    def __init__(self, original_chunk_size: int, original_file_size: int, transformed_chunk_size: int):
        super().__init__(original_chunk_size, original_file_size)
        _check_non_negative(transformed_chunk_size, "Transformed chunk size")
        self.transformed_chunk_size = transformed_chunk_size

    def _add(self, transformed_size: int) -> None:
        # Fixed index sanity check (reference FixedSizeChunkIndexBuilder):
        # every non-final transformed chunk must have the declared size.
        if transformed_size != self.transformed_chunk_size:
            raise ValueError(
                f"Transformed chunk size {transformed_size} != declared {self.transformed_chunk_size}"
            )

    def _finish(self, final_transformed_size: int) -> ChunkIndex:
        return FixedSizeChunkIndex(
            self.original_chunk_size,
            self.original_file_size,
            self.transformed_chunk_size,
            final_transformed_size,
        )


class VariableSizeChunkIndexBuilder(_ChunkIndexBuilder):
    def __init__(self, original_chunk_size: int, original_file_size: int):
        super().__init__(original_chunk_size, original_file_size)
        self._sizes: list[int] = []

    def _add(self, transformed_size: int) -> None:
        self._sizes.append(transformed_size)

    def _finish(self, final_transformed_size: int) -> ChunkIndex:
        return VariableSizeChunkIndex(
            self.original_chunk_size,
            self.original_file_size,
            self._sizes + [final_transformed_size],
        )
