"""Manifest + chunk-index data model (reference L4), wire-compatible.

JSON artifacts produced here are cross-readable with the reference's
`SegmentManifestV1` (version discriminator "1", chunk-index subtypes
"fixed"/"variable", base64 chunk-size codec). Reference:
core/src/main/java/io/aiven/kafka/tieredstorage/manifest/.
"""

from tieredstorage_tpu.manifest.chunk import Chunk
from tieredstorage_tpu.manifest.chunk_index import (
    ChunkIndex,
    FixedSizeChunkIndex,
    FixedSizeChunkIndexBuilder,
    VariableSizeChunkIndex,
    VariableSizeChunkIndexBuilder,
    chunk_index_from_json,
    chunk_index_to_json,
)
from tieredstorage_tpu.manifest.codec import decode_chunk_sizes, encode_chunk_sizes
from tieredstorage_tpu.manifest.segment_indexes import (
    IndexType,
    SegmentIndexesV1,
    SegmentIndexesV1Builder,
    SegmentIndexV1,
)
from tieredstorage_tpu.manifest.encryption_metadata import SegmentEncryptionMetadataV1
from tieredstorage_tpu.manifest.segment_manifest import (
    SegmentManifestV1,
    manifest_from_json,
    manifest_to_json,
)

__all__ = [
    "Chunk",
    "ChunkIndex",
    "FixedSizeChunkIndex",
    "FixedSizeChunkIndexBuilder",
    "VariableSizeChunkIndex",
    "VariableSizeChunkIndexBuilder",
    "chunk_index_from_json",
    "chunk_index_to_json",
    "decode_chunk_sizes",
    "encode_chunk_sizes",
    "IndexType",
    "SegmentIndexV1",
    "SegmentIndexesV1",
    "SegmentIndexesV1Builder",
    "SegmentEncryptionMetadataV1",
    "SegmentManifestV1",
    "manifest_from_json",
    "manifest_to_json",
]
