"""Batched canonical-Huffman encode/decode on device (the TPU codec core).

The reference compresses chunks on the JVM heap with zstd-jni
(core/.../transform/CompressionChunkEnumeration.java:50-63). A TPU has no
sequential match-finder, so this framework's device codec is an order-0
length-limited canonical Huffman coder designed around what the chip does
well, batched over whole chunk windows:

- encode: per-symbol (code, length) lookup is a per-row 256-entry gather,
  bit positions are one exclusive `cumsum`, and packing is two scatter-adds
  (contributions of one symbol never overlap in bits, so add == or).
- decode: block-parallel — the frame records the absolute bit offset of
  every JUMP_BLOCK-symbol block, so a [rows, blocks] lane grid scans
  symbols sequentially per block while all blocks decode in parallel
  (`lax.scan` over the in-block symbol index).

Codes are stored bit-reversed so the stream reads MSB-first; the canonical
(first_code, count, base, perm) tables per row make length detection a
15-way vectorized range test, no tree walk. Host-side table construction
(length-limited package-merge) lives in transform/thuff.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

#: Symbols per independently-decodable block (the frame stores one absolute
#: bit offset per block; 4 B per 4096 symbols ≈ 0.1% overhead).
JUMP_BLOCK = 4096

MAX_CODE_LEN = 15

#: Hard per-chunk cap of the v1 frame format: bit positions are int32
#: (worst case MAX_CODE_LEN bits/symbol -> 128 MiB * 15 < 2^31) and the
#: jump-table count is u16 (128 MiB / JUMP_BLOCK = 32768 <= 65535).
MAX_CHUNK_BYTES = 128 << 20


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def max_words(n_max: int) -> int:
    """Worst-case payload words for n_max symbols (15 bits each)."""
    return _ceil_div(n_max * MAX_CODE_LEN, 32) + 1


@functools.partial(jax.jit, static_argnames=("n_max",))
def encode_batch(
    data: jnp.ndarray,      # uint8[B, n_max], zero-padded past n_sym
    n_sym: jnp.ndarray,     # int32[B]
    codes_rev: jnp.ndarray, # int32[B, 256] bit-reversed canonical codes
    lengths: jnp.ndarray,   # int32[B, 256] code lengths (0 for absent syms)
    *,
    n_max: int,
):
    """Returns (words uint32[B, W], total_bits int32[B], jump int32[B, J]).

    jump[b, j] is the absolute bit offset of symbol j*JUMP_BLOCK — the
    per-block entry points the parallel decoder starts from."""
    batch = data.shape[0]
    idx = data.astype(jnp.int32)
    sym_len = jnp.take_along_axis(lengths, idx, axis=1)
    sym_code = jnp.take_along_axis(codes_rev, idx, axis=1).astype(jnp.uint32)
    valid = (
        jnp.arange(n_max, dtype=jnp.int32)[None, :] < n_sym[:, None]
    )
    sym_len = jnp.where(valid, sym_len, 0)

    end_bits = jnp.cumsum(sym_len, axis=1, dtype=jnp.int32)
    bitpos = end_bits - sym_len  # exclusive prefix sum
    total_bits = end_bits[:, -1]

    w = max_words(n_max)
    word_idx = bitpos >> 5
    shift = (bitpos & 31).astype(jnp.uint32)
    lo = sym_code << shift
    # code >> (32 - s); s == 0 must yield 0 (no spill into the next word).
    hi = jnp.where(
        shift == 0,
        jnp.uint32(0),
        sym_code >> (jnp.uint32(32) - jnp.where(shift == 0, 1, shift)),
    )
    rows = jnp.arange(batch, dtype=jnp.int32)[:, None]
    words = jnp.zeros((batch, w), jnp.uint32)
    words = words.at[rows, word_idx].add(lo, mode="drop")
    words = words.at[rows, word_idx + 1].add(hi, mode="drop")

    jump = bitpos[:, ::JUMP_BLOCK]
    return words, total_bits, jump


def _bitrev15(v: jnp.ndarray) -> jnp.ndarray:
    """Reverse the low 15 bits of a uint32 (result in the low 15 bits)."""
    v = ((v & 0x55555555) << 1) | ((v >> 1) & 0x55555555)
    v = ((v & 0x33333333) << 2) | ((v >> 2) & 0x33333333)
    v = ((v & 0x0F0F0F0F) << 4) | ((v >> 4) & 0x0F0F0F0F)
    v = ((v & 0x00FF00FF) << 8) | ((v >> 8) & 0x00FF00FF)
    v = (v << 16) | (v >> 16)
    return v >> 17  # 32-bit reversal, keep the top 15 of the reversed low 15


@functools.partial(jax.jit, static_argnames=("n_max",))
def decode_batch(
    words: jnp.ndarray,       # uint32[B, W]
    jump: jnp.ndarray,        # int32[B, J] absolute bit offsets per block
    first_code: jnp.ndarray,  # int32[B, 16] canonical first code per length
    counts: jnp.ndarray,      # int32[B, 16] symbols per length
    base: jnp.ndarray,        # int32[B, 16] perm index of first sym per length
    perm: jnp.ndarray,        # int32[B, 256] symbols sorted by (len, sym)
    *,
    n_max: int,
):
    """Returns (symbols uint8[B, n_max_padded], final_bitpos int32[B, J]).

    Pad rows/tails are garbage; callers slice to their per-row n_sym.
    final_bitpos[b, j] is the bit position after block j's JUMP_BLOCK
    symbols — for full blocks it must equal jump[b, j+1] (and the frame's
    total bits for an exactly-full last block), which is the decoder's
    corruption check."""
    batch, w = words.shape
    n_blocks = jump.shape[1]
    l_axis = jnp.arange(1, MAX_CODE_LEN + 1, dtype=jnp.int32)  # [15]

    def step(bitpos, _):
        # bitpos int32[B, J]; extract a 15-bit MSB-first window per lane.
        widx = jnp.minimum(bitpos >> 5, w - 2)
        s = (bitpos & 31).astype(jnp.uint32)
        w0 = jnp.take_along_axis(words, widx, axis=1)
        w1 = jnp.take_along_axis(words, widx + 1, axis=1)
        window = (w0 >> s) | jnp.where(
            s == 0, jnp.uint32(0), w1 << (jnp.uint32(32) - jnp.maximum(s, 1))
        )
        u15 = _bitrev15(window & jnp.uint32(0x7FFF)).astype(jnp.int32)  # [B, J]
        # Length detection: the unique l with first[l] <= u15>>(15-l) < first+count.
        u_l = u15[:, :, None] >> (MAX_CODE_LEN - l_axis)[None, None, :]  # [B,J,15]
        f = jnp.take(first_code, l_axis, axis=1)[:, None, :]             # [B,1,15]
        c = jnp.take(counts, l_axis, axis=1)[:, None, :]
        ok = (u_l >= f) & (u_l < f + c)
        l_sel = jnp.argmax(ok, axis=2)  # [B, J] -> index into l_axis (l-1)
        u_sel = jnp.take_along_axis(u_l, l_sel[:, :, None], axis=2)[:, :, 0]
        f_sel = jnp.take_along_axis(
            jnp.broadcast_to(f, ok.shape), l_sel[:, :, None], axis=2
        )[:, :, 0]
        b_sel = jnp.take_along_axis(
            jnp.broadcast_to(
                jnp.take(base, l_axis, axis=1)[:, None, :], ok.shape
            ),
            l_sel[:, :, None],
            axis=2,
        )[:, :, 0]
        idx = jnp.clip(b_sel + u_sel - f_sel, 0, 255)
        sym = jnp.take_along_axis(perm, idx, axis=1).astype(jnp.uint8)
        return bitpos + l_sel + 1, sym

    final_bitpos, syms = jax.lax.scan(step, jump, None, length=JUMP_BLOCK)
    # [steps, B, J] -> [B, J, steps] -> [B, J*steps]
    return (
        syms.transpose(1, 2, 0).reshape(batch, n_blocks * JUMP_BLOCK),
        final_bitpos,
    )
