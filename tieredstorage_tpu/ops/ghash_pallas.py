"""Pallas TPU kernel for grouped-GHASH level 1.

The XLA formulation (ops/gcm.py `_ghash_grouped`) materializes 8 int8
bit-planes of the ciphertext in HBM — 8 bytes of traffic per payload byte —
before contracting them against the level-1 operand on the MXU. This kernel
reads the raw bytes once: a [R_T, K] uint8 tile lands in VMEM, the 8 planes
are extracted as in-register shifts/masks, and 8 f32 MXU matmuls accumulate
the 128 output bits (values bounded by K ≤ 2048 < 2^24, so f32 accumulation
is exact; the mod-2 reduction happens once at the end). HBM traffic drops to
read-bytes + write-nodes (~1.06 B/B).

Levels >= 2 stay in XLA: they touch 128x less data.

Replaces the per-chunk GHASH of the reference's JDK GCM cipher
(core/.../transform/EncryptionChunkEnumeration.java:66-81) together with
ops/gcm.py; wired behind the same preflight-and-fallback gate pattern as the
Pallas AES circuit (ops/aes_bitsliced._use_pallas_circuit).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: Rows of the flattened [B*G, K] level-1 matrix per grid step. 256 rows x
#: 2048 cols keeps the widened int32 tile (2 MiB — x is upcast before the
#: bit math, see _ghash_l1_kernel) + per-plane f32 operand (2 MiB) + the
#: f32 weight slice (1 MiB) well inside VMEM.
ROWS_PER_STEP = 256


_PREFLIGHT: list[bool] = []  # memoized per-process platform verdict


def _preflight_attempt() -> bool:
    import numpy as np

    rng = np.random.default_rng(0)
    k = 256
    data = rng.integers(0, 256, (ROWS_PER_STEP, k), dtype=np.uint8)
    w1 = rng.integers(0, 2, (8, k, 128), dtype=np.int8)
    planes = np.stack([(data >> p) & 1 for p in range(8)]).astype(np.int64)
    expect = (
        np.einsum("prk,pko->ro", planes, w1.astype(np.int64)) & 1
    ).astype(np.int8)
    with jax.ensure_compile_time_eval():
        got = jax.block_until_ready(
            ghash_level1_pallas(jnp.asarray(data), jnp.asarray(w1))
        )
        ok = bool(jnp.array_equal(got, expect))
    if not ok:  # pragma: no cover - platform-specific
        raise AssertionError(
            "unsupported: kernel output diverges from numpy reference"
        )
    return ok


def _preflight_ok() -> bool:
    """Compile and run the kernel once on a small tile, cross-checked
    against an exact numpy mod-2 reference. Any Mosaic lowering/runtime
    failure or mismatch degrades to the XLA level-1 path with a warning;
    transient relay failures are retried in place before the verdict is
    memoized (same contract as aes_bitsliced._pallas_preflight_ok, shared
    machinery in ops/_preflight.py; runs under ensure_compile_time_eval
    because the gate is consulted at trace time)."""
    import logging

    from tieredstorage_tpu.ops._preflight import run_preflight

    return run_preflight(
        _PREFLIGHT,
        _preflight_attempt,
        logging.getLogger(__name__),
        "Pallas GHASH kernel unavailable on this platform, "
        "falling back to the XLA level-1 path: %s",
    )


def use_pallas_ghash(rows: int, k: int) -> bool:
    """Shape eligibility for the level-1 kernel — pure host logic, no
    platform probe, so benchmarks and CPU-only CI can assert that the
    production window shapes tile onto the kernel. K must tile the 128-lane
    minor dimension and the row count must fill at least one grid step
    (`ghash_level1_pallas` pads shorter remainders internally; a sub-step
    batch would waste more than half the padded compute). The dispatch
    decision is `use_pallas_ghash(...) and pallas_ghash_available()` —
    shape preconditions hold regardless of forcing: an un-tiled K would
    fail Mosaic lowering, so forcing only overrides the platform check and
    the preflight, never validity."""
    return k > 0 and k % 128 == 0 and rows >= ROWS_PER_STEP


def pallas_ghash_available() -> bool:
    """Platform half of the gate: can (or must) the kernel run here?

    TIEREDSTORAGE_TPU_PALLAS_GHASH=0/1 overrides (read at trace time, like
    the AES gate); otherwise real TPUs only, preflight-verified."""
    import os

    forced = os.environ.get("TIEREDSTORAGE_TPU_PALLAS_GHASH")
    if forced is not None:
        return forced not in ("0", "false", "off")
    try:
        if jax.default_backend() not in ("tpu", "axon"):
            return False
    except Exception:
        return False
    return _preflight_ok()


def _ghash_l1_kernel(x_ref, w_ref, o_ref):
    """x_ref: VMEM uint8[R, K]; w_ref: VMEM int8[8, K, 128];
    o_ref: VMEM int8[R, 128]."""
    # Widen to int32 BEFORE the bit math: Mosaic on the v5e toolchain can
    # legalize neither i8 vector shifts (arith.shrui on vector<...xi8>) nor
    # direct u8/i8->f32 casts — both failed on the real chip, round 5.
    x = x_ref[:].astype(jnp.int32)
    acc = None
    for p in range(8):
        plane = ((x >> p) & 1).astype(jnp.float32)
        w_p = w_ref[p].astype(jnp.int32).astype(jnp.float32)
        part = jnp.dot(plane, w_p, preferred_element_type=jnp.float32)
        acc = part if acc is None else acc + part
    o_ref[:] = (acc.astype(jnp.int32) & 1).astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ghash_level1_pallas(
    data: jnp.ndarray, w1: jnp.ndarray, *, interpret: bool = False
) -> jnp.ndarray:
    """data uint8[R, K] (K the level-1 group byte width),
    w1 int8[8, K, 128] -> node bits int8[R, 128].

    Bit-exact drop-in for the XLA plane-stack + dot_general level 1 in
    `gcm._ghash_grouped`. R is padded to the ROWS_PER_STEP grid INSIDE the
    op (zero rows contract to zero node bits) and the result sliced back,
    so callers dispatch production window shapes as-is."""
    rows, k = data.shape
    if rows <= 0:
        raise ValueError("rows must be positive")
    if w1.shape != (8, k, 128):
        raise ValueError(f"weights {w1.shape} do not match K={k}")
    padded = -(-rows // ROWS_PER_STEP) * ROWS_PER_STEP
    if padded != rows:
        data = jnp.pad(data, ((0, padded - rows), (0, 0)))
    steps = padded // ROWS_PER_STEP
    out = pl.pallas_call(
        _ghash_l1_kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((ROWS_PER_STEP, k), lambda s: (s, 0)),
            pl.BlockSpec((8, k, 128), lambda s: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((ROWS_PER_STEP, 128), lambda s: (s, 0)),
        out_shape=jax.ShapeDtypeStruct((padded, 128), jnp.int8),
        interpret=interpret,
    )(data, w1)
    return out[:rows]
