"""Pallas TPU kernels for the grouped-GHASH reduction.

Two kernels, one per reduction strategy:

- **Level-1 kernel** (`ghash_level1_pallas`): the XLA formulation
  (ops/gcm.py `_ghash_grouped`) materializes 8 int8 bit-planes of the
  ciphertext in HBM — 8 bytes of traffic per payload byte — before
  contracting them against the level-1 operand on the MXU. This kernel
  reads the raw bytes once: a [R_T, K] uint8 tile lands in VMEM, the 8
  planes are extracted as in-register shifts/masks, and 8 f32 MXU matmuls
  accumulate the 128 output bits (values bounded by K ≤ 2048 < 2^24, so
  f32 accumulation is exact; the mod-2 reduction happens once at the end).
  HBM traffic drops to read-bytes + write-nodes (~1.06 B/B). Levels >= 2
  then run as the XLA grouped-power ladder — one HBM round trip of
  [B, G, 128] node bits per level.

- **Tree kernel** (`ghash_tree_pallas`, ISSUE 13): the ENTIRE reduction —
  level 1 AND every aggregation level above it — in one kernel. The grid
  walks each row tile's groups sequentially; a VMEM scratch accumulator
  carries the running T across groups and is folded by a precomputed
  multiply-by-H^k bit matrix between steps
  (``T = (T @ M_{H^k}) ^ node_g``, gf128.ghash_step_matrix), so the node
  bits of level 2+ NEVER materialize in HBM: the payload crosses HBM
  exactly once on the way in and [B, 128] final node bits on the way out.
  The trade: group g+1 of a row depends on group g, so only the row axis
  is parallel — the level-1 matmuls run at B(+pad) sublanes instead of
  the level-1 kernel's 256-row tiles. For the production window shapes
  (B=16 rows of 4 MiB) that exchanges MXU occupancy for zero inter-stage
  HBM traffic and a single-stage program; the next relay window decides
  the default with real numbers (TIEREDSTORAGE_TPU_PALLAS_GHASH_TREE=0
  keeps the level-1 kernel + XLA ladder for A/B).

Replaces the per-chunk GHASH of the reference's JDK GCM cipher
(core/.../transform/EncryptionChunkEnumeration.java:66-81) together with
ops/gcm.py; wired behind the same preflight-and-fallback gate pattern as the
Pallas AES circuit (ops/aes_bitsliced._use_pallas_circuit).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: Rows of the flattened [B*G, K] level-1 matrix per grid step. 256 rows x
#: 2048 cols keeps the widened int32 tile (2 MiB — x is upcast before the
#: bit math, see _ghash_l1_kernel) + per-plane f32 operand (2 MiB) + the
#: f32 weight slice (1 MiB) well inside VMEM.
ROWS_PER_STEP = 256


_PREFLIGHT: list[bool] = []  # memoized per-process platform verdict


def _preflight_attempt() -> bool:
    import numpy as np

    rng = np.random.default_rng(0)
    k = 256
    data = rng.integers(0, 256, (ROWS_PER_STEP, k), dtype=np.uint8)
    w1 = rng.integers(0, 2, (8, k, 128), dtype=np.int8)
    planes = np.stack([(data >> p) & 1 for p in range(8)]).astype(np.int64)
    expect = (
        np.einsum("prk,pko->ro", planes, w1.astype(np.int64)) & 1
    ).astype(np.int8)
    with jax.ensure_compile_time_eval():
        got = jax.block_until_ready(
            ghash_level1_pallas(jnp.asarray(data), jnp.asarray(w1))
        )
        ok = bool(jnp.array_equal(got, expect))
    if not ok:  # pragma: no cover - platform-specific
        raise AssertionError(
            "unsupported: kernel output diverges from numpy reference"
        )
    return ok


def _preflight_ok() -> bool:
    """Compile and run the kernel once on a small tile, cross-checked
    against an exact numpy mod-2 reference. Any Mosaic lowering/runtime
    failure or mismatch degrades to the XLA level-1 path with a warning;
    transient relay failures are retried in place before the verdict is
    memoized (same contract as aes_bitsliced._pallas_preflight_ok, shared
    machinery in ops/_preflight.py; runs under ensure_compile_time_eval
    because the gate is consulted at trace time)."""
    import logging

    from tieredstorage_tpu.ops._preflight import run_preflight

    return run_preflight(
        _PREFLIGHT,
        _preflight_attempt,
        logging.getLogger(__name__),
        "Pallas GHASH kernel unavailable on this platform, "
        "falling back to the XLA level-1 path: %s",
    )


def use_pallas_ghash(rows: int, k: int) -> bool:
    """Shape eligibility for the level-1 kernel — pure host logic, no
    platform probe, so benchmarks and CPU-only CI can assert that the
    production window shapes tile onto the kernel. K must tile the 128-lane
    minor dimension and the row count must fill at least one grid step
    (`ghash_level1_pallas` pads shorter remainders internally; a sub-step
    batch would waste more than half the padded compute). The dispatch
    decision is `use_pallas_ghash(...) and pallas_ghash_available()` —
    shape preconditions hold regardless of forcing: an un-tiled K would
    fail Mosaic lowering, so forcing only overrides the platform check and
    the preflight, never validity."""
    return k > 0 and k % 128 == 0 and rows >= ROWS_PER_STEP


def pallas_ghash_available() -> bool:
    """Platform half of the gate: can (or must) the kernel run here?

    TIEREDSTORAGE_TPU_PALLAS_GHASH=0/1 overrides (read at trace time, like
    the AES gate); otherwise real TPUs only, preflight-verified."""
    import os

    forced = os.environ.get("TIEREDSTORAGE_TPU_PALLAS_GHASH")
    if forced is not None:
        return forced not in ("0", "false", "off")
    try:
        if jax.default_backend() not in ("tpu", "axon"):
            return False
    except Exception:
        return False
    return _preflight_ok()


def _ghash_l1_kernel(x_ref, w_ref, o_ref):
    """x_ref: VMEM uint8[R, K]; w_ref: VMEM int8[8, K, 128];
    o_ref: VMEM int8[R, 128]."""
    # Widen to int32 BEFORE the bit math: Mosaic on the v5e toolchain can
    # legalize neither i8 vector shifts (arith.shrui on vector<...xi8>) nor
    # direct u8/i8->f32 casts — both failed on the real chip, round 5.
    x = x_ref[:].astype(jnp.int32)
    acc = None
    for p in range(8):
        plane = ((x >> p) & 1).astype(jnp.float32)
        w_p = w_ref[p].astype(jnp.int32).astype(jnp.float32)
        part = jnp.dot(plane, w_p, preferred_element_type=jnp.float32)
        acc = part if acc is None else acc + part
    o_ref[:] = (acc.astype(jnp.int32) & 1).astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ghash_level1_pallas(
    data: jnp.ndarray, w1: jnp.ndarray, *, interpret: bool = False
) -> jnp.ndarray:
    """data uint8[R, K] (K the level-1 group byte width),
    w1 int8[8, K, 128] -> node bits int8[R, 128].

    Bit-exact drop-in for the XLA plane-stack + dot_general level 1 in
    `gcm._ghash_grouped`. R is padded to the ROWS_PER_STEP grid INSIDE the
    op (zero rows contract to zero node bits) and the result sliced back,
    so callers dispatch production window shapes as-is."""
    rows, k = data.shape
    if rows <= 0:
        raise ValueError("rows must be positive")
    if w1.shape != (8, k, 128):
        raise ValueError(f"weights {w1.shape} do not match K={k}")
    padded = -(-rows // ROWS_PER_STEP) * ROWS_PER_STEP
    if padded != rows:
        data = jnp.pad(data, ((0, padded - rows), (0, 0)))
    steps = padded // ROWS_PER_STEP
    out = pl.pallas_call(
        _ghash_l1_kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((ROWS_PER_STEP, k), lambda s: (s, 0)),
            pl.BlockSpec((8, k, 128), lambda s: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((ROWS_PER_STEP, 128), lambda s: (s, 0)),
        out_shape=jax.ShapeDtypeStruct((padded, 128), jnp.int8),
        interpret=interpret,
    )(data, w1)
    return out[:rows]


# --------------------------------------------------------------- tree kernel

#: Rows per grid step of the tree kernel. The row axis only carries the GCM
#: batch (each row's groups are a sequential chain), so the tile is the f32
#: sublane minimum: VMEM per step stays at the widened int32 data tile
#: (8 x K x 4 B = 64 KiB at K=2048) + the int8 level-1 operand (2 MiB) +
#: one f32 plane operand (1 MiB) + the fold matrix and [8, 128] accumulator.
TREE_ROWS_PER_STEP = 8

_TREE_PREFLIGHT: list[bool] = []  # memoized per-process platform verdict


def use_pallas_ghash_tree(batch: int, groups: int, k_bytes: int) -> bool:
    """Shape eligibility for the fused tree kernel — pure host logic, no
    platform probe (same split-gate contract as `use_pallas_ghash`). The
    group byte width must tile the 128-lane minor dimension and fit the
    kernel's VMEM budget (the agg plan caps k at 128 blocks = 2048 bytes),
    and at least two groups must exist: a single-group reduction is already
    one level-1 pass with nothing to aggregate, so the tree buys nothing."""
    return (
        0 < k_bytes <= 2048
        and k_bytes % 128 == 0
        and groups >= 2
        and batch >= 1
    )


def _tree_preflight_attempt() -> bool:
    import numpy as np

    rng = np.random.default_rng(0)
    k, groups = 256, 3
    data = rng.integers(0, 256, (TREE_ROWS_PER_STEP, groups * k), dtype=np.uint8)
    w1 = rng.integers(0, 2, (8, k, 128), dtype=np.int8)
    step = rng.integers(0, 2, (128, 128), dtype=np.int8)
    acc = np.zeros((TREE_ROWS_PER_STEP, 128), dtype=np.int64)
    for g in range(groups):
        tile = data[:, g * k : (g + 1) * k]
        planes = np.stack([(tile >> p) & 1 for p in range(8)]).astype(np.int64)
        node = np.einsum("prk,pko->ro", planes, w1.astype(np.int64)) & 1
        acc = ((acc @ step.astype(np.int64)) & 1) ^ node if g else node
    expect = acc.astype(np.int8)
    with jax.ensure_compile_time_eval():
        got = jax.block_until_ready(
            ghash_tree_pallas(
                jnp.asarray(data), jnp.asarray(w1), jnp.asarray(step)
            )
        )
        ok = bool(jnp.array_equal(got, expect))
    if not ok:  # pragma: no cover - platform-specific
        raise AssertionError(
            "unsupported: tree kernel output diverges from numpy reference"
        )
    return ok


def _tree_preflight_ok() -> bool:
    """First-use compile-and-run of the tree kernel on a minimal shape,
    cross-checked against an exact numpy fold (same retry/memoization
    contract as `_preflight_ok`; a Mosaic failure degrades to the level-1
    kernel + XLA ladder, never aborts the caller's trace)."""
    import logging

    from tieredstorage_tpu.ops._preflight import run_preflight

    return run_preflight(
        _TREE_PREFLIGHT,
        _tree_preflight_attempt,
        logging.getLogger(__name__),
        "Pallas GHASH tree kernel unavailable on this platform, "
        "falling back to the level-1 kernel + XLA ladder: %s",
    )


def pallas_ghash_tree_available() -> bool:
    """Platform half of the tree gate. TIEREDSTORAGE_TPU_PALLAS_GHASH_TREE
    overrides just the tree (on-chip A/B against the ladder); unset, it
    follows TIEREDSTORAGE_TPU_PALLAS_GHASH, then real-TPU + preflight —
    all read at trace time like the sibling gates."""
    import os

    forced = os.environ.get("TIEREDSTORAGE_TPU_PALLAS_GHASH_TREE")
    if forced is None:
        forced = os.environ.get("TIEREDSTORAGE_TPU_PALLAS_GHASH")
    if forced is not None:
        return forced not in ("0", "false", "off")
    try:
        if jax.default_backend() not in ("tpu", "axon"):
            return False
    except Exception:
        return False
    return _tree_preflight_ok()


def _ghash_tree_kernel(x_ref, w_ref, step_ref, o_ref, acc_ref):
    """x_ref: VMEM uint8[R, K] — group g's byte columns of the row tile;
    w_ref: VMEM int8[8, K, 128] level-1 operand; step_ref: VMEM
    int8[128, 128] transposed multiply-by-H^(K/16) fold matrix; o_ref:
    VMEM int8[R, 128]; acc_ref: VMEM f32[R, 128] running T accumulator
    (0/1 values), persistent across the sequential group axis."""
    g = pl.program_id(1)
    # Widen BEFORE the bit math: Mosaic on the v5e toolchain legalizes
    # neither u8 vector shifts nor direct u8->f32 casts (round 5).
    x = x_ref[:].astype(jnp.int32)
    node = None
    for p in range(8):
        plane = ((x >> p) & 1).astype(jnp.float32)
        w_p = w_ref[p].astype(jnp.int32).astype(jnp.float32)
        part = jnp.dot(plane, w_p, preferred_element_type=jnp.float32)
        node = part if node is None else node + part
    # Exact: plane sums are bounded by K <= 2048 < 2^24.
    node_bits = node.astype(jnp.int32) & 1

    @pl.when(g == 0)
    def _init():
        acc_ref[:] = node_bits.astype(jnp.float32)

    @pl.when(g != 0)
    def _fold():
        step = step_ref[:].astype(jnp.int32).astype(jnp.float32)
        folded = jnp.dot(
            acc_ref[:], step, preferred_element_type=jnp.float32
        )
        # Exact again: fold sums are bounded by 128.
        acc_ref[:] = (
            (folded.astype(jnp.int32) & 1) ^ node_bits
        ).astype(jnp.float32)

    @pl.when(g == pl.num_programs(1) - 1)
    def _emit():
        o_ref[:] = acc_ref[:].astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ghash_tree_pallas(
    data: jnp.ndarray,
    w1: jnp.ndarray,
    step_mat: jnp.ndarray,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """data uint8[B, G*K] (G groups of the level-1 byte width K, leading
    zero-block padding already applied by the caller), w1 int8[8, K, 128],
    step_mat int8[128, 128] (gf128.ghash_step_matrix of H^(K/16)) ->
    T(C) node bits int8[B, 128].

    Bit-exact drop-in for the WHOLE `gcm._ghash_grouped` reduction — level
    1 and every grouped-power level above it — as ONE kernel: the grid
    walks (row tile, group) with the group axis sequential, a VMEM scratch
    accumulator folds ``T = (T @ M_{H^k}) ^ node_g`` between groups, and
    only the final [B, 128] node bits leave the kernel. B is padded to the
    TREE_ROWS_PER_STEP grid inside the op (zero rows reduce to zero bits)
    and sliced back."""
    rows, total = data.shape
    k = w1.shape[1]
    if rows <= 0:
        raise ValueError("rows must be positive")
    if w1.shape != (8, k, 128):
        raise ValueError(f"weights {w1.shape} are not (8, K, 128)")
    if k <= 0 or total % k:
        raise ValueError(f"data width {total} does not tile into K={k} groups")
    if step_mat.shape != (128, 128):
        raise ValueError(f"step matrix {step_mat.shape} is not (128, 128)")
    groups = total // k
    padded = -(-rows // TREE_ROWS_PER_STEP) * TREE_ROWS_PER_STEP
    if padded != rows:
        data = jnp.pad(data, ((0, padded - rows), (0, 0)))
    row_steps = padded // TREE_ROWS_PER_STEP
    out = pl.pallas_call(
        _ghash_tree_kernel,
        grid=(row_steps, groups),
        in_specs=[
            pl.BlockSpec((TREE_ROWS_PER_STEP, k), lambda r, g: (r, g)),
            pl.BlockSpec((8, k, 128), lambda r, g: (0, 0, 0)),
            pl.BlockSpec((128, 128), lambda r, g: (0, 0)),
        ],
        out_specs=pl.BlockSpec((TREE_ROWS_PER_STEP, 128), lambda r, g: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((padded, 128), jnp.int8),
        scratch_shapes=[pltpu.VMEM((TREE_ROWS_PER_STEP, 128), jnp.float32)],
        interpret=interpret,
    )(data, w1, step_mat)
    return out[:rows]
