"""Batched LZ match-finding on device — the match layer of `tpu-lzhuff-v1`.

The reference's codec is zstd: sequential hash-chain match-finding plus
entropy coding, on the JVM heap (core/.../transform/
CompressionChunkEnumeration.java:50-63). A TPU has no sequential match
finder, so this module re-states LZ77 as three data-parallel passes over a
whole window of chunks at once:

1. **Candidates** — a rolling 4-byte gram is hashed at every position; a
   per-row hash table is built block-by-block under `lax.scan` (the only
   sequential axis, n/SCAN_BLOCK steps): each step gathers the previous
   blocks' last-position-per-hash as the candidate set for its block, then
   scatter-**max**es its own positions in (positions grow monotonically, so
   max == last-wins without ordered-scatter semantics).
2. **Match lengths** — for each position, the candidate (and a distance-1
   probe that catches runs, which block-stepping can't see) is extended by
   comparing 4-byte grams word-at-a-time, MATCH_WORDS words deep; the first
   differing word's leading equal bytes come from its XOR's high bytes.
   Everything is gathers + elementwise ops; no scan.
3. **Parse** — greedy token selection (`next[i] = i + max(len[i], 1)`) is a
   path through the position graph; the path is materialized in O(log n)
   rounds of pointer doubling (gather ptr[ptr] + scatter-max of the
   reachability mask), not an O(n) walk.

Per-position lengths are capped at MAX_MATCH; the host serializer merges
adjacent same-distance tokens back into arbitrarily long matches, so runs
cost one sequence, as they do in zstd. Entropy coding of the resulting
streams is the existing device Huffman stage (ops/huffman.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

HASH_BITS = 16
TABLE_SIZE = 1 << HASH_BITS
#: Below this a match loses to the sequence record it would emit: a record
#: is 6 bytes pre-entropy but ~2 bytes after the per-field Huffman
#: (transform/lzhuff.py), so 5-byte matches still pay — measured best on
#: text (1.21x -> 1.19x of zstd-3) with logs unchanged.
MIN_MATCH = 5
#: Per-position cap; the serializer's same-distance merge rebuilds longer
#: matches, so this bounds device compare work, not the format.
MATCH_WORDS = 16
MAX_MATCH = MATCH_WORDS * 4
#: Table-update granularity: candidates for a block come from strictly
#: earlier blocks, so in-block-only repeats shorter than this are invisible
#: to the hash probe (the distance-1 probe still catches runs).
SCAN_BLOCK = 512
#: Match offsets are u16 in the sequence record.
MAX_DIST = 65535
#: Per-row dominant distances probed in the second pass (see
#: lz_analyze_batch); more buys little once the offset alphabet collapses.
TOP_DISTANCES = 4


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def lz_shape(n: int) -> int:
    """Static row width for a batch whose longest chunk is n bytes."""
    return max(SCAN_BLOCK, _ceil_div(n, SCAN_BLOCK) * SCAN_BLOCK)


def _grams(data: jnp.ndarray, n: int) -> jnp.ndarray:
    """uint32[B, n]: big-endian 4-byte gram starting at every position
    (zero-padded past the row end, so tail grams are well-defined)."""
    batch = data.shape[0]
    d = jnp.concatenate(
        [data, jnp.zeros((batch, 3), jnp.uint8)], axis=1
    ).astype(jnp.uint32)
    return (
        (d[:, :n] << 24) | (d[:, 1 : n + 1] << 16) | (d[:, 2 : n + 2] << 8) | d[:, 3 : n + 3]
    )


def _match_lengths(g: jnp.ndarray, cand: jnp.ndarray, valid: jnp.ndarray, n: int):
    """Equal-byte run length between each position and its candidate,
    capped at MAX_MATCH, via word-granular compares (no [n, MAX_MATCH]
    byte tensor in HBM)."""
    idx = jnp.arange(n, dtype=jnp.int32)[None, :]
    lens = jnp.zeros(cand.shape, jnp.int32)
    alive = valid
    c = jnp.clip(cand, 0, n - 1)
    for t in range(MATCH_WORDS):
        gi = jnp.take_along_axis(g, jnp.minimum(idx + 4 * t, n - 1), axis=1)
        gc = jnp.take_along_axis(g, jnp.minimum(c + 4 * t, n - 1), axis=1)
        x = gi ^ gc
        eq_word = x == 0
        # Grams are big-endian, so the first differing byte is the highest
        # non-zero byte of the XOR.
        b0 = (x >> 24) == 0
        b1 = b0 & (((x >> 16) & 0xFF) == 0)
        b2 = b1 & (((x >> 8) & 0xFF) == 0)
        partial = b0.astype(jnp.int32) + b1.astype(jnp.int32) + b2.astype(jnp.int32)
        lens = lens + jnp.where(alive, jnp.where(eq_word, 4, partial), 0)
        alive = alive & eq_word
    return lens


@functools.partial(jax.jit, static_argnames=("n_max",))
def lz_analyze_batch(data: jnp.ndarray, n_sym: jnp.ndarray, *, n_max: int):
    """data uint8[B, n_max] (n_max % SCAN_BLOCK == 0, zero-padded past each
    row's n_sym) -> (lens int32[B, n_max], dists int32[B, n_max],
    sel bool[B, n_max]).

    lens[i] > 0 marks a usable match of that many bytes at distance
    dists[i] (always in [1, MAX_DIST], source strictly earlier in the same
    chunk); sel marks the greedy parse's token starts. Padding rows/tails
    carry garbage — the serializer slices to n_sym."""
    if n_max % SCAN_BLOCK:
        raise ValueError(f"n_max={n_max} not a multiple of {SCAN_BLOCK}")
    batch = data.shape[0]
    n = n_max
    rows = jnp.arange(batch, dtype=jnp.int32)[:, None]
    idx = jnp.arange(n, dtype=jnp.int32)[None, :]

    g = _grams(data, n)
    # Two candidate tables, zstd-double-fast style: the 4-byte gram finds
    # short/nearby repeats but its most-recent hit is often an unrelated
    # common gram (`":"…`), truncating the match; the 8-byte gram is
    # selective enough that its hit is usually the true long repeat
    # (the previous record in log-structured data).
    h4 = ((g * jnp.uint32(2654435761)) >> jnp.uint32(32 - HASH_BITS)).astype(jnp.int32)
    g_next = jnp.concatenate([g[:, 4:], jnp.zeros((batch, 4), jnp.uint32)], axis=1)
    h8 = (
        ((g * jnp.uint32(2654435761)) ^ (g_next * jnp.uint32(2246822519)))
        >> jnp.uint32(32 - HASH_BITS)
    ).astype(jnp.int32)

    n_blocks = n // SCAN_BLOCK
    h4s = h4.reshape(batch, n_blocks, SCAN_BLOCK).transpose(1, 0, 2)  # [nb, B, S]
    h8s = h8.reshape(batch, n_blocks, SCAN_BLOCK).transpose(1, 0, 2)
    pos = jnp.arange(n, dtype=jnp.int32).reshape(n_blocks, 1, SCAN_BLOCK)

    def step(tables, xs):
        t4, t8 = tables
        hk4, hk8, pk = xs  # [B, S] hashes, [1, S] positions
        p = jnp.broadcast_to(pk, hk4.shape)
        c4 = jnp.take_along_axis(t4, hk4, axis=1)
        c8 = jnp.take_along_axis(t8, hk8, axis=1)
        t4 = t4.at[rows, hk4].max(p)
        t8 = t8.at[rows, hk8].max(p)
        return (t4, t8), (c4, c8)

    table0 = jnp.full((batch, TABLE_SIZE), -1, jnp.int32)
    _, (c4s, c8s) = jax.lax.scan(step, (table0, table0), (h4s, h8s, pos))
    cand4 = c4s.transpose(1, 0, 2).reshape(batch, n)
    cand8 = c8s.transpose(1, 0, 2).reshape(batch, n)

    len4 = _match_lengths(g, cand4, (cand4 >= 0) & (idx - cand4 <= MAX_DIST), n)
    len8 = _match_lengths(g, cand8, (cand8 >= 0) & (idx - cand8 <= MAX_DIST), n)
    len_run = _match_lengths(g, idx - 1, idx >= 1, n)

    # Longest wins; ties prefer the shorter distance (run, then 4-gram —
    # its most-recent hit is at most as far as the 8-gram table's).
    lens = len_run
    dists = jnp.ones_like(lens)
    use4 = len4 > lens
    lens = jnp.where(use4, len4, lens)
    dists = jnp.where(use4, idx - cand4, dists)
    use8 = len8 > lens
    lens = jnp.where(use8, len8, lens)
    dists = jnp.where(use8, idx - cand8, dists)
    tail = n_sym[:, None] - idx

    def clamp(lens):
        lens = jnp.minimum(lens, jnp.maximum(tail, 0))
        return jnp.where(lens >= MIN_MATCH, lens, 0)

    def parse(lens):
        # Greedy parse via pointer doubling: ptr[i] = next token start
        # after i; the parse is the set of positions reachable from 0.
        nxt = jnp.minimum(idx + jnp.where(lens > 0, lens, 1), n)
        ptr = jnp.concatenate([nxt, jnp.full((batch, 1), n, jnp.int32)], axis=1)
        reach = jnp.zeros((batch, n + 1), jnp.bool_).at[:, 0].set(True)

        def double(carry, _):
            reach, ptr = carry
            reach = reach.at[rows, ptr].max(reach)
            ptr = jnp.take_along_axis(ptr, ptr, axis=1)
            return (reach, ptr), None

        rounds = max(1, n.bit_length())
        (reach, _), _ = jax.lax.scan(double, (reach, ptr), None, length=rounds)
        return reach[:, :n]

    lens = clamp(lens)
    sel = parse(lens)

    # Dominant-distance pass — zstd's rep-offset insight restated for a
    # parallel matcher. Sequential rep codes (repeat the PREVIOUS match's
    # offset) assume consecutive matches share a distance; in
    # multi-field structured data they instead cycle through several
    # periodicities, so the parallel equivalent is GLOBAL: histogram the
    # parse-1 match distances per row, take the top-K, probe those
    # distances at every position, and prefer them on near-ties (up to 1
    # byte shorter still wins — collapsing the offset alphabet to a few
    # values is worth more than the lost byte). The serializer's
    # same-offset sentinel plus the per-field Huffman then make the
    # dominant offsets nearly free. Re-parse with the adjusted matches.
    sel_match = sel & (lens > 0)
    hist = jnp.zeros((batch, MAX_DIST + 1), jnp.int32).at[
        rows, jnp.where(sel_match, dists, 0)
    ].add(jnp.where(sel_match, 1, 0))
    hist = hist.at[:, 0].set(0)
    # Pick the best of the top-K by STRICT length first (so a rarer later
    # distance can't steal near-ties from a more dominant earlier one and
    # chain length degradation), then apply the 1-byte near-tie preference
    # once, against the pass-1 candidate.
    top_len = jnp.zeros_like(lens)
    top_dist = jnp.zeros_like(dists)
    for _ in range(TOP_DISTANCES):
        top = jnp.argmax(hist, axis=1).astype(jnp.int32)  # [B]
        hist = hist.at[rows[:, 0], top].set(0)
        pk = top[:, None]
        len_k = clamp(
            _match_lengths(g, idx - pk, (pk >= 1) & (idx - pk >= 0), n)
        )
        better = len_k > top_len
        top_len = jnp.where(better, len_k, top_len)
        top_dist = jnp.where(better, pk, top_dist)
    use_top = (top_len > 0) & (top_len + 1 >= lens)
    lens = jnp.where(use_top, top_len, lens)
    dists = jnp.where(use_top, top_dist, dists)
    sel = parse(lens)
    return lens, dists, sel
