"""AES-256: host-side key schedule + vectorized device-side cipher (JAX).

Replaces the JDK AES-GCM intrinsics the reference's EncryptionChunkEnumeration
leans on (core/.../transform/EncryptionChunkEnumeration.java): here the block
cipher is applied to ALL counter blocks of a whole chunk batch at once.

The S-box and round constants are generated programmatically from the field
definition (FIPS-197 math, not copied tables) and validated against FIPS/NIST
vectors in tests. The device cipher is the table form (SubBytes via gather,
MixColumns via GF(2^8) doubling in uint8 arithmetic); a bitsliced variant can
replace it behind the same function signature if gather throughput on the
target chip warrants it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


# --- GF(2^8) groundwork (host) ---

def _gf8_mult(a: int, b: int) -> int:
    p = 0
    while b:
        if b & 1:
            p ^= a
        a <<= 1
        if a & 0x100:
            a ^= 0x11B  # x^8 + x^4 + x^3 + x + 1
        b >>= 1
    return p


@functools.cache
def _sbox() -> np.ndarray:
    inv = [0] * 256
    for x in range(1, 256):
        # Multiplicative inverse by exponentiation: x^254.
        y = 1
        for _ in range(254):
            y = _gf8_mult(y, x)
        inv[x] = y
    table = np.zeros(256, dtype=np.uint8)
    for x in range(256):
        v = inv[x]
        b = 0
        for i in range(8):
            bit = (
                (v >> i) ^ (v >> ((i + 4) % 8)) ^ (v >> ((i + 5) % 8))
                ^ (v >> ((i + 6) % 8)) ^ (v >> ((i + 7) % 8)) ^ (0x63 >> i)
            ) & 1
            b |= bit << i
        table[x] = b
    return table


@functools.cache
def _inv_sbox() -> np.ndarray:
    s = _sbox()
    inv = np.zeros(256, dtype=np.uint8)
    inv[s] = np.arange(256, dtype=np.uint8)
    return inv


SBOX = _sbox()
INV_SBOX = _inv_sbox()

_NR = 14  # rounds for AES-256

# ShiftRows permutation over the 16-byte state in FIPS column-major layout:
# byte index = 4*col + row; row r rotates left by r columns.
_SHIFT_ROWS = np.array(
    [4 * ((c + r) % 4) + r for c in range(4) for r in range(4)], dtype=np.int32
)
_INV_SHIFT_ROWS = np.argsort(_SHIFT_ROWS).astype(np.int32)


def key_expansion(key: bytes) -> np.ndarray:
    """AES-256 key schedule -> uint8[15, 16] round keys (host, FIPS-197 §5.2)."""
    if len(key) != 32:
        raise ValueError("AES-256 key must be 32 bytes")
    nk = 8
    words = [list(key[4 * i : 4 * i + 4]) for i in range(nk)]
    rcon = 1
    sbox = _sbox()
    for i in range(nk, 4 * (_NR + 1)):
        temp = list(words[i - 1])
        if i % nk == 0:
            temp = temp[1:] + temp[:1]
            temp = [int(sbox[t]) for t in temp]
            temp[0] ^= rcon
            rcon = _gf8_mult(rcon, 2)
        elif i % nk == 4:
            temp = [int(sbox[t]) for t in temp]
        words.append([a ^ b for a, b in zip(words[i - nk], temp)])
    flat = np.array(words, dtype=np.uint8).reshape(_NR + 1, 16)
    return flat


# --- device-side cipher ---

def _xtime(x: jnp.ndarray) -> jnp.ndarray:
    """GF(2^8) doubling on uint8 arrays."""
    return ((x << 1) & 0xFF) ^ ((x >> 7) * 0x1B)


def _mix_columns(state: jnp.ndarray) -> jnp.ndarray:
    """state: uint8[..., 16] in column-major layout; mix each 4-byte column."""
    s = state.reshape(state.shape[:-1] + (4, 4))  # [..., col, row]
    rot1 = jnp.roll(s, -1, axis=-1)
    rot2 = jnp.roll(s, -2, axis=-1)
    rot3 = jnp.roll(s, -3, axis=-1)
    # out_r = 2*s_r ^ 3*s_{r+1} ^ s_{r+2} ^ s_{r+3}
    out = _xtime(s) ^ (_xtime(rot1) ^ rot1) ^ rot2 ^ rot3
    return out.reshape(state.shape)


def _inv_mix_columns(state: jnp.ndarray) -> jnp.ndarray:
    s = state.reshape(state.shape[:-1] + (4, 4))
    x2 = _xtime(s)
    x4 = _xtime(x2)
    x8 = _xtime(x4)
    m9 = x8 ^ s
    m11 = x8 ^ x2 ^ s
    m13 = x8 ^ x4 ^ s
    m14 = x8 ^ x4 ^ x2
    out = (
        m14
        ^ jnp.roll(m11, -1, axis=-1)
        ^ jnp.roll(m13, -2, axis=-1)
        ^ jnp.roll(m9, -3, axis=-1)
    )
    return out.reshape(state.shape)


def aes_encrypt_blocks(round_keys: jnp.ndarray, blocks: jnp.ndarray) -> jnp.ndarray:
    """Encrypt uint8[..., 16] blocks; round_keys uint8[15,16]."""
    sbox = jnp.asarray(SBOX)
    shift = jnp.asarray(_SHIFT_ROWS)
    state = blocks ^ round_keys[0]
    for rnd in range(1, _NR):
        state = jnp.take(sbox, state.astype(jnp.int32), axis=0)
        state = jnp.take(state, shift, axis=-1)
        state = _mix_columns(state)
        state = state ^ round_keys[rnd]
    state = jnp.take(sbox, state.astype(jnp.int32), axis=0)
    state = jnp.take(state, shift, axis=-1)
    return state ^ round_keys[_NR]


def aes_decrypt_blocks(round_keys: jnp.ndarray, blocks: jnp.ndarray) -> jnp.ndarray:
    """Inverse cipher (unused by CTR mode; provided for completeness/tests)."""
    inv_sbox = jnp.asarray(INV_SBOX)
    inv_shift = jnp.asarray(_INV_SHIFT_ROWS)
    state = blocks ^ round_keys[_NR]
    for rnd in range(_NR - 1, 0, -1):
        state = jnp.take(state, inv_shift, axis=-1)
        state = jnp.take(inv_sbox, state.astype(jnp.int32), axis=0)
        state = state ^ round_keys[rnd]
        state = _inv_mix_columns(state)
    state = jnp.take(state, inv_shift, axis=-1)
    state = jnp.take(inv_sbox, state.astype(jnp.int32), axis=0)
    return state ^ round_keys[0]


def ctr_keystream(
    round_keys: jnp.ndarray, iv: jnp.ndarray, first_counter: int, n_blocks: int
) -> jnp.ndarray:
    """Keystream blocks uint8[n_blocks, 16] for a 12-byte IV.

    Counter block = IV || big-endian32(first_counter + i). GCM encrypts data
    with counters starting at 2 (J0 = IV||1 is reserved for the tag mask).
    """
    counters = jnp.arange(first_counter, first_counter + n_blocks, dtype=jnp.uint32)
    ctr_bytes = (
        counters[:, None] >> jnp.array([24, 16, 8, 0], dtype=jnp.uint32)[None, :]
    ).astype(jnp.uint8)
    iv_rep = jnp.broadcast_to(iv.astype(jnp.uint8), (n_blocks, 12))
    blocks = jnp.concatenate([iv_rep, ctr_bytes], axis=1)
    return aes_encrypt_blocks(round_keys, blocks)
