"""TPU kernels (JAX/XLA; Pallas where beneficial) for the transform hot path.

These replace the native libraries the reference's transform pipeline
delegates to (zstd-jni and JDK AES-GCM intrinsics; see SURVEY.md §2.2):

- ops.aes    — AES-256 key schedule (host) + vectorized cipher/CTR (device)
- ops.gf128  — host-side GF(2^128) math: GHASH constants as GF(2) bit
               matrices so the device-side reduction runs on the MXU
- ops.gcm    — batched AES-256-GCM over uint8[batch, chunk_size] arrays
- ops.crc32c — CRC32C as a GF(2) linear-map tree (MXU)
"""
