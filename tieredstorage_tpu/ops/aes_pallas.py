"""Pallas TPU kernel for the bitsliced AES-256 boolean circuit.

The XLA lowering of the ~2000-gate tower-field circuit (ops/aes_bitsliced.py)
round-trips every gate's uint32[16, 8, W] operand through HBM — measured at
0.66 GiB/s of keystream on a v5e (PROFILE.md). This kernel evaluates the whole
circuit per 512 KiB tile inside VMEM: the 128 bit-planes live as (R, 128)
uint32 vregs, ShiftRows is pure Python-level variable relabeling at trace
time, MixColumns is relabeling plus XORs, and only the initial/final state
touches HBM (2 bytes moved per keystream byte).

Wiring notes (replaces the reference's per-chunk JDK `AES/GCM/NoPadding`
cipher, core/.../transform/EncryptionChunkEnumeration.java:66-81):
- SubBytes reuses the derived tower-field circuit (`_sbox_planes`), applied
  once per round on all 16 byte positions stacked along sublanes (16R, 128).
- MixColumns per column: out[r] = xtime(a ^ c) ^ a ^ (a^c^d^e), with xtime a
  bit-index rotation feeding bit 7 into bits {0,1,3,4} (poly 0x11B) — all
  relabeling + XOR, no data movement.
- Round keys are uint32 full-word masks in SMEM, XORed in as scalars.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tieredstorage_tpu.ops.aes import _NR, _SHIFT_ROWS
from tieredstorage_tpu.ops.aes_bitsliced import _sbox_planes, _tower

#: Sublane rows per plane per grid step: one (8, 128) uint32 vreg per plane,
#: i.e. 1024 words = 32768 blocks = 512 KiB of keystream per step.
#: TSTPU_AES_R overrides for on-chip tile sweeps (tools/probe_min.py):
#: larger R = more words per vector op and fewer grid steps, at the price
#: of R/8 vregs live per plane.


def _validated_r(raw: str) -> int:
    """The ShiftRows un-stack slices the (16R, 128) sublane stack at R-row
    boundaries; an R that isn't a power-of-two multiple of 8 mis-tiles those
    slices. Fail loud at import; the TIEREDSTORAGE_TPU_PALLAS=1 forced path
    (which skips the preflight) additionally runs a behavioral output
    cross-check of the kernel body at first use
    (aes_bitsliced._forced_crosscheck_ok), so even a range-valid but
    mistiled kernel cannot corrupt keystream silently."""
    try:
        r = int(raw)
    except ValueError as e:
        raise ValueError(f"TSTPU_AES_R={raw!r} is not an integer") from e
    if r < 8 or r > 256 or r & (r - 1):
        raise ValueError(
            f"TSTPU_AES_R={raw!r} must be a power of two in [8, 256] "
            "(sublane tiling of the ShiftRows un-stack)"
        )
    return r


R = _validated_r(os.environ.get("TSTPU_AES_R", "8"))
WORDS_PER_STEP = R * 128


def use_pallas_aes(n_words: int) -> bool:
    """Shape eligibility for the fused circuit kernel — pure host logic, no
    platform probe, so benchmarks and CPU-only CI can assert that the
    production window shapes tile onto the kernel (the platform half lives
    in `aes_bitsliced.pallas_aes_available`).

    `aes_encrypt_planes_pallas` zero-pads W to the WORDS_PER_STEP grid
    internally, so eligibility is only a worth-it floor: at least 1024
    words (512 KiB of keystream — below that the XLA circuit wins on
    launch overhead) and at least half a grid step (so padding never more
    than doubles the dispatched compute under a TSTPU_AES_R override)."""
    return n_words >= 1024 and 2 * n_words >= WORDS_PER_STEP


def _xtime_planes(x: list) -> list:
    """GF(2^8) multiply-by-x on 8 bit-planes (LSB-first bit index)."""
    return [
        x[7],
        x[0] ^ x[7],
        x[1],
        x[2] ^ x[7],
        x[3] ^ x[7],
        x[4],
        x[5],
        x[6],
    ]


def _mix_columns_vars(st: list) -> list:
    """MixColumns over 16 position-vars of 8 planes each (pos = col*4 + row)."""
    out = [None] * 16
    for col in range(4):
        idx = [col * 4 + r for r in range(4)]
        all4 = [
            st[idx[0]][b] ^ st[idx[1]][b] ^ st[idx[2]][b] ^ st[idx[3]][b]
            for b in range(8)
        ]
        for r in range(4):
            a = st[idx[r]]
            c = st[idx[(r + 1) % 4]]
            xt = _xtime_planes([a[b] ^ c[b] for b in range(8)])
            out[idx[r]] = [xt[b] ^ a[b] ^ all4[b] for b in range(8)]
    return out


def _aes_kernel(rk_ref, in_ref, out_ref):
    """rk_ref: SMEM uint32[15, 128] round-key masks ([rnd, pos*8 + bit]);
    in_ref/out_ref: VMEM uint32[16, 8, R, 128] plane tiles."""
    tw = _tower()
    st = [
        [in_ref[p, b] ^ rk_ref[0, p * 8 + b] for b in range(8)] for p in range(16)
    ]
    for rnd in range(1, _NR + 1):
        # SubBytes: all 16 positions stacked along sublanes, one circuit pass.
        big = [
            jnp.concatenate([st[p][b] for p in range(16)], axis=0) for b in range(8)
        ]
        big = _sbox_planes(tw, big)
        # Un-stack with ShiftRows fused into the slice index.
        st = [
            [
                jax.lax.slice_in_dim(
                    big[b], _SHIFT_ROWS[p] * R, (_SHIFT_ROWS[p] + 1) * R, axis=0
                )
                for b in range(8)
            ]
            for p in range(16)
        ]
        if rnd != _NR:
            st = _mix_columns_vars(st)
        st = [
            [st[p][b] ^ rk_ref[rnd, p * 8 + b] for b in range(8)] for p in range(16)
        ]
    for p in range(16):
        for b in range(8):
            out_ref[p, b] = st[p][b]


class _ArrayRef:
    """Read-only stand-in for a Pallas ref backed by a plain array."""

    def __init__(self, arr):
        self._arr = arr

    def __getitem__(self, idx):
        return self._arr[idx]


class _CollectRef:
    """Write-only stand-in collecting kernel outputs."""

    def __init__(self):
        self.out = {}

    def __setitem__(self, idx, val):
        self.out[idx] = val


def kernel_body_reference(rk_planes: jnp.ndarray, state: jnp.ndarray) -> jnp.ndarray:
    """Evaluate `_aes_kernel` for ONE grid step with plain-array stand-ins
    for the refs — identical math (including the R-dependent ShiftRows
    un-stack slicing), no Mosaic or interpreter in the loop, ~1 s eager on
    CPU. This is what the forced-path `TSTPU_AES_R` output cross-check and
    the kernel-body tests both run: any mis-tiling of the (16R, 128)
    sublane stack shows up here exactly as it would on device.

    rk_planes: uint32[15, 16, 8] masks; state: uint32[16, 8, WORDS_PER_STEP].
    """
    out_ref = _CollectRef()
    _aes_kernel(
        _ArrayRef(rk_planes.reshape(_NR + 1, 128)),
        _ArrayRef(state.reshape(16, 8, R, 128)),
        out_ref,
    )
    rows = [
        jnp.stack([out_ref.out[(p, b)] for b in range(8)], axis=0)
        for p in range(16)
    ]
    return jnp.stack(rows, axis=0).reshape(16, 8, state.shape[2])


@functools.partial(jax.jit, static_argnames=("interpret",))
def aes_encrypt_planes_pallas(
    rk_planes: jnp.ndarray, state: jnp.ndarray, *, interpret: bool = False
) -> jnp.ndarray:
    """Encrypt a bitsliced state uint32[16, 8, W] with AES-256 in one kernel.

    Drop-in for `aes_bitsliced.aes_encrypt_planes`; W is zero-padded to the
    WORDS_PER_STEP grid INSIDE the op and the result sliced back, so callers
    dispatch production window shapes as-is. `interpret=True` runs the
    kernel op-by-op on CPU for tests."""
    w = state.shape[2]
    if w <= 0:
        raise ValueError("W must be positive")
    padded = -(-w // WORDS_PER_STEP) * WORDS_PER_STEP
    if padded != w:
        state = jnp.pad(state, ((0, 0), (0, 0), (0, padded - w)))
    steps = padded // WORDS_PER_STEP
    st4 = state.reshape(16, 8, steps * R, 128)
    rk = rk_planes.reshape(_NR + 1, 128)
    out = pl.pallas_call(
        _aes_kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((16, 8, R, 128), lambda s: (0, 0, s, 0)),
        ],
        out_specs=pl.BlockSpec((16, 8, R, 128), lambda s: (0, 0, s, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 8, steps * R, 128), jnp.uint32),
        interpret=interpret,
    )(rk, st4)
    return out.reshape(16, 8, padded)[:, :, :w]
