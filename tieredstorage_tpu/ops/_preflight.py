"""Shared preflight-and-fallback machinery for the Pallas kernel gates.

Both kernels (ops/aes_pallas.py, ops/ghash_pallas.py) are guarded by a
first-use preflight that compiles and runs the kernel on a minimal tile and
cross-checks it against an exact reference. The verdict is memoized per
process so an unattended round-end benchmark can't lose its artifact to a
kernel regression — but the memo must distinguish two failure classes:

- **Lowering failures** (Mosaic can't compile the kernel here): deterministic,
  retrying cannot help, memoize False immediately.
- **Transient failures** (relay RPC deadline, transport reset — the
  documented axon outage modes): retried a bounded number of times *inside
  the consult*, because the gate is read at trace time and the caller's jit
  cache pins whichever verdict the first trace saw; a verdict returned
  without retrying would silently pin that shape to the slow XLA path for
  the life of the process.

Only the final verdict is memoized, so the answer the bench's eager gate
probe records is the same answer every traced shape saw.
"""

from __future__ import annotations

import logging
import time
from typing import Callable

#: Marks of a deterministic compile/lowering failure.
_LOWERING_MARKS = (
    "mosaic",
    "lowering",
    "unsupported",
    "notimplemented",
    "cannot lower",
    "unimplemented",
    "tracerbool",       # omnistaging leak: retrying the same trace can't help
    "concretization",
)

#: Exception types that are deterministic regardless of message text:
#: a missing module, a failed cross-check assertion, or an unimplemented
#: path will fail identically on every retry.
_DETERMINISTIC_TYPES = (ImportError, AssertionError, NotImplementedError)

#: Transient retry budget per preflight run, and the pause between tries.
TRANSIENT_RETRIES = 2
RETRY_DELAY_S = 1.0


def is_lowering_failure(exc: BaseException) -> bool:
    if isinstance(exc, _DETERMINISTIC_TYPES):
        return True
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(mark in text for mark in _LOWERING_MARKS)


def run_preflight(
    memo: list,
    attempt: Callable[[], bool],
    logger: logging.Logger,
    unavailable_msg: str,
    *,
    retries: int = TRANSIENT_RETRIES,
    delay_s: float = RETRY_DELAY_S,
) -> bool:
    """Run `attempt` with bounded in-place retries for transient failures,
    memoizing the final verdict into `memo` (a module-level list; tests clear
    it to re-arm the gate). `attempt` returns whether the kernel's output
    matched the reference; any exception it raises is classified by
    `is_lowering_failure`."""
    if memo:
        return memo[0]
    transient_tries = 0
    while True:
        try:
            ok = bool(attempt())
            break
        except Exception as exc:
            if not is_lowering_failure(exc) and transient_tries < retries:
                transient_tries += 1
                logger.warning(
                    "Pallas preflight failed transiently (retry %d/%d in "
                    "%.1fs): %s",
                    transient_tries,
                    retries,
                    delay_s,
                    exc,
                )
                time.sleep(delay_s)
                continue
            logger.warning(unavailable_msg, exc)
            ok = False
            break
    memo.append(ok)
    return ok


def interpret_off_device(logger: logging.Logger, what: str) -> bool:
    """True when the backend is not a real TPU, so a *forced* kernel path
    should run in Mosaic interpret mode. The probe itself can raise during
    backend acquisition (the documented relay outage mode); degrade to
    interpret with a warning rather than aborting the caller's trace."""
    import jax

    try:
        return jax.default_backend() not in ("tpu", "axon")
    except Exception as exc:
        logger.warning(
            "Backend probe failed; running the forced %s in interpret mode "
            "(orders slower): %s",
            what,
            exc,
        )
        return True
