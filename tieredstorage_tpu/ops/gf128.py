"""Host-side GF(2^128) math for GHASH, in GCM's reflected-bit convention.

GHASH multiplication by a FIXED field element C is linear over GF(2), so it
is exactly a 128x128 bit-matrix apply. The device-side GHASH reduction
(ops/gcm.py) is a log-tree whose level-j combine multiplies by H^(2^j); this
module builds those matrices (one per level, per segment key) so the entire
reduction becomes int8 matmuls (mod 2) on the MXU — no carryless-multiply
instruction needed, which TPUs don't have.

Conventions: a field element is a 128-bit Python int whose bit i (from the
MSB end) is the coefficient of x^i — i.e. int.from_bytes(block, "big") with
GCM's bit-reflected polynomial P(x) = x^128 + x^7 + x^2 + x + 1, where the
block's first byte's MSB is the x^0 coefficient. In this int encoding the
x^0 coefficient sits at bit 127 and multiplication by x is a right shift
with conditional reduction by R = 0xE1 << 120.
"""

from __future__ import annotations

import numpy as np

_R = 0xE1000000000000000000000000000000  # reduction constant (reflected P)
_MASK = (1 << 128) - 1


def gcm_mult(x: int, y: int) -> int:
    """GF(2^128) product in GCM convention (both operands as 128-bit ints)."""
    z = 0
    v = x
    for i in range(127, -1, -1):
        if (y >> i) & 1:
            z ^= v
        if v & 1:
            v = (v >> 1) ^ _R
        else:
            v >>= 1
    return z


def mult_by_x(v: int) -> int:
    """Multiply by x (one reflected shift step)."""
    if v & 1:
        return (v >> 1) ^ _R
    return v >> 1


def gcm_pow(h: int, exponent: int) -> int:
    """H^exponent by square-and-multiply."""
    result = 1 << 127  # the field's multiplicative identity in this encoding
    base = h
    e = exponent
    while e:
        if e & 1:
            result = gcm_mult(result, base)
        base = gcm_mult(base, base)
        e >>= 1
    return result


def _int_to_bits(v: int) -> np.ndarray:
    """128-bit int -> uint8[128] bit vector, index 0 = MSB (byte-order bits)."""
    return np.frombuffer(v.to_bytes(16, "big"), dtype=np.uint8)[:, None] >> np.arange(
        7, -1, -1, dtype=np.uint8
    ).reshape(1, 8) & 1


def int_to_bitvec(v: int) -> np.ndarray:
    return _int_to_bits(v).reshape(128).astype(np.uint8)


def bitvec_to_int(bits: np.ndarray) -> int:
    packed = np.packbits(bits.astype(np.uint8).reshape(16, 8), axis=1, bitorder="big")
    return int.from_bytes(packed.tobytes(), "big")


def mult_matrix(c: int) -> np.ndarray:
    """uint8[128,128] matrix M with bits(a*c) = M @ bits(a) mod 2.

    Column i is c * x^i, built incrementally with 128 shift-reduce steps
    (c * x^(i+1) = (c * x^i) * x), so matrix construction is O(128) field
    steps, not O(128) full multiplications.
    """
    m = np.zeros((128, 128), dtype=np.uint8)
    col = c
    for i in range(128):
        m[:, i] = int_to_bitvec(col)
        col = mult_by_x(col)
    return m


def ghash_level_matrices(h: int, levels: int) -> np.ndarray:
    """uint8[levels,128,128]: level j's combine matrix = mult by H^(2^j).

    Level 0 pairs single blocks (L*H^1 ^ R), level 1 pairs 2-block nodes
    (L*H^2 ^ R), etc. H^(2^(j+1)) is the square of H^(2^j).
    """
    mats = np.zeros((levels, 128, 128), dtype=np.uint8)
    c = h
    for j in range(levels):
        mats[j] = mult_matrix(c)
        c = gcm_mult(c, c)
    return mats


def ghash_reference(h: int, blocks: list[bytes]) -> int:
    """Straightforward serial GHASH for testing: Y_i = (Y_{i-1} ^ X_i) * H."""
    y = 0
    for b in blocks:
        y = gcm_mult(y ^ int.from_bytes(b.ljust(16, b"\x00"), "big"), h)
    return y
