"""Host-side GF(2^128) math for GHASH, in GCM's reflected-bit convention.

GHASH multiplication by a FIXED field element C is linear over GF(2), so it
is exactly a 128x128 bit-matrix apply. The device-side GHASH reduction
(ops/gcm.py) is a grouped-power contraction — each level multiplies up to
128 slots by precomputed powers of H in one MXU matmul; this module builds
the stacked per-level operands (ghash_agg_matrices, per segment key) so the
entire reduction becomes int8 matmuls (mod 2) — no carryless-multiply
instruction needed, which TPUs don't have.

Conventions: a field element is a 128-bit Python int whose bit i (from the
MSB end) is the coefficient of x^i — i.e. int.from_bytes(block, "big") with
GCM's bit-reflected polynomial P(x) = x^128 + x^7 + x^2 + x + 1, where the
block's first byte's MSB is the x^0 coefficient. In this int encoding the
x^0 coefficient sits at bit 127 and multiplication by x is a right shift
with conditional reduction by R = 0xE1 << 120.
"""

from __future__ import annotations

import numpy as np

_R = 0xE1000000000000000000000000000000  # reduction constant (reflected P)


def gcm_mult(x: int, y: int) -> int:
    """GF(2^128) product in GCM convention (both operands as 128-bit ints)."""
    z = 0
    v = x
    for i in range(127, -1, -1):
        if (y >> i) & 1:
            z ^= v
        if v & 1:
            v = (v >> 1) ^ _R
        else:
            v >>= 1
    return z


def mult_by_x(v: int) -> int:
    """Multiply by x (one reflected shift step)."""
    if v & 1:
        return (v >> 1) ^ _R
    return v >> 1


def gcm_pow(h: int, exponent: int) -> int:
    """H^exponent by square-and-multiply."""
    result = 1 << 127  # the field's multiplicative identity in this encoding
    base = h
    e = exponent
    while e:
        if e & 1:
            result = gcm_mult(result, base)
        base = gcm_mult(base, base)
        e >>= 1
    return result


def _int_to_bits(v: int) -> np.ndarray:
    """128-bit int -> uint8[128] bit vector, index 0 = MSB (byte-order bits)."""
    return np.frombuffer(v.to_bytes(16, "big"), dtype=np.uint8)[:, None] >> np.arange(
        7, -1, -1, dtype=np.uint8
    ).reshape(1, 8) & 1


def int_to_bitvec(v: int) -> np.ndarray:
    return _int_to_bits(v).reshape(128).astype(np.uint8)


def bitvec_to_int(bits: np.ndarray) -> int:
    packed = np.packbits(bits.astype(np.uint8).reshape(16, 8), axis=1, bitorder="big")
    return int.from_bytes(packed.tobytes(), "big")


def mult_matrix(c: int) -> np.ndarray:
    """uint8[128,128] matrix M with bits(a*c) = M @ bits(a) mod 2.

    Column i is c * x^i, built incrementally with 128 shift-reduce steps
    (c * x^(i+1) = (c * x^i) * x), so matrix construction is O(128) field
    steps, not O(128) full multiplications.
    """
    m = np.zeros((128, 128), dtype=np.uint8)
    col = c
    for i in range(128):
        m[:, i] = int_to_bitvec(col)
        col = mult_by_x(col)
    return m


def ghash_agg_plan(m: int, max_k: int = 128) -> list[tuple[int, int]]:
    """Level plan for grouped GHASH aggregation over m blocks.

    Returns [(k, padded_count), ...] per level: each level left-pads the
    current block count to a multiple of k (leading zero blocks don't change
    the polynomial) and contracts k slots at a time until one remains. With
    max_k=128 the contraction is a [B*G, k*128] x [k*128, 128] int8 matmul —
    one MXU-sized kernel per level instead of the former log2(m) sequential
    pairwise tree levels (PROFILE.md round-3 consequence 2)."""
    plan = []
    cur = max(1, m)
    while cur > 1:
        k = min(max_k, cur)
        padded = -(-cur // k) * k
        plan.append((k, padded))
        cur = padded // k
    if not plan:
        plan.append((1, 1))
    return plan


def ghash_agg_matrices(h: int, m: int, max_k: int = 128) -> tuple[np.ndarray, ...]:
    """Per-level grouped-GHASH operands; composed they give
    T(C) = sum_i C_i * H^(m-1-i) — exactly what the former pairwise tree
    computed, so the surrounding final-mat/const folding is unchanged.

    Level 1 is int8[8, k_1*16, 128], contracted against the 8 BYTE-bit planes
    of the raw chunk bytes (plane kbit = (bytes >> kbit) & 1): entry
    [kbit, s*16+p, o] is the o-th output bit's coefficient for block-slot s,
    byte p, byte-bit kbit (GCM bit index p*8 + 7 - kbit). This keeps every
    device intermediate's minor dimension large — a [B, m, 128]-bit layout
    would tile-pad its [.., 16, 8] expansion 16x in HBM (the round-3 OOM).

    Levels >= 2 are int8[k_L*128, 128]: out = bits[g, :] @ W_L (mod 2), slot
    j carrying P_L^(k_L-1-j), P_1 = H, P_{L+1} = P_L^(k_L)."""
    mats = []
    p = h
    for lvl, (k, _padded) in enumerate(ghash_agg_plan(m, max_k)):
        acc = 1 << 127  # multiplicative identity
        powers = [None] * k
        for j in range(k - 1, -1, -1):
            powers[j] = acc
            acc = gcm_mult(acc, p)
        w = np.concatenate(
            [mult_matrix(x).T.astype(np.int8) for x in powers], axis=0
        )
        if lvl == 0:
            w4 = w.reshape(k, 16, 8, 128)  # [slot, byte, bitpos, out]
            w = np.stack(
                [w4[:, :, 7 - kbit, :].reshape(k * 16, 128) for kbit in range(8)]
            )
        mats.append(np.ascontiguousarray(w))
        p = gcm_pow(p, k)
    return tuple(mats)


def ghash_step_matrix(h: int, k: int) -> np.ndarray:
    """int8[128,128] transposed multiply-by-H^k matrix: ``bits @ M`` (mod 2)
    multiplies a row of node bits by H^k — the between-group fold of the
    fused Pallas GHASH tree kernel (ops/ghash_pallas.ghash_tree_pallas).
    Folding sequentially over G groups of k blocks,
    ``T = (T * H^k) ^ node_g``, yields exactly
    ``sum_g node_g * H^(k*(G-1-g))`` — the same T(C) the grouped-power
    ladder computes level by level, with no per-level HBM materialization.
    Same transposed row-vector convention as the ladder operands and
    ``mult_matrix(...).T`` final fold in ops/gcm.py."""
    return np.ascontiguousarray(mult_matrix(gcm_pow(h, k)).T.astype(np.int8))


def ghash_reference(h: int, blocks: list[bytes]) -> int:
    """Straightforward serial GHASH for testing: Y_i = (Y_{i-1} ^ X_i) * H."""
    y = 0
    for b in blocks:
        y = gcm_mult(y ^ int.from_bytes(b.ljust(16, b"\x00"), "big"), h)
    return y
