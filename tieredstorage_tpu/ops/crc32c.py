"""CRC32C (Castagnoli) as a GF(2) linear-map tree on the MXU.

CRC with init=0/xorout=0 ("crc0") is linear over GF(2) in the message bits,
so per-16-byte-block contributions are a 32x128 bit matrix, and combining a
left span with a right span of k bytes is `Z^k(left) ^ right` where Z is the
32x32 zero-byte state-evolution matrix. A log-tree with per-level matrices
(Z^(16*2^j), squared host-side) reduces a whole chunk batch with int8 matmuls
mod 2 — the same machinery as the GHASH kernel (ops/gcm.py).

The standard CRC32C (init 0xFFFFFFFF, xorout 0xFFFFFFFF) is recovered with a
length-dependent affine offset: crc(M) = crc0(M) ^ crc(0^len), the latter
computed host-side in O(log len) matrix powers. Used for integrity accounting
of transformed chunks (the reference has no integrity checksum of its own —
it relies on the object stores' checksums; this is an extension that the
manifest can carry per chunk).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_POLY_REFLECTED = 0x82F63B78


def crc32c_reference(data: bytes, init: int = 0xFFFFFFFF, xorout: int = 0xFFFFFFFF) -> int:
    """Bitwise software CRC32C (host oracle)."""
    crc = init
    for byte in data:
        crc ^= byte
        for _ in range(8):
            crc = (crc >> 1) ^ (_POLY_REFLECTED if crc & 1 else 0)
    return crc ^ xorout


def _crc0(data: bytes) -> int:
    return crc32c_reference(data, init=0, xorout=0)


_HOST_TABLE: list | None = None


def crc32c_host(data: bytes) -> int:
    """Table-driven host CRC32C — the fast path for host-side framing (the
    e2e broker sim's v2 record batches use it; Kafka's batch CRC is CRC32C).
    The bitwise `crc32c_reference` above stays the independent oracle."""
    global _HOST_TABLE
    if _HOST_TABLE is None:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (_POLY_REFLECTED if crc & 1 else 0)
            table.append(crc)
        _HOST_TABLE = table
    crc = 0xFFFFFFFF
    table = _HOST_TABLE
    for byte in data:
        crc = table[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _bits32(v: int) -> np.ndarray:
    return np.frombuffer(v.to_bytes(4, "big"), dtype=np.uint8)[:, None] >> np.arange(
        7, -1, -1, dtype=np.uint8
    ) & 1


def _bits32_vec(v: int) -> np.ndarray:
    return _bits32(v).reshape(32).astype(np.uint8)


def _vec32_to_int(bits: np.ndarray) -> int:
    packed = np.packbits(bits.astype(np.uint8).reshape(4, 8), axis=1, bitorder="big")
    return int.from_bytes(packed.tobytes(), "big")


@functools.cache
def _leaf_matrix() -> np.ndarray:
    """uint8[32,128]: bits32(crc0(block)) = L @ bits(block), MSB-first bits."""
    m = np.zeros((32, 128), dtype=np.uint8)
    for bit in range(128):
        block = bytearray(16)
        block[bit // 8] = 0x80 >> (bit % 8)
        m[:, bit] = _bits32_vec(_crc0(bytes(block)))
    return m


@functools.cache
def _zero_byte_matrix() -> np.ndarray:
    """uint8[32,32]: state evolution over ONE zero byte."""
    m = np.zeros((32, 32), dtype=np.uint8)
    for bit in range(32):
        # Column for basis state e_bit (MSB-first indexing of the uint32),
        # evolved through one zero byte with the bitwise step.
        crc_val = 1 << (31 - bit)
        for _ in range(8):
            crc_val = (crc_val >> 1) ^ (_POLY_REFLECTED if crc_val & 1 else 0)
        m[:, bit] = _bits32_vec(crc_val)
    return m


def _mat_mod2(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return (a.astype(np.int64) @ b.astype(np.int64) % 2).astype(np.uint8)


def _mat_pow(m: np.ndarray, e: int) -> np.ndarray:
    result = np.eye(m.shape[0], dtype=np.uint8)
    base = m
    while e:
        if e & 1:
            result = _mat_mod2(result, base)
        base = _mat_mod2(base, base)
        e >>= 1
    return result


@functools.cache
def _level_matrices(levels: int) -> np.ndarray:
    """int8[levels,32,32] transposed: level j combines spans of 16*2^j bytes."""
    z16 = _mat_pow(_zero_byte_matrix(), 16)
    mats = np.zeros((levels, 32, 32), dtype=np.int8)
    m = z16
    for j in range(levels):
        mats[j] = m.T.astype(np.int8)
        m = _mat_mod2(m, m)
    return mats


@functools.cache
def _length_offset(length: int) -> int:
    """crc32c of `length` zero bytes, via matrix powers (O(log n))."""
    state = _mat_pow(_zero_byte_matrix(), length) @ _bits32_vec(0xFFFFFFFF) % 2
    return _vec32_to_int(state) ^ 0xFFFFFFFF


# numpy, not jnp: a module-level device array would initialize the JAX
# backend (and dial the axon relay) at import time.
_BIT_SHIFTS = np.arange(7, -1, -1, dtype=np.uint8)


@functools.partial(jax.jit, static_argnames=("chunk_bytes", "levels"))
def _crc0_batch(data: jnp.ndarray, leaf_t: jnp.ndarray, level_mats: jnp.ndarray,
                *, chunk_bytes: int, levels: int) -> jnp.ndarray:
    batch = data.shape[0]
    n_blocks = chunk_bytes // 16
    blocks = data.reshape(batch, n_blocks, 16)
    bits = ((blocks[..., None] >> _BIT_SHIFTS) & 1).reshape(batch, n_blocks, 128)
    vals = (
        jax.lax.dot_general(
            bits.astype(jnp.int8), leaf_t, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        & 1
    ).astype(jnp.uint8)  # [batch, n_blocks, 32]
    # Left-pad to a power of two with zero states (crc0 of zero bytes = 0,
    # and prepending zero bytes to the left span is the identity here
    # because Z^k(0) = 0).
    m_pow2 = 1 << levels
    if m_pow2 > n_blocks:
        vals = jnp.concatenate(
            [jnp.zeros((batch, m_pow2 - n_blocks, 32), jnp.uint8), vals], axis=1
        )
    for j in range(levels):
        pairs = vals.reshape(batch, -1, 2, 32)
        left, right = pairs[:, :, 0, :], pairs[:, :, 1, :]
        shifted = (
            jax.lax.dot_general(
                left.astype(jnp.int8), level_mats[j], (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            & 1
        ).astype(jnp.uint8)
        vals = shifted ^ right
    return vals[:, 0, :]  # [batch, 32] bit vectors


def crc32c_constants(chunk_bytes: int):
    """Host-precomputed constants for `crc32c_chunks_device` at a chunk size."""
    if chunk_bytes % 16:
        raise ValueError("chunk_bytes must be a multiple of 16")
    n_blocks = chunk_bytes // 16
    levels = max(1, (n_blocks - 1).bit_length())
    return (
        jnp.asarray(_leaf_matrix().T.astype(np.int8)),
        jnp.asarray(_level_matrices(levels)),
        chunk_bytes,
        levels,
        np.uint32(_length_offset(chunk_bytes)),
    )


def crc32c_chunks_device(data, leaf_t, level_mats, chunk_bytes, levels, length_offset):
    """Device-resident CRC32C: uint8[batch, chunk_bytes] -> uint32[batch].

    Composable under an outer jit/shard_map (unlike `crc32c_chunks`, which
    round-trips through numpy on the host).
    """
    bits = _crc0_batch(data, leaf_t, level_mats, chunk_bytes=chunk_bytes, levels=levels)
    weights = jnp.asarray((1 << np.arange(31, -1, -1)).astype(np.uint32))
    vals = jnp.sum(bits.astype(jnp.uint32) * weights, axis=1)
    return vals ^ jnp.uint32(length_offset)


#: Below this many total bytes in a same-length group, the jit dispatch costs
#: more than the table loop; the host path takes over.
_BATCH_MIN_BYTES = 1 << 16


def crc32c_batch(chunks) -> list[int]:
    """CRC32C of each chunk in a heterogeneous batch (the scrubber's verify
    primitive).

    Same-length groups are LEFT-zero-padded to a 16-byte multiple and reduced
    through the MXU log-tree in one `crc32c_chunks` call — left padding is
    free for the math (crc0(0^k || M) = crc0(M), since Z^k(0) = 0 and the
    zero prefix contributes nothing), so only the length-offset term needs
    swapping: crc(M) = kernel(0^k||M) ^ crc(0^lenP) ^ crc(0^lenM). Small
    groups fall back to the table-driven host CRC, so CPU-only deployments
    (and tiny scrub batches) never pay a device dispatch.
    """
    chunks = list(chunks)
    out: list[Optional[int]] = [None] * len(chunks)
    groups: dict[int, list[int]] = {}
    for i, c in enumerate(chunks):
        groups.setdefault(len(c), []).append(i)
    for length, idxs in groups.items():
        if length == 0:
            for i in idxs:
                out[i] = 0  # crc32c(b"") == 0
            continue
        padded = -(-length // 16) * 16
        if length * len(idxs) < _BATCH_MIN_BYTES:
            for i in idxs:
                out[i] = crc32c_host(chunks[i])
            continue
        mat = np.zeros((len(idxs), padded), dtype=np.uint8)
        for row, i in enumerate(idxs):
            mat[row, padded - length:] = np.frombuffer(chunks[i], dtype=np.uint8)
        crcs = crc32c_chunks(mat)
        fix = 0 if padded == length else (
            _length_offset(padded) ^ _length_offset(length)
        )
        for row, i in enumerate(idxs):
            out[i] = int(crcs[row]) ^ fix
    return out  # type: ignore[return-value]


def crc32c_chunks(data: np.ndarray) -> np.ndarray:
    """uint32[batch] CRC32C of each row of uint8[batch, chunk_bytes].

    chunk_bytes must currently be a multiple of 16 (transformed chunks are
    padded by the caller; arbitrary tails fold host-side if needed).
    """
    data = np.asarray(data, dtype=np.uint8)
    batch, chunk_bytes = data.shape
    if chunk_bytes % 16:
        raise ValueError("chunk_bytes must be a multiple of 16")
    n_blocks = chunk_bytes // 16
    levels = max(1, (n_blocks - 1).bit_length())
    bits = _crc0_batch(
        jnp.asarray(data),
        jnp.asarray(_leaf_matrix().T.astype(np.int8)),
        jnp.asarray(_level_matrices(levels)),
        chunk_bytes=chunk_bytes,
        levels=levels,
    )
    bits = np.asarray(bits)
    weights = (1 << np.arange(31, -1, -1, dtype=np.uint64)).astype(np.uint64)
    crc0_vals = (bits.astype(np.uint64) * weights).sum(axis=1).astype(np.uint64)
    # crc(M) = crc0(M) ^ crc(0^len); crc(0^len) already includes init+xorout.
    return (crc0_vals ^ np.uint64(_length_offset(chunk_bytes))).astype(np.uint32)
