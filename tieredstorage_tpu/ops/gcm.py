"""Batched AES-256-GCM over whole chunk arrays (the TPU transform hot path).

One call encrypts/decrypts uint8[batch, chunk_bytes] with per-chunk IVs and a
shared key+AAD (the per-segment DEK+AAD of the security layer), producing the
same bytes as the host AES-GCM oracle:

- CTR keystream: the block cipher (ops/aes.py) runs over all counter blocks
  of the whole batch at once; counter 1 yields the tag mask E(J0), counters
  2.. encrypt the data (NIST SP 800-38D).
- GHASH: a grouped-power reduction where each level contracts 128 blocks at
  once via one [B*G, 128*128] x [128*128, 128] GF(2) bit-matrix matmul on the
  MXU (slot j carries H^(127-j); ops/gf128.py builds the stacked operands) —
  log128(m) big matmuls instead of log2(m) pairwise tree levels. Per-segment
  constants (AAD contribution, length block) fold into one host-computed
  128-bit vector.

Shapes are static per (chunk_bytes, batch); the TPU transform backend keys
its jit cache on them.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from tieredstorage_tpu.ops import gf128
from tieredstorage_tpu.ops.aes import aes_encrypt_blocks, key_expansion
from tieredstorage_tpu.ops.aes_bitsliced import ctr_keystream_batch
from tieredstorage_tpu.utils.locks import new_lock

TAG_SIZE = 16


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


@dataclasses.dataclass(frozen=True, eq=False)  # identity hash: weakly cacheable
class GcmContext:
    """Host-precomputed per-(key, aad, chunk_size) constants for the kernel."""

    round_keys: np.ndarray       # uint8[15,16]
    agg_mats: tuple              # per-level int8[k*128,128] grouped operands
    final_mat: np.ndarray        # int8[128,128] transposed mult-by-H^2 matrix
    const_bits: np.ndarray       # uint8[128] = bits(T(A)*H^(mC+2) ^ L*H)
    chunk_bytes: int
    n_blocks: int                # ceil(chunk_bytes/16)
    #: int8[128,128] transposed mult-by-H^k1 between-group fold matrix of
    #: the fused GHASH tree kernel (gf128.ghash_step_matrix).
    step_mat: np.ndarray = None


@functools.lru_cache(maxsize=16)
def _derive_h(key: bytes) -> tuple[np.ndarray, int]:
    """Round keys and the GHASH key H = E_K(0^128) for an AES-256 key."""
    round_keys = key_expansion(key)
    h_block = np.asarray(
        aes_encrypt_blocks(jnp.asarray(round_keys), jnp.zeros((1, 16), jnp.uint8))
    )[0]
    return round_keys, int.from_bytes(h_block.tobytes(), "big")


@functools.lru_cache(maxsize=64)
def _context_cached(key: bytes, aad: bytes, chunk_bytes: int) -> GcmContext:
    round_keys, h = _derive_h(key)

    m_c = _ceil_div(chunk_bytes, 16)
    agg_mats = gf128.ghash_agg_matrices(h, m_c)

    # T(A) = sum_i A_i H^(mA-i) over the AAD blocks (zero-padded).
    aad_blocks = [aad[i : i + 16] for i in range(0, len(aad), 16)]
    t_a = 0
    for i, blk in enumerate(aad_blocks):
        power = gf128.gcm_pow(h, len(aad_blocks) - 1 - i)
        t_a ^= gf128.gcm_mult(int.from_bytes(blk.ljust(16, b"\x00"), "big"), power)

    # Length block: 64-bit bit-lengths of AAD and ciphertext.
    len_block = int.from_bytes(
        (len(aad) * 8).to_bytes(8, "big") + (chunk_bytes * 8).to_bytes(8, "big"), "big"
    )
    # GHASH(A||C||L) = T(A)*H^(mC+2) ^ T(C)*H^2 ^ L*H.
    const = gf128.gcm_mult(t_a, gf128.gcm_pow(h, m_c + 2)) ^ gf128.gcm_mult(
        len_block, h
    )
    final_mat = gf128.mult_matrix(gf128.gcm_mult(h, h))  # H^2

    return GcmContext(
        round_keys=round_keys,
        agg_mats=agg_mats,
        final_mat=np.ascontiguousarray(final_mat.T.astype(np.int8)),
        const_bits=gf128.int_to_bitvec(const),
        chunk_bytes=chunk_bytes,
        n_blocks=m_c,
        step_mat=gf128.ghash_step_matrix(h, agg_mats[0].shape[1] // 16),
    )


def make_context(key: bytes, aad: bytes, chunk_bytes: int) -> GcmContext:
    if len(key) != 32:
        raise ValueError("AES-256 key required")
    if chunk_bytes <= 0:
        raise ValueError("chunk_bytes must be positive")
    return _context_cached(bytes(key), bytes(aad), chunk_bytes)


# --- device-side helpers ---

# numpy, not jnp: a module-level device array would initialize the JAX
# backend (and dial the axon relay) at import time.
_BIT_SHIFTS = np.arange(7, -1, -1, dtype=np.uint8)


def _bits_to_bytes(bits: jnp.ndarray) -> jnp.ndarray:
    b = bits.reshape(bits.shape[:-1] + (bits.shape[-1] // 8, 8)).astype(jnp.uint8)
    weights = (jnp.uint8(1) << _BIT_SHIFTS).astype(jnp.uint8)
    return (b * weights).sum(axis=-1, dtype=jnp.uint32).astype(jnp.uint8)


def _ghash_grouped(
    data_flat: jnp.ndarray, agg_mats: tuple, step_mat=None
) -> jnp.ndarray:
    """data_flat uint8[B, m*16] -> T(C) = sum_i C_i H^(m-1-i), uint8[B, 128].

    Three strategies, best-available first:

    - **Fused tree kernel** (`ghash_pallas.ghash_tree_pallas`, ISSUE 13):
      with `step_mat` and more than one aggregation level, the WHOLE
      reduction runs as one Pallas kernel — in-kernel plane extraction,
      level-1 matmuls, and the level-2+ aggregation as a sequential
      per-group fold of a VMEM accumulator (``T = (T @ M_{H^k1}) ^
      node_g``). Zero inter-stage HBM materialization: payload in, [B,128]
      node bits out.
    - **Level-1 kernel + XLA ladder**: level 1 contracts the 8 byte-bit
      planes in-kernel (bytes cross HBM once); levels >= 2 contract k
      128-bit node vectors at a time via [B*G, k*128] x [k*128, 128] XLA
      matmuls, one [B, G, 128] HBM round trip per level.
    - **Pure XLA**: the plane stack materializes in HBM (8 B/B) before the
      same ladder.

    Each ladder level left-pads to a multiple of its group width (leading
    zero blocks are the polynomial's identity). All three compute the same
    function the former pairwise tree did (gf128.ghash_agg_matrices);
    `planned_hbm_roundtrips` mirrors this branch for the per-window
    accounting, so keep them in sync."""
    batch = data_flat.shape[0]
    w1 = agg_mats[0]
    k1 = w1.shape[1] // 16
    m = data_flat.shape[1] // 16
    g = _ceil_div(m, k1)
    pad_bytes = (g * k1 - m) * 16
    if pad_bytes:
        data_flat = jnp.concatenate(
            [jnp.zeros((batch, pad_bytes), jnp.uint8), data_flat], axis=1
        )
    from tieredstorage_tpu.ops import ghash_pallas

    if (
        step_mat is not None
        and len(agg_mats) > 1
        and ghash_pallas.use_pallas_ghash_tree(batch, g, k1 * 16)
        and ghash_pallas.pallas_ghash_tree_available()
    ):
        import logging

        from tieredstorage_tpu.ops._preflight import interpret_off_device

        return ghash_pallas.ghash_tree_pallas(
            data_flat,
            w1,
            step_mat,
            interpret=interpret_off_device(
                logging.getLogger(__name__), "Pallas GHASH tree"
            ),
        ).astype(jnp.uint8)
    if ghash_pallas.use_pallas_ghash(
        batch * g, k1 * 16
    ) and ghash_pallas.pallas_ghash_available():
        # In-kernel plane extraction: bytes cross HBM once instead of as
        # 8 materialized int8 planes (ghash_pallas.py, which pads the row
        # count to its own grid internally).
        rows = batch * g
        mat = data_flat.reshape(rows, k1 * 16)
        # interpret off-TPU lets the forced path run (slowly) anywhere; the
        # backend probe can raise (like in the gates) and degrades to
        # interpret rather than aborting the trace (ops/_preflight.py).
        import logging

        from tieredstorage_tpu.ops._preflight import interpret_off_device

        x = ghash_pallas.ghash_level1_pallas(
            mat,
            w1,
            interpret=interpret_off_device(
                logging.getLogger(__name__), "Pallas GHASH level 1"
            ),
        ).reshape(batch, g, 128)
    else:
        planes = jnp.stack(
            [(data_flat >> np.uint8(kbit)) & np.uint8(1) for kbit in range(8)]
        ).astype(jnp.int8)
        x = (
            jax.lax.dot_general(
                planes.reshape(8, batch * g, k1 * 16),
                w1,
                (((0, 2), (0, 1)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            & 1
        ).astype(jnp.int8).reshape(batch, g, 128)
    for w in agg_mats[1:]:
        k = w.shape[0] // 128
        m = x.shape[1]
        g = _ceil_div(m, k)
        pad = g * k - m
        if pad:
            x = jnp.concatenate([jnp.zeros((batch, pad, 128), jnp.int8), x], axis=1)
        x = (
            jax.lax.dot_general(
                x.reshape(batch * g, k * 128), w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            & 1
        ).astype(jnp.int8).reshape(batch, g, 128)
    return x[:, 0, :].astype(jnp.uint8)


def _ghash_of_ct(
    ct_padded: jnp.ndarray,
    agg_mats: tuple, final_mat: jnp.ndarray, const_bits: jnp.ndarray,
    step_mat=None,
) -> jnp.ndarray:
    """ct_padded uint8[B, m*16] (tail already zeroed) -> GHASH bits [B,128]."""
    t_c = _ghash_grouped(ct_padded, agg_mats, step_mat)
    ghash = (
        jax.lax.dot_general(
            t_c.astype(jnp.int8), final_mat, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        & 1
    ).astype(jnp.uint8)
    return ghash ^ const_bits


@functools.partial(
    jax.jit, static_argnames=("chunk_bytes", "n_blocks", "decrypt")
)
def _gcm_process_batch(
    round_keys: jnp.ndarray,
    ivs: jnp.ndarray,
    data: jnp.ndarray,
    agg_mats: tuple,
    final_mat: jnp.ndarray,
    const_bits: jnp.ndarray,
    step_mat=None,
    *,
    chunk_bytes: int,
    n_blocks: int,
    decrypt: bool,
):
    """Shared encrypt/decrypt core. data uint8[B, chunk_bytes].

    Returns (output uint8[B, chunk_bytes], tags uint8[B, 16]); the tag is
    always computed over the CIPHERTEXT (input when decrypting, output when
    encrypting).
    """
    batch = data.shape[0]
    padded_len = n_blocks * 16

    ks = ctr_keystream_batch(round_keys, ivs, 1, n_blocks + 1)  # [B, n_blocks+1, 16]
    tag_mask = ks[:, 0, :]
    keystream = ks[:, 1:, :].reshape(batch, padded_len)[:, :chunk_bytes]

    output = data ^ keystream

    ct = data if decrypt else output
    if padded_len != chunk_bytes:
        ct_padded = jnp.zeros((batch, padded_len), jnp.uint8).at[:, :chunk_bytes].set(ct)
    else:
        ct_padded = ct
    ghash = _ghash_of_ct(ct_padded, agg_mats, final_mat, const_bits, step_mat)
    tags = _bits_to_bytes(ghash) ^ tag_mask
    return output, tags


# --- dispatch accounting ---

#: Device-program launches issued by this module's public entry points.
#: The transform backend reads per-thread deltas around each window, which
#: makes the "one fused dispatch per window" invariant testable without a
#: TPU. The process-wide total is guarded (concurrent backends on gateway
#: worker threads would tear a bare increment — races checker); the delta
#: source is THREAD-LOCAL so one backend's window never absorbs a sibling
#: thread's launches into its own count.
_DISPATCHES = [0]
_DISPATCH_MU = new_lock("gcm._DISPATCH_MU")
_DISPATCH_TLS = threading.local()


def device_dispatches() -> int:
    """Total GCM device-program launches issued so far in this process."""
    return _DISPATCHES[0]


def thread_dispatches() -> int:
    """GCM launches issued by the CALLING thread (exact delta source for
    per-window accounting under concurrent backends)."""
    return getattr(_DISPATCH_TLS, "count", 0)


def _count_dispatch() -> None:
    with _DISPATCH_MU:
        _DISPATCHES[0] += 1
    _DISPATCH_TLS.count = getattr(_DISPATCH_TLS, "count", 0) + 1


#: Payload-scale HBM round trips between the stages of the GCM window
#: program (ISSUE 13). Same process-wide + thread-local accounting shape as
#: the launch counter above; the transform backend reads per-thread deltas
#: around each window so `make transform-demo` can gate
#: hbm_roundtrips_per_window <= 1 without a TPU. The count is STATIC (host
#: logic mirroring the branch _ghash_grouped traces) — the runtime ground
#: truth remains the measured GiB/s at relay windows.
_ROUNDTRIPS = [0]
_ROUNDTRIP_TLS = threading.local()


def device_hbm_roundtrips() -> int:
    """Total inter-stage HBM round trips dispatched so far in this process."""
    return _ROUNDTRIPS[0]


def thread_hbm_roundtrips() -> int:
    """Inter-stage HBM round trips dispatched by the CALLING thread."""
    return getattr(_ROUNDTRIP_TLS, "count", 0)


def _count_roundtrips(n: int) -> None:
    with _DISPATCH_MU:
        _ROUNDTRIPS[0] += n
    _ROUNDTRIP_TLS.count = getattr(_ROUNDTRIP_TLS, "count", 0) + n


def planned_hbm_roundtrips(ctx, rows: int) -> int:
    """Stage boundaries of the GCM program that materialize a payload-scale
    intermediate in HBM, for a window of `rows` rows (PER-SHARD rows under
    a mesh — each shard traces the same program). Mirrors the strategy
    branch in `_ghash_grouped` — keep the two in sync (the fused-closure
    checker in analysis/dispatch.py pins the trace side).

    Counted:

    - 1 always — the keystream handoff: the AES kernel (or XLA circuit)
      writes its bit-plane output to HBM once; the unpack + XOR fuse into
      its consumer. This is the ONE round trip the two-kernel pipeline is
      allowed (the window's own input staging and output fetch are
      transfers, counted separately as h2d/d2h).
    - +1 per XLA grouped-power ladder level >= 2 — each level materializes
      its [B, G, 128] node tensor between matmuls.
    - +1 when GHASH level 1 runs as the XLA plane path — the 8-plane int8
      expansion (8 B of HBM traffic per payload byte).
    - +0 when the fused tree kernel engages: level 1 and every aggregation
      level run inside one kernel, nodes never leave VMEM.

    The varlen sequence assembly (mask, length-block scatter, rotation) is
    elementwise/gather work XLA fuses into the level-1 operand read, not a
    stage boundary."""
    from tieredstorage_tpu.ops import ghash_pallas

    agg_mats = ctx.agg_mats
    m = ctx.n_blocks if isinstance(ctx, GcmContext) else ctx.m_cap
    k1 = agg_mats[0].shape[1] // 16
    g = _ceil_div(m, k1)
    count = 1  # keystream planes: AES kernel -> unpack/XOR fusion
    tree = (
        getattr(ctx, "step_mat", None) is not None
        and len(agg_mats) > 1
        and ghash_pallas.use_pallas_ghash_tree(rows, g, k1 * 16)
        and ghash_pallas.pallas_ghash_tree_available()
    )
    if not tree:
        count += len(agg_mats) - 1
        if not (
            ghash_pallas.use_pallas_ghash(rows * g, k1 * 16)
            and ghash_pallas.pallas_ghash_available()
        ):
            count += 1
    return count


# Device-resident copies of each context's constant arrays, uploaded once
# per context instead of once per window call (the round keys, GHASH level
# matrices, and folded constants are identical for every window of a
# segment). Weak keying lets evicted lru_cache contexts free their HBM.
_DEVICE_CONSTS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _device_consts(ctx) -> tuple:
    try:
        return _DEVICE_CONSTS[ctx]
    except KeyError:
        pass
    if isinstance(ctx, GcmContext):
        consts = (
            jnp.asarray(ctx.round_keys),
            tuple(jnp.asarray(m) for m in ctx.agg_mats),
            jnp.asarray(ctx.final_mat),
            jnp.asarray(ctx.const_bits),
        )
    else:
        consts = (
            jnp.asarray(ctx.round_keys),
            jnp.asarray(ctx.aad_blocks),
            tuple(jnp.asarray(m) for m in ctx.agg_mats),
            jnp.asarray(ctx.h_mat),
        )
    _DEVICE_CONSTS[ctx] = consts
    return consts


# Device-resident fold matrices of the tree kernel, cached separately so
# `_device_consts`'s tuple arity (unpacked by the profiling tools) stays
# stable. Same weak keying as above.
_DEVICE_STEP_MATS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _device_step_mat(ctx):
    """Device copy of the context's tree fold matrix (None when absent)."""
    if getattr(ctx, "step_mat", None) is None:
        return None
    try:
        return _DEVICE_STEP_MATS[ctx]
    except KeyError:
        mat = jnp.asarray(ctx.step_mat)
        _DEVICE_STEP_MATS[ctx] = mat
        return mat


def gcm_encrypt_chunks(ctx: GcmContext, ivs: np.ndarray, plaintext: np.ndarray):
    """plaintext uint8[B, ctx.chunk_bytes], ivs uint8[B,12] ->
    (ciphertext uint8[B, chunk_bytes], tags uint8[B,16])."""
    round_keys, agg_mats, final_mat, const_bits = _device_consts(ctx)
    _count_dispatch()
    _count_roundtrips(planned_hbm_roundtrips(ctx, len(plaintext)))
    ct, tags = _gcm_process_batch(
        round_keys,
        jnp.asarray(ivs, dtype=jnp.uint8),
        jnp.asarray(plaintext, dtype=jnp.uint8),
        agg_mats,
        final_mat,
        const_bits,
        _device_step_mat(ctx),
        chunk_bytes=ctx.chunk_bytes,
        n_blocks=ctx.n_blocks,
        decrypt=False,
    )
    return ct, tags


# --- variable-length batches (encrypt-after-compress path) ---
#
# Chunks in one batch may have different byte lengths (compressed sizes).
# The CTR keystream pads/truncates trivially; for GHASH, each row's block
# sequence [AAD blocks, C blocks, length block] is built left-aligned and
# then rotated right so it ends exactly at the tree's last slot — leading
# zero blocks don't change the polynomial, so one fixed-shape tree tags all
# rows correctly regardless of their true lengths.


@dataclasses.dataclass(frozen=True, eq=False)  # identity hash: weakly cacheable
class GcmVarlenContext:
    round_keys: np.ndarray   # uint8[15,16]
    aad_blocks: np.ndarray   # uint8[m_A,16] zero-padded AAD blocks
    agg_mats: tuple          # per-level int8[k*128,128] grouped operands
    h_mat: np.ndarray        # int8[128,128] transposed mult-by-H matrix
    aad_bit_len: int
    max_bytes: int
    m_max: int               # max data blocks
    m_cap: int               # sequence slots (AAD + data + length block)
    #: int8[128,128] transposed mult-by-H^k1 between-group fold matrix of
    #: the fused GHASH tree kernel (gf128.ghash_step_matrix).
    step_mat: np.ndarray = None


@functools.lru_cache(maxsize=64)
def _varlen_context_cached(key: bytes, aad: bytes, max_bytes: int) -> GcmVarlenContext:
    round_keys, h = _derive_h(key)
    m_max = _ceil_div(max_bytes, 16)
    m_a = _ceil_div(len(aad), 16)
    seq_len = m_a + m_max + 1
    aad_padded = np.frombuffer(
        aad + b"\x00" * (m_a * 16 - len(aad)), dtype=np.uint8
    ).reshape(m_a, 16) if m_a else np.zeros((0, 16), np.uint8)
    agg_mats = gf128.ghash_agg_matrices(h, seq_len)
    return GcmVarlenContext(
        round_keys=round_keys,
        aad_blocks=aad_padded,
        agg_mats=agg_mats,
        h_mat=np.ascontiguousarray(gf128.mult_matrix(h).T.astype(np.int8)),
        aad_bit_len=len(aad) * 8,
        max_bytes=max_bytes,
        m_max=m_max,
        m_cap=seq_len,
        step_mat=gf128.ghash_step_matrix(h, agg_mats[0].shape[1] // 16),
    )


def bucket_max_bytes(n: int) -> int:
    """Round a varlen batch's max chunk size up to a bounded ladder.

    With compression on, nearly every chunk window has a distinct max
    compressed size; using it directly as the jit-static shape would trigger
    a fresh multi-second XLA compile of the whole varlen GCM program per
    window (round-1 VERDICT weak 2). The ladder quantizes shapes to
    eighth-steps of the next power of two: at most ~4 cache entries per
    octave, ≤25% padded compute, and a steady-state hit rate of ~100% since
    real workloads cluster around one compressed-size regime."""
    if n <= 1024:
        return 1024
    step = 1 << max(4, (n - 1).bit_length() - 3)
    return step * _ceil_div(n, step)


def make_varlen_context(key: bytes, aad: bytes, max_bytes: int) -> GcmVarlenContext:
    if len(key) != 32:
        raise ValueError("AES-256 key required")
    return _varlen_context_cached(bytes(key), bytes(aad), bucket_max_bytes(max_bytes))


@functools.partial(
    jax.jit, static_argnames=("max_bytes", "m_max", "m_a", "m_cap", "decrypt")
)
def _gcm_varlen_batch(
    round_keys, ivs, data, lengths, len_blocks, aad_blocks, agg_mats, h_mat,
    step_mat=None,
    *, max_bytes: int, m_max: int, m_a: int, m_cap: int, decrypt: bool,
):
    """data uint8[B, max_bytes] left-aligned (zero tail), lengths int32[B],
    len_blocks uint8[B,16] (host-built GCM length blocks).
    Returns (output uint8[B, max_bytes], tags uint8[B, 16])."""
    batch = data.shape[0]

    ks = ctr_keystream_batch(round_keys, ivs, 1, m_max + 1)
    tag_mask = ks[:, 0, :]
    keystream = ks[:, 1:, :].reshape(batch, m_max * 16)[:, :max_bytes]

    byte_mask = (
        jnp.arange(max_bytes, dtype=jnp.int32)[None, :] < lengths[:, None]
    ).astype(jnp.uint8)
    output = (data ^ keystream) * byte_mask

    ct = data if decrypt else output  # ct is already masked in both directions
    ct_blocks = ct.reshape(batch, m_max, 16)

    n_blocks = _ceil_div_dev(lengths)  # int32[B] data blocks per row
    seq = jnp.concatenate(
        [
            jnp.broadcast_to(aad_blocks, (batch, m_a, 16)).astype(jnp.uint8),
            ct_blocks,
            jnp.zeros((batch, m_cap - m_a - m_max, 16), jnp.uint8),
        ],
        axis=1,
    )
    # Place each row's length block right after its data blocks.
    l_pos = m_a + n_blocks  # int32[B]
    onehot = (
        jnp.arange(m_cap, dtype=jnp.int32)[None, :] == l_pos[:, None]
    ).astype(jnp.uint8)
    seq = seq ^ (onehot[:, :, None] * len_blocks[:, None, :])
    # Rotate right so the sequence ends at slot m_cap-1.
    shift = m_cap - (l_pos + 1)
    idx = (jnp.arange(m_cap, dtype=jnp.int32)[None, :] - shift[:, None]) % m_cap
    seq = jnp.take_along_axis(seq, idx[:, :, None], axis=1)

    t = _ghash_grouped(seq.reshape(batch, -1), agg_mats, step_mat)
    ghash = (
        jax.lax.dot_general(
            t.astype(jnp.int8), h_mat, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        & 1
    ).astype(jnp.uint8)
    tags = _bits_to_bytes(ghash) ^ tag_mask
    return output, tags


def _ceil_div_dev(lengths: jnp.ndarray) -> jnp.ndarray:
    return (lengths + 15) // 16


def _host_len_blocks(ctx: GcmVarlenContext, lengths: np.ndarray) -> np.ndarray:
    out = np.zeros((len(lengths), 16), dtype=np.uint8)
    for i, l in enumerate(lengths):
        out[i] = np.frombuffer(
            ctx.aad_bit_len.to_bytes(8, "big") + (int(l) * 8).to_bytes(8, "big"),
            dtype=np.uint8,
        )
    return out


def _run_varlen(ctx: GcmVarlenContext, ivs, data, lengths, decrypt: bool):
    lengths = np.asarray(lengths, dtype=np.int32)
    round_keys, aad_blocks, agg_mats, h_mat = _device_consts(ctx)
    _count_dispatch()
    _count_roundtrips(planned_hbm_roundtrips(ctx, len(lengths)))
    return _gcm_varlen_batch(
        round_keys,
        jnp.asarray(ivs, dtype=jnp.uint8),
        jnp.asarray(data, dtype=jnp.uint8),
        jnp.asarray(lengths),
        jnp.asarray(_host_len_blocks(ctx, lengths)),
        aad_blocks,
        agg_mats,
        h_mat,
        _device_step_mat(ctx),
        max_bytes=ctx.max_bytes,
        m_max=ctx.m_max,
        m_a=ctx.aad_blocks.shape[0],
        m_cap=ctx.m_cap,
        decrypt=decrypt,
    )


def gcm_encrypt_varlen(ctx: GcmVarlenContext, ivs, plaintext, lengths):
    """plaintext uint8[B, ctx.max_bytes] (rows zero-padded past their length)."""
    return _run_varlen(ctx, ivs, plaintext, lengths, decrypt=False)


def gcm_decrypt_varlen(ctx: GcmVarlenContext, ivs, ciphertext, lengths):
    """Returns (plaintext, expected_tags) — caller verifies tags."""
    return _run_varlen(ctx, ivs, ciphertext, lengths, decrypt=True)


def gcm_decrypt_chunks(ctx: GcmContext, ivs: np.ndarray, ciphertext: np.ndarray):
    """Returns (plaintext uint8[B, chunk_bytes], expected_tags uint8[B,16]).

    The caller compares expected_tags against the received tags (constant-time
    comparison is not required server-side here, but verification is
    mandatory — the TPU transform backend raises on mismatch)."""
    round_keys, agg_mats, final_mat, const_bits = _device_consts(ctx)
    _count_dispatch()
    _count_roundtrips(planned_hbm_roundtrips(ctx, len(ciphertext)))
    return _gcm_process_batch(
        round_keys,
        jnp.asarray(ivs, dtype=jnp.uint8),
        jnp.asarray(ciphertext, dtype=jnp.uint8),
        agg_mats,
        final_mat,
        const_bits,
        _device_step_mat(ctx),
        chunk_bytes=ctx.chunk_bytes,
        n_blocks=ctx.n_blocks,
        decrypt=True,
    )


# --- fused single-dispatch windows (the production transform path) ---
#
# One jit executable per window: CTR keystream -> XOR -> GHASH -> tag fold
# in a single device program whose ONE output buffer packs `output || tag`
# per row. On the measured harness every extra launch or fetch pays a
# ~62 ms size-independent floor (PROFILE.md), so the window path dispatches
# once and fetches once per window. The input is staged in the same packed
# shape uint8[B, n_bytes + TAG_SIZE] (tail bytes ignored — on decrypt they
# can simply carry the received tag), which makes the output shape
# identical to the input's so XLA can DONATE the staged buffer into the
# result: steady-state windows reuse one HBM allocation instead of
# allocating input + output per window.
#
# Passing ivs=None (and for varlen lengths=None) switches the per-row
# metadata to ride IN the packed tail — [iv 12 B][length u32 LE 4 B] after
# the payload columns — so a window crosses the host→device link as ONE
# buffer: no side transfers for IVs, lengths, or length blocks (the GCM
# length block is then rebuilt in-graph, bit-identical to
# `_host_len_blocks`).


def _packed_fixed_impl(
    round_keys, ivs, data_packed, agg_mats, final_mat, const_bits,
    step_mat=None,
    *, chunk_bytes: int, n_blocks: int, decrypt: bool,
):
    if ivs is None:  # trace-time branch: IVs ride the packed tail
        ivs = data_packed[:, chunk_bytes : chunk_bytes + 12]
    out, tags = _gcm_process_batch(
        round_keys, ivs, data_packed[:, :chunk_bytes], agg_mats, final_mat,
        const_bits, step_mat,
        chunk_bytes=chunk_bytes, n_blocks=n_blocks, decrypt=decrypt,
    )
    return jnp.concatenate([out, tags], axis=1)


def _device_len_blocks(lengths: jnp.ndarray, aad_bit_len: int) -> jnp.ndarray:
    """uint8[B, 16] GCM length blocks built in-graph — bit-identical to
    `_host_len_blocks` (64-bit big-endian AAD and ciphertext bit lengths)
    without needing x64: big-endian byte j of (lengths * 8) is
    lengths >> (8*(7-j) - 3), and the bytes whose shift would overflow
    int32 are zero for any length below 2^37 bytes (chunks are capped two
    orders below that)."""
    batch = lengths.shape[0]
    aad_half = jnp.broadcast_to(
        jnp.asarray(
            np.frombuffer(int(aad_bit_len).to_bytes(8, "big"), dtype=np.uint8)
        ),
        (batch, 8),
    )
    cols = []
    for j in range(8):
        shift = 8 * (7 - j) - 3
        if shift >= 31:
            cols.append(jnp.zeros((batch,), jnp.uint8))
        elif shift >= 0:
            cols.append(((lengths >> shift) & 0xFF).astype(jnp.uint8))
        else:
            cols.append(((lengths & 0x1F) << 3).astype(jnp.uint8))
    return jnp.concatenate([aad_half, jnp.stack(cols, axis=1)], axis=1)


def _packed_varlen_impl(
    round_keys, ivs, data_packed, lengths, len_blocks, aad_blocks, agg_mats,
    h_mat, step_mat=None,
    *, aad_bit_len: int, max_bytes: int, m_max: int, m_a: int,
    m_cap: int, decrypt: bool,
):
    if ivs is None:
        ivs = data_packed[:, max_bytes : max_bytes + 12]
    if lengths is None:
        lb = data_packed[:, max_bytes + 12 : max_bytes + 16].astype(jnp.int32)
        lengths = lb[:, 0] | (lb[:, 1] << 8) | (lb[:, 2] << 16) | (lb[:, 3] << 24)
    if len_blocks is None:
        len_blocks = _device_len_blocks(lengths, aad_bit_len)
    out, tags = _gcm_varlen_batch(
        round_keys, ivs, data_packed[:, :max_bytes], lengths, len_blocks,
        aad_blocks, agg_mats, h_mat, step_mat,
        max_bytes=max_bytes, m_max=m_max,
        m_a=m_a, m_cap=m_cap, decrypt=decrypt,
    )
    return jnp.concatenate([out, tags], axis=1)


def _require_tail_metadata(*side_args) -> None:
    if any(a is not None for a in side_args):
        raise ValueError(
            "sharded packed windows read per-row metadata from the packed "
            "tail columns: pass ivs=None (and lengths=None) so the window "
            "crosses the host->device link as one row-sharded buffer"
        )


def _packed_fixed_sharded(mesh):
    """`_packed_fixed_impl` fanned out over a 1-D device mesh: the packed
    buffer's row axis is sharded (every row is independent — keystream,
    XOR, GHASH and tag are all row-local, so no collectives), the GCM
    constants are replicated, and in/out carry the SAME row sharding so
    jit can still donate the staged input as the output allocation."""
    from tieredstorage_tpu.parallel.mesh import DATA_AXIS, shard_map_compat
    from jax.sharding import PartitionSpec as P

    row, rep = P(DATA_AXIS, None), P()

    def run(
        round_keys, ivs, data_packed, agg_mats, final_mat, const_bits,
        step_mat=None,
        *, chunk_bytes: int, n_blocks: int, decrypt: bool,
    ):
        _require_tail_metadata(ivs)

        def body(rk, dp, am, fm, cb, sm):
            return _packed_fixed_impl(
                rk, None, dp, am, fm, cb, sm,
                chunk_bytes=chunk_bytes, n_blocks=n_blocks, decrypt=decrypt,
            )

        return shard_map_compat(
            body, mesh=mesh, in_specs=(rep, row, rep, rep, rep, rep),
            out_specs=row, check_vma=False,
        )(round_keys, data_packed, agg_mats, final_mat, const_bits, step_mat)

    return run


def _packed_varlen_sharded(mesh):
    """Varlen counterpart of `_packed_fixed_sharded`: per-row lengths ride
    the packed tail, so each shard rebuilds its own GCM length blocks
    in-graph and no cross-chip exchange is needed."""
    from tieredstorage_tpu.parallel.mesh import DATA_AXIS, shard_map_compat
    from jax.sharding import PartitionSpec as P

    row, rep = P(DATA_AXIS, None), P()

    def run(
        round_keys, ivs, data_packed, lengths, len_blocks, aad_blocks,
        agg_mats, h_mat, step_mat=None,
        *, aad_bit_len: int, max_bytes: int, m_max: int, m_a: int,
        m_cap: int, decrypt: bool,
    ):
        _require_tail_metadata(ivs, lengths, len_blocks)

        def body(rk, dp, ab, am, hm, sm):
            return _packed_varlen_impl(
                rk, None, dp, None, None, ab, am, hm, sm,
                aad_bit_len=aad_bit_len, max_bytes=max_bytes, m_max=m_max,
                m_a=m_a, m_cap=m_cap, decrypt=decrypt,
            )

        return shard_map_compat(
            body, mesh=mesh, in_specs=(rep, row, rep, rep, rep, rep),
            out_specs=row, check_vma=False,
        )(round_keys, data_packed, aad_blocks, agg_mats, h_mat, step_mat)

    return run


@functools.lru_cache(maxsize=16)
def _packed_jit(varlen: bool, donate: bool, mesh=None):
    """One jit executable per (shape family, donation, mesh) combination.

    With a mesh the impl runs under shard_map (row axis over the chips) but
    the call is still ONE logical dispatch — the launch counter and
    `DispatchStats` count it as one, which keeps the one-dispatch-per-window
    invariant meaningful across mesh sizes. `data_packed` stays argument 2
    in every spelling so donation always targets the staged window buffer.
    """
    if mesh is not None:
        fn = _packed_varlen_sharded(mesh) if varlen else _packed_fixed_sharded(mesh)
    else:
        fn = _packed_varlen_impl if varlen else _packed_fixed_impl
    static = (
        ("aad_bit_len", "max_bytes", "m_max", "m_a", "m_cap", "decrypt")
        if varlen
        else ("chunk_bytes", "n_blocks", "decrypt")
    )
    return jax.jit(
        fn, static_argnames=static, donate_argnums=(2,) if donate else ()
    )


def gcm_window_packed(
    ctx: GcmContext,
    ivs,
    data_packed,
    *,
    decrypt: bool,
    donate: bool = False,
    mesh=None,
):
    """Fused fixed-size window: data_packed uint8[B, chunk_bytes + 16] ->
    packed uint8[B, chunk_bytes + 16] where row i is `output_i || tag_i` —
    one device dispatch, one output buffer. With ivs=None the per-row IV
    is read from the packed tail (bytes [chunk_bytes, chunk_bytes+12));
    otherwise the tail columns are ignored. The tag is over the ciphertext
    in both directions (expected tag on decrypt; the caller verifies).
    `donate=True` hands the staged input buffer to XLA for reuse as the
    output — the caller must not touch data_packed afterwards. With `mesh`
    (a 1-D data mesh; batch divisible by its size, metadata in the tail)
    the one program fans out across every chip via shard_map, output rows
    sharded identically to the input's so donation still aliases."""
    round_keys, agg_mats, final_mat, const_bits = _device_consts(ctx)
    _count_dispatch()
    rows = data_packed.shape[0] // (mesh.size if mesh is not None else 1)
    _count_roundtrips(planned_hbm_roundtrips(ctx, rows))
    return _packed_jit(False, donate, mesh)(
        round_keys,
        None if ivs is None else jnp.asarray(ivs, dtype=jnp.uint8),
        jnp.asarray(data_packed, dtype=jnp.uint8),
        agg_mats,
        final_mat,
        const_bits,
        _device_step_mat(ctx),
        chunk_bytes=ctx.chunk_bytes,
        n_blocks=ctx.n_blocks,
        decrypt=decrypt,
    )


def gcm_varlen_window_packed(
    ctx: GcmVarlenContext,
    ivs,
    data_packed,
    lengths,
    *,
    decrypt: bool,
    donate: bool = False,
    mesh=None,
):
    """Fused variable-length window: data_packed uint8[B, max_bytes + 16]
    (rows left-aligned with a ZERO payload tail — GHASH requires it) ->
    packed uint8[B, max_bytes + 16] = `masked output || tag` per row. With
    ivs=None and lengths=None the per-row metadata rides the packed tail
    ([iv 12 B][length u32 LE 4 B] at columns [max_bytes, max_bytes+16))
    and the GCM length blocks are rebuilt in-graph, so the whole window is
    ONE host→device buffer. Same single-dispatch/donation/mesh contract as
    `gcm_window_packed` (sharded windows require the tail-metadata form)."""
    if lengths is not None:
        lengths = np.asarray(lengths, dtype=np.int32)
    round_keys, aad_blocks, agg_mats, h_mat = _device_consts(ctx)
    _count_dispatch()
    rows = data_packed.shape[0] // (mesh.size if mesh is not None else 1)
    _count_roundtrips(planned_hbm_roundtrips(ctx, rows))
    return _packed_jit(True, donate, mesh)(
        round_keys,
        None if ivs is None else jnp.asarray(ivs, dtype=jnp.uint8),
        jnp.asarray(data_packed, dtype=jnp.uint8),
        None if lengths is None else jnp.asarray(lengths),
        None if lengths is None else jnp.asarray(_host_len_blocks(ctx, lengths)),
        aad_blocks,
        agg_mats,
        h_mat,
        _device_step_mat(ctx),
        aad_bit_len=ctx.aad_bit_len,
        max_bytes=ctx.max_bytes,
        m_max=ctx.m_max,
        m_a=ctx.aad_blocks.shape[0],
        m_cap=ctx.m_cap,
        decrypt=decrypt,
    )


#: Public alias for composing the GCM core under an outer jit/shard_map
#: (e.g. the multichip dry-run step); same contract as `_gcm_process_batch`.
gcm_process_batch_device = _gcm_process_batch
