"""Batched AES-256-GCM over whole chunk arrays (the TPU transform hot path).

One call encrypts/decrypts uint8[batch, chunk_bytes] with per-chunk IVs and a
shared key+AAD (the per-segment DEK+AAD of the security layer), producing the
same bytes as the host AES-GCM oracle:

- CTR keystream: the block cipher (ops/aes.py) runs over all counter blocks
  of the whole batch at once; counter 1 yields the tag mask E(J0), counters
  2.. encrypt the data (NIST SP 800-38D).
- GHASH: a log-tree reduction where level j multiplies by H^(2^j) via a
  128x128 GF(2) bit matrix (ops/gf128.py), i.e. int8 matmuls mod 2 on the
  MXU. Per-segment constants (AAD contribution, length block) fold into one
  host-computed 128-bit vector.

Shapes are static per (chunk_bytes, batch); the TPU transform backend keys
its jit cache on them.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from tieredstorage_tpu.ops import gf128
from tieredstorage_tpu.ops.aes import aes_encrypt_blocks, ctr_keystream, key_expansion

TAG_SIZE = 16


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


@dataclasses.dataclass(frozen=True)
class GcmContext:
    """Host-precomputed per-(key, aad, chunk_size) constants for the kernel."""

    round_keys: np.ndarray       # uint8[15,16]
    level_mats: np.ndarray       # int8[levels,128,128] transposed mult matrices
    final_mat: np.ndarray        # int8[128,128] transposed mult-by-H^2 matrix
    const_bits: np.ndarray       # uint8[128] = bits(T(A)*H^(mC+2) ^ L*H)
    chunk_bytes: int
    n_blocks: int                # ceil(chunk_bytes/16)
    levels: int                  # log2 of padded block count


@functools.lru_cache(maxsize=64)
def _context_cached(key: bytes, aad: bytes, chunk_bytes: int) -> GcmContext:
    round_keys = key_expansion(key)
    # H = E_K(0^128), computed with the same cipher host-side via numpy/jax cpu.
    h_block = np.asarray(
        aes_encrypt_blocks(jnp.asarray(round_keys), jnp.zeros((1, 16), jnp.uint8))
    )[0]
    h = int.from_bytes(h_block.tobytes(), "big")

    m_c = _ceil_div(chunk_bytes, 16)
    levels = max(1, (m_c - 1).bit_length())  # tree over next pow2 >= m_c

    level_mats = gf128.ghash_level_matrices(h, levels)

    # T(A) = sum_i A_i H^(mA-i) over the AAD blocks (zero-padded).
    aad_blocks = [aad[i : i + 16] for i in range(0, len(aad), 16)]
    t_a = 0
    for i, blk in enumerate(aad_blocks):
        power = gf128.gcm_pow(h, len(aad_blocks) - 1 - i)
        t_a ^= gf128.gcm_mult(int.from_bytes(blk.ljust(16, b"\x00"), "big"), power)

    # Length block: 64-bit bit-lengths of AAD and ciphertext.
    len_block = int.from_bytes(
        (len(aad) * 8).to_bytes(8, "big") + (chunk_bytes * 8).to_bytes(8, "big"), "big"
    )
    # GHASH(A||C||L) = T(A)*H^(mC+2) ^ T(C)*H^2 ^ L*H.
    const = gf128.gcm_mult(t_a, gf128.gcm_pow(h, m_c + 2)) ^ gf128.gcm_mult(
        len_block, h
    )
    final_mat = gf128.mult_matrix(gf128.gcm_mult(h, h))  # H^2

    return GcmContext(
        round_keys=round_keys,
        level_mats=np.ascontiguousarray(
            level_mats.transpose(0, 2, 1).astype(np.int8)
        ),
        final_mat=np.ascontiguousarray(final_mat.T.astype(np.int8)),
        const_bits=gf128.int_to_bitvec(const),
        chunk_bytes=chunk_bytes,
        n_blocks=m_c,
        levels=levels,
    )


def make_context(key: bytes, aad: bytes, chunk_bytes: int) -> GcmContext:
    if len(key) != 32:
        raise ValueError("AES-256 key required")
    if chunk_bytes <= 0:
        raise ValueError("chunk_bytes must be positive")
    return _context_cached(bytes(key), bytes(aad), chunk_bytes)


# --- device-side helpers ---

_BIT_SHIFTS = jnp.arange(7, -1, -1, dtype=jnp.uint8)


def _bytes_to_bits(x: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., n] -> uint8[..., n*8], MSB-first per byte (GCM bit order)."""
    bits = (x[..., None] >> _BIT_SHIFTS) & 1
    return bits.reshape(x.shape[:-1] + (x.shape[-1] * 8,))

def _bits_to_bytes(bits: jnp.ndarray) -> jnp.ndarray:
    b = bits.reshape(bits.shape[:-1] + (bits.shape[-1] // 8, 8)).astype(jnp.uint8)
    weights = (jnp.uint8(1) << _BIT_SHIFTS).astype(jnp.uint8)
    return (b * weights).sum(axis=-1, dtype=jnp.uint32).astype(jnp.uint8)


def _ghash_tree(bits: jnp.ndarray, level_mats: jnp.ndarray, levels: int) -> jnp.ndarray:
    """bits uint8[B, m, 128] (m = 2^levels) -> T(C) bits uint8[B, 128]."""
    for j in range(levels):
        pairs = bits.reshape(bits.shape[0], -1, 2, 128)
        left, right = pairs[:, :, 0, :], pairs[:, :, 1, :]
        prod = (
            jax.lax.dot_general(
                left.astype(jnp.int8),
                level_mats[j],
                (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            & 1
        ).astype(jnp.uint8)
        bits = prod ^ right
    return bits[:, 0, :]


def _ghash_of_ct(
    ct_padded: jnp.ndarray, ctx_levels: int, n_blocks: int,
    level_mats: jnp.ndarray, final_mat: jnp.ndarray, const_bits: jnp.ndarray,
) -> jnp.ndarray:
    """ct_padded uint8[B, n_blocks*16] (tail already zeroed) -> GHASH bits [B,128]."""
    batch = ct_padded.shape[0]
    blocks_bits = _bytes_to_bits(ct_padded.reshape(batch, n_blocks, 16))
    m_pow2 = 1 << ctx_levels
    if m_pow2 > n_blocks:
        # Left-pad with zero blocks: leading zeros don't change the polynomial.
        pad = jnp.zeros((batch, m_pow2 - n_blocks, 128), jnp.uint8)
        blocks_bits = jnp.concatenate([pad, blocks_bits], axis=1)
    t_c = _ghash_tree(blocks_bits, level_mats, ctx_levels)
    ghash = (
        jax.lax.dot_general(
            t_c.astype(jnp.int8), final_mat, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        & 1
    ).astype(jnp.uint8)
    return ghash ^ const_bits


@functools.partial(
    jax.jit, static_argnames=("chunk_bytes", "n_blocks", "levels", "decrypt")
)
def _gcm_process_batch(
    round_keys: jnp.ndarray,
    ivs: jnp.ndarray,
    data: jnp.ndarray,
    level_mats: jnp.ndarray,
    final_mat: jnp.ndarray,
    const_bits: jnp.ndarray,
    *,
    chunk_bytes: int,
    n_blocks: int,
    levels: int,
    decrypt: bool,
):
    """Shared encrypt/decrypt core. data uint8[B, chunk_bytes].

    Returns (output uint8[B, chunk_bytes], tags uint8[B, 16]); the tag is
    always computed over the CIPHERTEXT (input when decrypting, output when
    encrypting).
    """
    batch = data.shape[0]
    padded_len = n_blocks * 16

    ks = jax.vmap(
        lambda iv: ctr_keystream(round_keys, iv, 1, n_blocks + 1)
    )(ivs)  # [B, n_blocks+1, 16]
    tag_mask = ks[:, 0, :]
    keystream = ks[:, 1:, :].reshape(batch, padded_len)[:, :chunk_bytes]

    output = data ^ keystream

    ct = data if decrypt else output
    if padded_len != chunk_bytes:
        ct_padded = jnp.zeros((batch, padded_len), jnp.uint8).at[:, :chunk_bytes].set(ct)
    else:
        ct_padded = ct
    ghash = _ghash_of_ct(ct_padded, levels, n_blocks, level_mats, final_mat, const_bits)
    tags = _bits_to_bytes(ghash) ^ tag_mask
    return output, tags


def gcm_encrypt_chunks(ctx: GcmContext, ivs: np.ndarray, plaintext: np.ndarray):
    """plaintext uint8[B, ctx.chunk_bytes], ivs uint8[B,12] ->
    (ciphertext uint8[B, chunk_bytes], tags uint8[B,16])."""
    ct, tags = _gcm_process_batch(
        jnp.asarray(ctx.round_keys),
        jnp.asarray(ivs, dtype=jnp.uint8),
        jnp.asarray(plaintext, dtype=jnp.uint8),
        jnp.asarray(ctx.level_mats),
        jnp.asarray(ctx.final_mat),
        jnp.asarray(ctx.const_bits),
        chunk_bytes=ctx.chunk_bytes,
        n_blocks=ctx.n_blocks,
        levels=ctx.levels,
        decrypt=False,
    )
    return ct, tags


def gcm_decrypt_chunks(ctx: GcmContext, ivs: np.ndarray, ciphertext: np.ndarray):
    """Returns (plaintext uint8[B, chunk_bytes], expected_tags uint8[B,16]).

    The caller compares expected_tags against the received tags (constant-time
    comparison is not required server-side here, but verification is
    mandatory — the TPU transform backend raises on mismatch)."""
    return _gcm_process_batch(
        jnp.asarray(ctx.round_keys),
        jnp.asarray(ivs, dtype=jnp.uint8),
        jnp.asarray(ciphertext, dtype=jnp.uint8),
        jnp.asarray(ctx.level_mats),
        jnp.asarray(ctx.final_mat),
        jnp.asarray(ctx.const_bits),
        chunk_bytes=ctx.chunk_bytes,
        n_blocks=ctx.n_blocks,
        levels=ctx.levels,
        decrypt=True,
    )
