"""Bitsliced AES-256-CTR keystream: boolean circuit, no gathers.

The table-form cipher in ops/aes.py spends its time in per-byte 256-entry
gathers — the worst op class for a TPU vector unit. This module replaces
SubBytes with a programmatically derived composite-field boolean circuit
(GF(2^8) inverse computed in GF((2^4)^2), Satoh/Canright-style tower): the
whole cipher becomes XOR/AND on uint32 bitplanes packed 32 blocks per lane —
pure VPU work at full vector throughput.

Every matrix/tensor in the circuit is DERIVED here from the field definitions
(FIPS-197 polynomial 0x11B, GF(16) polynomial y^4+y+1) and validated against
the generated S-box table in tests — nothing is hand-transcribed.

Layout: state is uint32[16, 8, W] — byte position (FIPS column-major), bit
index (LSB first), and W packed words, word w bit j = block 32*w + j.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from tieredstorage_tpu.ops.aes import SBOX, _NR, _SHIFT_ROWS, _gf8_mult, key_expansion

# ---------------------------------------------------------------------------
# Host-side derivation of the tower-field S-box circuit (numpy, cached)
# ---------------------------------------------------------------------------


def _gf16_mult(a: int, b: int) -> int:
    p = 0
    while b:
        if b & 1:
            p ^= a
        a <<= 1
        if a & 0x10:
            a ^= 0x13  # y^4 + y + 1
        b >>= 1
    return p


def _gf8_pow(a: int, n: int) -> int:
    r = 1
    while n:
        if n & 1:
            r = _gf8_mult(r, a)
        a = _gf8_mult(a, a)
        n >>= 1
    return r


@functools.cache
def _tower() -> dict:
    """Derive the GF(256) ≅ GF((2^4)^2) isomorphism and circuit constants."""
    # Generator of GF(256)*.
    g = next(
        c for c in range(2, 256)
        if len({_gf8_pow(c, i) for i in range(255)}) == 255
    )
    # The subfield GF(16) inside GF(256) is {0} ∪ {g^(17k)}; find an element u
    # with u^4 + u + 1 = 0 so GF(2)[y]/(y^4+y+1) maps y ↦ u.
    u = next(
        x
        for k in range(1, 15)
        for x in [_gf8_pow(g, 17 * k)]
        if _gf8_pow(x, 4) ^ x ^ 1 == 0
    )

    def embed16(v: int) -> int:
        """GF(16) element (bits over y) → GF(256) element (bits over x)."""
        out = 0
        for i in range(4):
            if (v >> i) & 1:
                out ^= _gf8_pow(u, i)
        return out

    # λ ∈ GF(16) such that t^2 + t + λ is irreducible over GF(16) and a root
    # V exists in GF(256): V^2 + V = embed(λ). Search both.
    lam, V = next(
        (l, v)
        for l in range(1, 16)
        for v in range(1, 256)
        if _gf8_mult(v, v) ^ v == embed16(l)
        # irreducibility over GF(16): no root w in GF(16)
        and all(_gf16_mult(w, w) ^ w ^ l != 0 for w in range(16))
    )

    # Basis of GF(256) over GF(2): b ⊕ a·V with a,b ∈ GF(16) on basis u^i.
    # M maps composite coords (b0..b3, a0..a3) → AES bits.
    M = np.zeros((8, 8), dtype=np.uint8)
    for i in range(4):
        col_b = embed16(1 << i)
        col_a = _gf8_mult(embed16(1 << i), V)
        for bit in range(8):
            M[bit, i] = (col_b >> bit) & 1
            M[bit, 4 + i] = (col_a >> bit) & 1
    Minv = _gf2_inv(M)

    # AES affine layer bits: S(x) = Aff(inv(x)); Aff(v)_i = v_i ^ v_{i+4} ^
    # v_{i+5} ^ v_{i+6} ^ v_{i+7} ^ const_i (FIPS-197 §5.1.1).
    A = np.zeros((8, 8), dtype=np.uint8)
    for i in range(8):
        for j in (0, 4, 5, 6, 7):
            A[i, (i + j) % 8] ^= 1

    # Fold: input linear = Minv (AES bits → composite), output linear = A @ M
    # (composite → AES bits then affine), constant 0x63.
    lin_in = Minv % 2
    lin_out = (A @ M) % 2

    # GF(16) multiply tensor: out_k = XOR_{i,j} T[k,i,j] u_i v_j.
    T = np.zeros((4, 4, 4), dtype=np.uint8)
    for i in range(4):
        for j in range(4):
            prod = _gf16_mult(1 << i, 1 << j)
            for k in range(4):
                T[k, i, j] = (prod >> k) & 1

    # x ↦ λ·x² over GF(16): linear (Frobenius + scale), as a 4×4 bit matrix.
    SqLam = np.zeros((4, 4), dtype=np.uint8)
    for i in range(4):
        v = _gf16_mult(lam, _gf16_mult(1 << i, 1 << i))
        for k in range(4):
            SqLam[k, i] = (v >> k) & 1

    # GF(16) inverse as algebraic normal form (Möbius transform of the truth
    # table): inv_anf[k] = set of monomial masks whose XOR gives bit k.
    inv_table = [0] + [next(y for y in range(16) if _gf16_mult(x, y) == 1)
                       for x in range(1, 16)]
    inv_anf: list[list[int]] = []
    for k in range(4):
        f = [(inv_table[x] >> k) & 1 for x in range(16)]
        coeff = list(f)
        for i in range(4):  # Möbius transform over the 4-cube
            for mask in range(16):
                if mask & (1 << i):
                    coeff[mask] ^= coeff[mask ^ (1 << i)]
        inv_anf.append([m for m in range(16) if coeff[m]])

    return {
        "lin_in": lin_in,
        "lin_out": lin_out,
        "const": 0x63,
        "mult": T,
        "sq_lam": SqLam,
        "inv_anf": inv_anf,
    }


def _gf2_inv(m: np.ndarray) -> np.ndarray:
    n = m.shape[0]
    aug = np.concatenate([m.copy() % 2, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = next(r for r in range(col, n) if aug[r, col])
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        for r in range(n):
            if r != col and aug[r, col]:
                aug[r] ^= aug[col]
    return aug[:, n:]


# ---------------------------------------------------------------------------
# Device-side circuit on uint32 bitplanes
# ---------------------------------------------------------------------------


def _linear4(mat: np.ndarray, bits: list[jnp.ndarray]) -> list[jnp.ndarray]:
    """Apply a GF(2) matrix (rows = outputs) to a list of planes via XORs."""
    out = []
    for row in mat:
        terms = [bits[i] for i in range(len(bits)) if row[i]]
        acc = terms[0]
        for t in terms[1:]:
            acc = acc ^ t
        out.append(acc)
    return out


def _gf16_mul_planes(t: np.ndarray, u: list, v: list) -> list:
    prods = {}
    out = []
    for k in range(4):
        acc = None
        for i in range(4):
            for j in range(4):
                if t[k, i, j]:
                    if (i, j) not in prods:
                        prods[(i, j)] = u[i] & v[j]
                    acc = prods[(i, j)] if acc is None else acc ^ prods[(i, j)]
        out.append(acc)
    return out


def _gf16_inv_planes(anf: list[list[int]], x: list) -> list:
    ones = jnp.full_like(x[0], 0xFFFFFFFF)
    monomials: dict[int, jnp.ndarray] = {0: ones}
    for m in range(1, 16):
        low = m & (-m)
        rest = m ^ low
        if rest == 0:
            monomials[m] = x[low.bit_length() - 1]
    for m in range(1, 16):
        if m not in monomials:
            low = m & (-m)
            monomials[m] = monomials[m ^ low] & monomials[low]
    out = []
    for k in range(4):
        acc = None
        for m in anf[k]:
            acc = monomials[m] if acc is None else acc ^ monomials[m]
        out.append(acc if acc is not None else jnp.zeros_like(x[0]))
    return out


def _sbox_planes(tw: dict, bits: list[jnp.ndarray]) -> list[jnp.ndarray]:
    """S-box over 8 bitplanes (any shape) via the tower circuit."""
    comp = _linear4(tw["lin_in"], bits)  # (b0..b3, a0..a3)
    b, a = comp[:4], comp[4:]
    # Δ = λa² ⊕ ab ⊕ b²  (b² is linear: square then no scale → use sq with λ=1)
    a_sq_lam = _linear4(tw["sq_lam"], a)
    ab = _gf16_mul_planes(tw["mult"], a, b)
    b_sq = _linear4(_sq_matrix(), b)
    delta = [a_sq_lam[i] ^ ab[i] ^ b_sq[i] for i in range(4)]
    dinv = _gf16_inv_planes(tw["inv_anf"], delta)
    a_out = _gf16_mul_planes(tw["mult"], a, dinv)
    apb = [a[i] ^ b[i] for i in range(4)]
    b_out = _gf16_mul_planes(tw["mult"], apb, dinv)
    res = _linear4(tw["lin_out"], b_out + a_out)
    const = tw["const"]
    return [
        # ~x, not x ^ jnp.uint32(-1): a scalar-const XOR materializes an
        # i32[] constant per call site, and the Pallas TPU lowering rejects
        # kernels that capture constants (~300 of them across 14 rounds —
        # seen on the real chip, round 5); bitwise NOT lowers constant-free.
        ~res[i] if (const >> i) & 1 else res[i]
        for i in range(8)
    ]


@functools.cache
def _sq_matrix() -> np.ndarray:
    m = np.zeros((4, 4), dtype=np.uint8)
    for i in range(4):
        v = _gf16_mult(1 << i, 1 << i)
        for k in range(4):
            m[k, i] = (v >> k) & 1
    return m


def _shift_rows_planes(state: jnp.ndarray) -> jnp.ndarray:
    return state[np.asarray(_SHIFT_ROWS)]


def _mix_columns_planes(state: jnp.ndarray) -> jnp.ndarray:
    """state uint32[16, 8, ...]; GF(2^8) xtime on bitplanes is a bit rotate
    with conditional feedback of bit 7 into bits {0,1,3,4} (poly 0x11B)."""
    s = state.reshape((4, 4) + state.shape[1:])  # [col, row, bit, ...]

    def xtime(x):
        top = x[:, :, 7]
        shifted = jnp.concatenate(
            [jnp.zeros_like(x[:, :, :1]), x[:, :, :-1]], axis=2
        )
        fb = jnp.zeros_like(shifted)
        for k in (0, 1, 3, 4):
            fb = fb.at[:, :, k].set(top)
        return shifted ^ fb

    rot1 = jnp.roll(s, -1, axis=1)
    rot2 = jnp.roll(s, -2, axis=1)
    rot3 = jnp.roll(s, -3, axis=1)
    out = xtime(s) ^ xtime(rot1) ^ rot1 ^ rot2 ^ rot3
    return out.reshape(state.shape)


def round_key_planes(round_keys: np.ndarray) -> np.ndarray:
    """uint8[15,16] round keys → uint32[15,16,8] full-word bit masks."""
    bits = (round_keys[..., None] >> np.arange(8)) & 1
    return (bits.astype(np.uint32) * 0xFFFFFFFF).astype(np.uint32)


def aes_encrypt_planes(rk_planes: jnp.ndarray, state: jnp.ndarray) -> jnp.ndarray:
    """Encrypt a bitsliced state uint32[16, 8, W] with AES-256.

    TSTPU_AES_SCAN=1 wraps the 13 middle rounds in a lax.scan: the traced
    graph shrinks ~14x (one round body instead of an unrolled cipher),
    which is the difference between a ~33-minute and a ~2-minute remote
    compile on the axon relay (round-5, artifacts_r5/probe_min.json) at
    identical per-byte math."""
    tw = _tower()
    state = state ^ rk_planes[0][..., None]

    def round_body(state, rk):
        planes = [state[:, b] for b in range(8)]
        planes = _sbox_planes(tw, planes)
        state = jnp.stack(planes, axis=1)
        state = _shift_rows_planes(state)
        state = _mix_columns_planes(state)
        return state ^ rk[..., None]

    if os.environ.get("TSTPU_AES_SCAN") == "1":
        state, _ = jax.lax.scan(
            lambda s, rk: (round_body(s, rk), None), state, rk_planes[1:_NR]
        )
    else:
        for rnd in range(1, _NR):
            state = round_body(state, rk_planes[rnd])
    planes = _sbox_planes(tw, [state[:, b] for b in range(8)])
    state = jnp.stack(planes, axis=1)
    state = _shift_rows_planes(state)
    return state ^ rk_planes[_NR][..., None]


def ctr_keystream_bitsliced(
    rk_planes: jnp.ndarray, iv: jnp.ndarray, first_counter: int, n_blocks: int
) -> jnp.ndarray:
    """Keystream uint8[n_blocks, 16] via the bitsliced cipher.

    n_blocks is rounded up to a multiple of 32 internally; callers slice.
    """
    w = (n_blocks + 31) // 32
    total = w * 32
    # Counter bytes 12..15 (big-endian); bit j of word w' ← block 32w'+j.
    n = first_counter + jnp.arange(total, dtype=jnp.uint32).reshape(w, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :]
    ctr_planes = []
    for byte_i, shift in enumerate((24, 16, 8, 0)):
        byte_v = (n >> shift) & 0xFF
        planes = []
        for b in range(8):
            bit = (byte_v >> b) & 1
            planes.append(jnp.sum(bit * weights, axis=1, dtype=jnp.uint32))
        ctr_planes.append(jnp.stack(planes))  # [8, w]
    # IV bytes 0..11: constant across blocks → full-word masks.
    iv_bits = ((iv.astype(jnp.uint32)[:, None] >> jnp.arange(8)[None, :]) & 1)
    iv_planes = (iv_bits * jnp.uint32(0xFFFFFFFF)).astype(jnp.uint32)  # [12, 8]
    state = jnp.concatenate(
        [
            jnp.broadcast_to(iv_planes[:, :, None], (12, 8, w)),
            jnp.stack(ctr_planes),  # [4, 8, w]
        ],
        axis=0,
    )  # [16, 8, w]
    out = aes_encrypt_planes(rk_planes, state)
    # Unpack: byte[pos, block 32w'+j] = Σ_b ((plane[pos,b,w'] >> j) & 1) << b
    j = jnp.arange(32, dtype=jnp.uint32)[None, None, None, :]
    bits = (out[..., None] >> j) & 1  # [16, 8, w, 32]
    weights_b = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))[None, :, None, None]
    bytes_ = jnp.sum(bits * weights_b, axis=1, dtype=jnp.uint32)  # [16, w, 32]
    ks = bytes_.transpose(1, 2, 0).reshape(total, 16).astype(jnp.uint8)
    return ks[:n_blocks]


def make_rk_planes(key: bytes) -> np.ndarray:
    return round_key_planes(key_expansion(key))


def rk_planes_from_round_keys(round_keys: jnp.ndarray) -> jnp.ndarray:
    """uint8[15,16] → uint32[15,16,8] masks, traceable (tiny; runs under jit)."""
    bits = (round_keys[..., None].astype(jnp.uint32) >> jnp.arange(8)) & 1
    return bits * jnp.uint32(0xFFFFFFFF)


_PALLAS_PREFLIGHT: list[bool] = []  # memoized: does the kernel lower+run here?


def _preflight_attempt() -> bool:
    from tieredstorage_tpu.ops.aes_pallas import (
        WORDS_PER_STEP,
        aes_encrypt_planes_pallas,
    )

    # The gate is consulted at TRACE time (ctr_keystream_batch runs
    # under the caller's jit), where omnistaging would turn these
    # constants into tracers and the bool() below into a
    # TracerBoolConversionError — which the handler would memoize as a
    # permanent False on perfectly healthy TPUs. Force eager evaluation.
    with jax.ensure_compile_time_eval():
        rk = rk_planes_from_round_keys(
            jnp.asarray(key_expansion(bytes(range(32))))
        )
        state = jnp.zeros((16, 8, WORDS_PER_STEP), jnp.uint32)
        out = jax.block_until_ready(aes_encrypt_planes_pallas(rk, state))
        # All input words are identical (zero), so EVERY output word
        # must equal the XLA circuit's — a lane/tile-indexing bug
        # anywhere in the step must fail the gate, not just word 0.
        ref = jax.block_until_ready(aes_encrypt_planes(rk, state[:, :, :1]))
        if not bool(jnp.all(out == ref)):  # pragma: no cover - platform-specific
            # Raise (deterministic class) so the fallback WARNS and the
            # transient budget isn't burned — same contract as ghash_pallas.
            raise AssertionError(
                "unsupported: kernel output diverges from the XLA circuit"
            )
        return True


def _pallas_preflight_ok() -> bool:
    """Compile and run the fused kernel once on a minimal tile.

    A Mosaic lowering or runtime failure on this platform must degrade to
    the XLA circuit, not take down the caller (the round-end benchmark runs
    unattended; an exception during its jit warmup would cost the artifact).
    Transient relay failures are retried in place before the verdict is
    memoized — the jit cache pins the first trace's verdict per shape, so a
    blip must not decide it (ops/_preflight.py)."""
    import logging

    from tieredstorage_tpu.ops._preflight import run_preflight

    return run_preflight(
        _PALLAS_PREFLIGHT,
        _preflight_attempt,
        logging.getLogger(__name__),
        "Pallas AES kernel unavailable on this platform, "
        "falling back to the XLA circuit: %s",
    )


_FORCED_CROSSCHECK: list[bool] = []  # memoized forced-path verdict


def _forced_crosscheck_ok() -> bool:
    """Output cross-check for the TIEREDSTORAGE_TPU_PALLAS=1 forced path.

    The forced path bypasses the preflight gate, so the import-time range
    check on TSTPU_AES_R used to be the ONLY guard — and a range-valid but
    behaviorally mistiled kernel (or a future tiling regression) would
    corrupt keystream silently. This runs the kernel BODY once per process
    with plain-array stand-ins (aes_pallas.kernel_body_reference — the
    R-dependent ShiftRows un-stack slicing included, no Mosaic needed, so
    it is cheap even on CPU where the forced path runs interpreted) against
    the XLA circuit on a position-distinct input, and fails LOUD on
    divergence: the caller explicitly forced the kernel, so silently
    falling back would mask exactly the corruption being guarded against."""
    if _FORCED_CROSSCHECK:
        if not _FORCED_CROSSCHECK[0]:
            raise RuntimeError(
                "Pallas AES kernel output diverges from the XLA circuit for "
                f"TSTPU_AES_R; refusing the forced TIEREDSTORAGE_TPU_PALLAS=1 "
                "path (keystream would be corrupted)"
            )
        return True
    from tieredstorage_tpu.ops import aes_pallas

    with jax.ensure_compile_time_eval():
        rk = rk_planes_from_round_keys(jnp.asarray(key_expansion(bytes(range(32)))))
        w = aes_pallas.WORDS_PER_STEP
        # Position-distinct, word-distinct input: a wrong un-stack slice
        # cannot alias to the right answer the way an all-zero state could.
        state = (
            jnp.arange(16 * 8 * w, dtype=jnp.uint32).reshape(16, 8, w)
            * jnp.uint32(2654435761)
        )
        got = jax.block_until_ready(aes_pallas.kernel_body_reference(rk, state))
        ref = jax.block_until_ready(aes_encrypt_planes(rk, state))
        ok = bool(jnp.all(got == ref))
    _FORCED_CROSSCHECK.append(ok)
    return _forced_crosscheck_ok()


def pallas_aes_available() -> bool:
    """Platform half of the kernel gate: can (or must) the kernel run here?

    CPU (tests, virtual meshes) keeps the XLA path — Mosaic interpret mode
    is orders slower to compile there. TIEREDSTORAGE_TPU_PALLAS=0/1
    overrides, but is read at trace time: set it before the first call for
    a given (batch, chunk) shape, or the cached executable wins. First TPU
    use preflights the kernel on a minimal tile and falls back to the XLA
    circuit if Mosaic can't lower or run it on this platform."""
    import os

    forced = os.environ.get("TIEREDSTORAGE_TPU_PALLAS")
    if forced is not None:
        if forced in ("0", "false", "off"):
            return False
        # The forced path skips the preflight, so it must run the output
        # cross-check itself — a mistiled TSTPU_AES_R fails loud here
        # instead of corrupting keystream silently.
        return _forced_crosscheck_ok()
    try:
        if jax.default_backend() not in ("tpu", "axon"):
            return False
    except Exception:
        return False
    return _pallas_preflight_ok()


def _use_pallas_circuit(n_words: int) -> bool:
    """Route the cipher through the fused Pallas kernel on real TPUs.

    The XLA lowering of the circuit round-trips every gate through HBM
    (0.66 GiB/s measured, PROFILE.md); the Pallas kernel keeps the planes
    in VMEM. Split gate: `aes_pallas.use_pallas_aes` is the pure-host shape
    eligibility (asserted on CPU by bench/CI), `pallas_aes_available` the
    platform/preflight half. A forced TIEREDSTORAGE_TPU_PALLAS=1 overrides
    the shape floor too — probes dispatch tiny tiles on purpose."""
    import os

    from tieredstorage_tpu.ops.aes_pallas import use_pallas_aes

    if os.environ.get("TIEREDSTORAGE_TPU_PALLAS") is not None:
        return pallas_aes_available()
    return use_pallas_aes(n_words) and pallas_aes_available()


def ctr_keystream_batch(
    round_keys: jnp.ndarray, ivs: jnp.ndarray, first_counter: int, n_blocks: int
) -> jnp.ndarray:
    """Keystream uint8[B, n_blocks, 16] for a batch of per-chunk IVs.

    One bitsliced cipher evaluation covers the whole batch: each chunk's
    blocks are packed into its own span of words (n_blocks rounded up to a
    multiple of 32), with that chunk's IV planes broadcast across its span.
    Replaces the vmapped per-chunk table cipher (gather-bound) with pure
    XOR/AND on uint32 lanes. On TPU the boolean circuit itself runs as the
    fused Pallas kernel (ops/aes_pallas.py)."""
    rk_planes = rk_planes_from_round_keys(round_keys)
    batch = ivs.shape[0]
    w = (n_blocks + 31) // 32
    total = w * 32
    # Counter planes are identical for every chunk: [4 bytes, 8 bits, w].
    n = first_counter + jnp.arange(total, dtype=jnp.uint32).reshape(w, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :]
    ctr_planes = []
    for shift in (24, 16, 8, 0):
        byte_v = (n >> shift) & 0xFF
        planes = [
            jnp.sum(((byte_v >> b) & 1) * weights, axis=1, dtype=jnp.uint32)
            for b in range(8)
        ]
        ctr_planes.append(jnp.stack(planes))
    ctr = jnp.stack(ctr_planes)  # [4, 8, w]
    # IV planes per chunk: [B, 12, 8] masks broadcast over the chunk's words.
    iv_bits = (ivs.astype(jnp.uint32)[..., None] >> jnp.arange(8)) & 1
    iv_planes = iv_bits * jnp.uint32(0xFFFFFFFF)  # [B, 12, 8]
    state = jnp.concatenate(
        [
            jnp.broadcast_to(iv_planes[..., None], (batch, 12, 8, w)),
            jnp.broadcast_to(ctr[None], (batch, 4, 8, w)),
        ],
        axis=1,
    )  # [B, 16, 8, w]
    # Fold batch into the word axis: [16, 8, B*w].
    state = state.transpose(1, 2, 0, 3).reshape(16, 8, batch * w)
    n_words = batch * w
    if _use_pallas_circuit(n_words):
        from tieredstorage_tpu.ops.aes_pallas import aes_encrypt_planes_pallas

        # interpret off-TPU lets the forced path run (slowly) anywhere;
        # the probe degrades to interpret instead of aborting the trace.
        # The op pads W to its own grid internally.
        import logging

        from tieredstorage_tpu.ops._preflight import interpret_off_device

        out = aes_encrypt_planes_pallas(
            rk_planes,
            state,
            interpret=interpret_off_device(
                logging.getLogger(__name__), "Pallas AES circuit"
            ),
        )
    else:
        out = aes_encrypt_planes(rk_planes, state)
    # Unpack to bytes: [16, 8, B, w] → [B, w*32, 16].
    out = out.reshape(16, 8, batch, w)
    j = jnp.arange(32, dtype=jnp.uint32)
    bits = (out[..., None] >> j) & 1  # [16, 8, B, w, 32]
    weights_b = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))[
        None, :, None, None, None
    ]
    bytes_ = jnp.sum(bits * weights_b, axis=1, dtype=jnp.uint32)  # [16, B, w, 32]
    ks = bytes_.transpose(1, 2, 3, 0).reshape(batch, total, 16).astype(jnp.uint8)
    return ks[:, :n_blocks]
