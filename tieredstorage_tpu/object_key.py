"""Object key factory: maps segment metadata to storage keys.

Reference: core/src/main/java/io/aiven/kafka/tieredstorage/ObjectKeyFactory.java —
layout `$(prefix)$(topic)-$(topicId)/$(partition)/$(20-digit offset)-$(segmentUuid).$(suffix)`
(mainPath :110-125, filenamePrefixFromOffset :130-145), suffixes
log/indexes/rsm-manifest (:44-48), optional masked prefix in string form
(ObjectKeyWithMaskedPrefix :182-195), and custom-metadata override of
prefix/main path (:96-108).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Mapping, Optional

from tieredstorage_tpu.metadata import RemoteLogSegmentMetadata
from tieredstorage_tpu.storage.core import ObjectKey


class Suffix(enum.Enum):
    LOG = "log"
    INDEXES = "indexes"
    MANIFEST = "rsm-manifest"


@dataclasses.dataclass(frozen=True)
class PlainObjectKey(ObjectKey):
    """value = prefix + mainPathAndSuffix; str(key) shows the full value."""

    prefix: str = ""
    main_path_and_suffix: str = ""

    @classmethod
    def of(cls, prefix: str, main_path_and_suffix: str) -> "PlainObjectKey":
        return cls(
            value=prefix + main_path_and_suffix,
            prefix=prefix,
            main_path_and_suffix=main_path_and_suffix,
        )


@dataclasses.dataclass(frozen=True)
class MaskedPrefixObjectKey(PlainObjectKey):
    """Same value, but logs/string form mask the prefix (log hygiene)."""

    def __str__(self) -> str:
        return "<prefix>/" + self.main_path_and_suffix


def filename_prefix_from_offset(offset: int) -> str:
    """Zero-pad offsets to 20 digits so object listings sort numerically."""
    return f"{offset:020d}"


def main_path(metadata: RemoteLogSegmentMetadata) -> str:
    segment_id = metadata.remote_log_segment_id
    tip = segment_id.topic_id_partition
    return (
        f"{tip.topic_partition.topic}-{tip.topic_id}"
        f"/{tip.topic_partition.partition}"
        f"/{filename_prefix_from_offset(metadata.start_offset)}-{segment_id.id}"
    )


class ObjectKeyFactory:
    def __init__(self, prefix: Optional[str], mask_prefix: bool = False):
        self.prefix = prefix or ""
        self._ctor = MaskedPrefixObjectKey.of if mask_prefix else PlainObjectKey.of

    def key(self, metadata: RemoteLogSegmentMetadata, suffix: Suffix) -> ObjectKey:
        return self._ctor(self.prefix, f"{main_path(metadata)}.{suffix.value}")

    def key_from_fields(
        self,
        fields: Mapping[int, object],
        metadata: RemoteLogSegmentMetadata,
        suffix: Suffix,
    ) -> ObjectKey:
        """Custom-metadata fields (OBJECT_PREFIX/OBJECT_KEY) override the
        configured prefix / derived main path, so fetches keep working after
        a `key.prefix` reconfiguration."""
        from tieredstorage_tpu.custom_metadata import SegmentCustomMetadataField

        prefix = str(fields.get(SegmentCustomMetadataField.OBJECT_PREFIX.index, self.prefix))
        main = str(fields.get(SegmentCustomMetadataField.OBJECT_KEY.index, main_path(metadata)))
        return self._ctor(prefix, f"{main}.{suffix.value}")
