"""Per-backend circuit breaker + retry budget: fail fast, retry bounded.

The HTTP transport already retries transient 5xx/429 with jittered backoff
(storage/httpclient.py); this layer sits above it and contains *sustained*
backend outages two ways:

- **Circuit breaker**: after `failure.threshold` consecutive
  StorageBackendExceptions the breaker opens and every call fails
  immediately with CircuitOpenException (no network), until a `cooldown.ms`
  period passes and a single half-open probe is allowed through — success
  closes the breaker, failure re-opens it. KeyNotFoundException /
  InvalidRangeException are contract responses from a healthy backend and
  count as successes.
- **Retry budget** (`retry.budget.*`): a token bucket that earns a fraction
  of a token per *successful* call and spends one whole token per retry, so
  the cluster-wide retry amplification factor is capped at
  1 + percent/100 (plus a fixed initial allowance). Unbounded per-call retry
  policies multiply: during an outage every caller retries, turning a
  backend brownout into a self-sustaining retry storm ("Overload Control for
  Scaling WeChat Microservices", SOSP 2018 measures exactly this spiral). A
  budget makes retries a *shared, earned* resource: when nothing succeeds,
  the bucket drains and the layer degrades to single attempts — which is
  what lets the breaker see the true failure rate and open.

Both are wired by the RSM behind `breaker.enabled` / `retry.budget.enabled`
(config/rsm_config.py); state and counters are exported as gauges via
metrics/rsm_metrics.register_resilience_metrics and transitions are recorded
as tracing events.
"""

from __future__ import annotations

import enum
import random
import time
from typing import BinaryIO, Callable, Mapping, Optional

from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
)
from tieredstorage_tpu.utils.locks import new_lock
from tieredstorage_tpu.utils.deadline import DeadlineExceededException, remaining_s


class BreakerState(enum.Enum):
    CLOSED = 0
    HALF_OPEN = 1
    OPEN = 2


class CircuitOpenException(StorageBackendException):
    """Fast-fail: the breaker is open and the call never reached the backend."""


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 30.0,
        *,
        time_source: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[BreakerState, BreakerState], None]] = None,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self._threshold = failure_threshold
        self._cooldown_s = cooldown_s
        self._now = time_source
        self._on_transition = on_transition
        self._lock = new_lock("resilient.CircuitBreaker._lock")
        self._state = BreakerState.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        #: Cumulative counters, exported as gauges.
        self.opens = 0
        self.fast_fails = 0
        #: Transition-observer callbacks that raised (swallowed-exception
        #: checker: a failing observer must not break the breaker, but the
        #: failure must still be countable).
        self.observer_failures = 0

    @property
    def state(self) -> BreakerState:
        with self._lock:
            return self._state

    @property
    def state_code(self) -> int:
        return self.state.value

    def _transition_locked(self, new: BreakerState) -> None:
        old, self._state = self._state, new
        if old is not new and self._on_transition is not None:
            try:
                self._on_transition(old, new)
            except Exception:  # noqa: BLE001 — observers must not break the breaker
                self.observer_failures += 1

    def acquire(self) -> None:
        """Gate a call; raises CircuitOpenException while open."""
        with self._lock:
            if self._state is BreakerState.OPEN:
                if self._now() - self._opened_at >= self._cooldown_s:
                    self._transition_locked(BreakerState.HALF_OPEN)
                else:
                    self.fast_fails += 1
                    raise CircuitOpenException(
                        f"Circuit breaker open ({self._consecutive_failures} "
                        "consecutive backend failures); failing fast"
                    )
            if self._state is BreakerState.HALF_OPEN:
                if self._probe_in_flight:
                    self.fast_fails += 1
                    raise CircuitOpenException(
                        "Circuit breaker half-open; probe already in flight"
                    )
                self._probe_in_flight = True

    def on_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            self._transition_locked(BreakerState.CLOSED)

    def on_neutral(self) -> None:
        """The call neither proves nor indicts the backend (e.g. the caller's
        deadline expired client-side): release a half-open probe slot without
        moving the state machine either way."""
        with self._lock:
            self._probe_in_flight = False

    def on_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            was_probe = self._probe_in_flight
            self._probe_in_flight = False
            if was_probe or self._consecutive_failures >= self._threshold:
                if self._state is not BreakerState.OPEN:
                    self.opens += 1
                self._opened_at = self._now()
                self._transition_locked(BreakerState.OPEN)


class RetryBudget:
    """Token bucket capping retry amplification across the whole backend.

    Earns ``percent/100`` tokens per successful call (capped at `capacity`,
    which is also the initial balance — a fixed allowance so cold starts and
    short blips can still retry), spends one token per retry. With ratio r,
    long-run retries ≤ r × successes + capacity: under a sustained 100%
    outage the bucket drains and stays empty, so amplification converges to
    exactly 1.0 instead of `max_attempts`."""

    def __init__(self, percent: int, capacity: float = 10.0) -> None:
        if not 0 < percent <= 100:
            raise ValueError(f"retry budget percent must be in (0, 100], got {percent}")
        self._earn = percent / 100.0
        self._capacity = max(1.0, capacity)
        self._balance = self._capacity
        self._lock = new_lock("resilient.RetryBudget._lock")
        #: Retries granted / denied (exported as resilience gauges).
        self.spent = 0
        self.denied = 0

    @property
    def balance(self) -> float:
        with self._lock:
            return self._balance

    def deposit(self) -> None:
        with self._lock:
            self._balance = min(self._capacity, self._balance + self._earn)

    def try_spend(self) -> bool:
        with self._lock:
            if self._balance >= 1.0:
                self._balance -= 1.0
                self.spent += 1
                return True
            self.denied += 1
            return False


class ResilientStorageBackend(StorageBackend):
    """StorageBackend decorator: circuit breaker + budgeted retries.

    Layering per call (replay-safe ops only — upload streams are consumed by
    the first attempt and are never replayed here; the RSM's orphan cleanup
    + broker re-copy own that path): breaker gate → delegate call → on
    failure, retry only while the budget has tokens, the deadline has room
    for the backoff, and `max_attempts` isn't exhausted. Each retry re-takes
    the breaker gate, so a retry storm can never bypass an opening breaker."""

    def __init__(
        self,
        delegate: StorageBackend,
        breaker: Optional[CircuitBreaker] = None,
        *,
        retry_budget: Optional[RetryBudget] = None,
        max_attempts: int = 3,
        backoff_s: float = 0.01,
        tracer=None,
    ) -> None:
        self._delegate = delegate
        self.breaker = breaker
        self.retry_budget = retry_budget
        self._max_attempts = max(1, max_attempts)
        self._backoff_s = backoff_s
        self._tracer = tracer

    @property
    def delegate(self) -> StorageBackend:
        return self._delegate

    def configure(self, configs: Mapping[str, object]) -> None:
        self._delegate.configure(configs)

    def _attempt(self, fn, *args):
        """One breaker-accounted delegate call."""
        if self.breaker is not None:
            self.breaker.acquire()
        try:
            result = fn(*args)
        except (KeyNotFoundException, InvalidRangeException):
            # The backend answered; the request was just unsatisfiable.
            if self.breaker is not None:
                self.breaker.on_success()
            raise
        except DeadlineExceededException:
            # Caller impatience, not backend failure: opening the breaker on
            # tight-deadline traffic would turn slow callers into an outage.
            if self.breaker is not None:
                self.breaker.on_neutral()
            raise
        except Exception:
            if self.breaker is not None:
                self.breaker.on_failure()
            raise
        if self.breaker is not None:
            self.breaker.on_success()
        return result

    def _call(self, fn, *args, replayable: bool = True):
        attempt = 0
        while True:
            try:
                result = self._attempt(fn, *args)
            except (KeyNotFoundException, InvalidRangeException):
                if self.retry_budget is not None:
                    self.retry_budget.deposit()  # contract answer = healthy
                raise
            except (CircuitOpenException, DeadlineExceededException):
                raise  # fast-fail paths are never retried
            except StorageBackendException:
                if (
                    not replayable
                    or self.retry_budget is None
                    or attempt >= self._max_attempts - 1
                    or not self.retry_budget.try_spend()
                ):
                    raise
                delay = random.uniform(0.0, self._backoff_s * (2**attempt))
                budget = remaining_s()
                if budget is not None and delay >= budget:
                    raise  # the deadline can't fit another attempt + backoff
                if self._tracer is not None:
                    self._tracer.event("storage.retry", attempt=attempt + 1)
                time.sleep(delay)
                attempt += 1
                continue
            if self.retry_budget is not None:
                self.retry_budget.deposit()
            return result

    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        # Not replayable: the first attempt consumes the stream.
        return self._call(self._delegate.upload, input_stream, key, replayable=False)

    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        return self._call(self._delegate.fetch, key, byte_range)

    def delete(self, key: ObjectKey) -> None:
        return self._call(self._delegate.delete, key)

    def delete_all(self, keys) -> None:
        # Materialized so a budgeted replay re-deletes the same key list.
        return self._call(self._delegate.delete_all, list(keys))

    def list_objects(self, prefix: str = ""):
        # Materialized under the breaker so mid-iteration page failures count
        # as backend failures instead of escaping the accounting.
        return iter(self._call(lambda p: list(self._delegate.list_objects(p)), prefix))

    def __str__(self) -> str:
        return f"ResilientStorageBackend{{delegate={self._delegate}}}"
