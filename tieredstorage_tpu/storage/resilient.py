"""Per-backend circuit breaker + retry budget: fail fast, retry bounded.

The HTTP transport already retries transient 5xx/429 with jittered backoff
(storage/httpclient.py); this layer sits above it and contains *sustained*
backend outages two ways:

- **Circuit breaker**: after `failure.threshold` consecutive
  StorageBackendExceptions the breaker opens and every call fails
  immediately with CircuitOpenException (no network), until a `cooldown.ms`
  period passes and a single half-open probe is allowed through — success
  closes the breaker, failure re-opens it. KeyNotFoundException /
  InvalidRangeException are contract responses from a healthy backend and
  count as successes. The state machine itself lives in the unified policy
  plane (utils/retry.py, ISSUE 19) and is re-exported here; this module
  keeps the storage-specific wiring.
- **Retry budget** (`retry.budget.*`): a token bucket that earns a fraction
  of a token per *successful* call and spends one whole token per retry, so
  the cluster-wide retry amplification factor is capped at
  1 + percent/100 (plus a fixed initial allowance). Unbounded per-call retry
  policies multiply: during an outage every caller retries, turning a
  backend brownout into a self-sustaining retry storm ("Overload Control for
  Scaling WeChat Microservices", SOSP 2018 measures exactly this spiral). A
  budget makes retries a *shared, earned* resource: when nothing succeeds,
  the bucket drains and the layer degrades to single attempts — which is
  what lets the breaker see the true failure rate and open.

The retry loop itself is `utils.retry.call_with_retry` with a typed
`RetryPolicy` — decorrelated-jitter backoff, deadline-aware scheduling, and
ledger/flight accounting are owned there, not here (one policy layer owns
backoff everywhere). The budget plugs in as the driver's `retry_gate`.

Both are wired by the RSM behind `breaker.enabled` / `retry.budget.enabled`
(config/rsm_config.py); state and counters are exported as gauges via
metrics/rsm_metrics.register_resilience_metrics and transitions are recorded
as tracing events.
"""

from __future__ import annotations

from typing import BinaryIO, Mapping, Optional

from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
)
from tieredstorage_tpu.utils.locks import new_lock
from tieredstorage_tpu.utils.retry import (  # noqa: F401 — re-exported compat names
    BreakerState,
    CircuitBreaker,
    CircuitOpenException,
    RetryPolicy,
    call_with_retry,
)


class RetryBudget:
    """Token bucket capping retry amplification across the whole backend.

    Earns ``percent/100`` tokens per successful call (capped at `capacity`,
    which is also the initial balance — a fixed allowance so cold starts and
    short blips can still retry), spends one token per retry. With ratio r,
    long-run retries ≤ r × successes + capacity: under a sustained 100%
    outage the bucket drains and stays empty, so amplification converges to
    exactly 1.0 instead of `max_attempts`."""

    def __init__(self, percent: int, capacity: float = 10.0) -> None:
        if not 0 < percent <= 100:
            raise ValueError(f"retry budget percent must be in (0, 100], got {percent}")
        self._earn = percent / 100.0
        self._capacity = max(1.0, capacity)
        self._balance = self._capacity
        self._lock = new_lock("resilient.RetryBudget._lock")
        #: Retries granted / denied (exported as resilience gauges).
        self.spent = 0
        self.denied = 0

    @property
    def balance(self) -> float:
        with self._lock:
            return self._balance

    def deposit(self) -> None:
        with self._lock:
            self._balance = min(self._capacity, self._balance + self._earn)

    def try_spend(self) -> bool:
        with self._lock:
            if self._balance >= 1.0:
                self._balance -= 1.0
                self.spent += 1
                return True
            self.denied += 1
            return False


class ResilientStorageBackend(StorageBackend):
    """StorageBackend decorator: circuit breaker + budgeted retries.

    Layering per call (replay-safe ops only — upload streams are consumed by
    the first attempt and are never replayed here; the RSM's orphan cleanup
    + broker re-copy own that path): breaker gate → delegate call → on
    failure, retry only while the budget has tokens, the deadline has room
    for the backoff, and `max_attempts` isn't exhausted. Each retry re-takes
    the breaker gate, so a retry storm can never bypass an opening breaker."""

    def __init__(
        self,
        delegate: StorageBackend,
        breaker: Optional[CircuitBreaker] = None,
        *,
        retry_budget: Optional[RetryBudget] = None,
        max_attempts: int = 3,
        backoff_s: float = 0.01,
        tracer=None,
    ) -> None:
        self._delegate = delegate
        self.breaker = breaker
        self.retry_budget = retry_budget
        self._policy = RetryPolicy(
            max_attempts=max(1, max_attempts),
            base_backoff_s=backoff_s,
            max_backoff_s=max(backoff_s, backoff_s * 8.0),
            retryable=(StorageBackendException,),
            healthy=(KeyNotFoundException, InvalidRangeException),
        )
        self._single = self._policy.single()
        self._tracer = tracer

    @property
    def delegate(self) -> StorageBackend:
        return self._delegate

    def configure(self, configs: Mapping[str, object]) -> None:
        self._delegate.configure(configs)

    def _on_retry(self, attempt: int, delay_s: float, exc: BaseException) -> None:
        if self._tracer is not None:
            self._tracer.event("storage.retry", attempt=attempt)

    def _call(self, fn, *args, op: str, replayable: bool = True):
        # No budget = no retries: the budget is what makes retries a shared,
        # earned resource; without one this layer degrades to the breaker
        # gate plus single attempts.
        retryable = replayable and self.retry_budget is not None
        policy = self._policy if retryable else self._single
        try:
            result = call_with_retry(
                lambda: fn(*args),
                policy=policy,
                site=f"storage.{op}",
                breaker=self.breaker,
                retry_gate=self.retry_budget.try_spend if retryable else None,
                on_retry=self._on_retry,
            )
        except (KeyNotFoundException, InvalidRangeException):
            if self.retry_budget is not None:
                self.retry_budget.deposit()  # contract answer = healthy
            raise
        if self.retry_budget is not None:
            self.retry_budget.deposit()
        return result

    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        # Not replayable: the first attempt consumes the stream.
        return self._call(
            self._delegate.upload, input_stream, key, op="upload", replayable=False
        )

    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        return self._call(self._delegate.fetch, key, byte_range, op="fetch")

    def delete(self, key: ObjectKey) -> None:
        return self._call(self._delegate.delete, key, op="delete")

    def delete_all(self, keys) -> None:
        # Materialized so a budgeted replay re-deletes the same key list.
        return self._call(self._delegate.delete_all, list(keys), op="delete")

    def list_objects(self, prefix: str = ""):
        # Materialized under the breaker so mid-iteration page failures count
        # as backend failures instead of escaping the accounting.
        return iter(
            self._call(
                lambda p: list(self._delegate.list_objects(p)), prefix, op="list"
            )
        )

    def __str__(self) -> str:
        return f"ResilientStorageBackend{{delegate={self._delegate}}}"
