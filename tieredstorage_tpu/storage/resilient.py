"""Per-backend circuit breaker: fail fast while the backend is down.

The HTTP transport already retries transient 5xx/429 with jittered backoff
(storage/httpclient.py); this layer sits above it and contains *sustained*
backend outages: after `failure.threshold` consecutive
StorageBackendExceptions the breaker opens and every call fails immediately
with CircuitOpenException (no network), until a `cooldown.ms` period passes
and a single half-open probe is allowed through — success closes the
breaker, failure re-opens it. KeyNotFoundException / InvalidRangeException
are contract responses from a healthy backend and count as successes.

Wired by the RSM behind the `breaker.enabled` config flag
(config/rsm_config.py); state and counters are exported as gauges via
metrics/rsm_metrics.register_resilience_metrics and transitions are recorded
as tracing events.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import BinaryIO, Callable, Mapping, Optional

from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
)


class BreakerState(enum.Enum):
    CLOSED = 0
    HALF_OPEN = 1
    OPEN = 2


class CircuitOpenException(StorageBackendException):
    """Fast-fail: the breaker is open and the call never reached the backend."""


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 30.0,
        *,
        time_source: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[BreakerState, BreakerState], None]] = None,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self._threshold = failure_threshold
        self._cooldown_s = cooldown_s
        self._now = time_source
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = BreakerState.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        #: Cumulative counters, exported as gauges.
        self.opens = 0
        self.fast_fails = 0

    @property
    def state(self) -> BreakerState:
        with self._lock:
            return self._state

    @property
    def state_code(self) -> int:
        return self.state.value

    def _transition_locked(self, new: BreakerState) -> None:
        old, self._state = self._state, new
        if old is not new and self._on_transition is not None:
            try:
                self._on_transition(old, new)
            except Exception:  # noqa: BLE001 — observers must not break the breaker
                pass

    def acquire(self) -> None:
        """Gate a call; raises CircuitOpenException while open."""
        with self._lock:
            if self._state is BreakerState.OPEN:
                if self._now() - self._opened_at >= self._cooldown_s:
                    self._transition_locked(BreakerState.HALF_OPEN)
                else:
                    self.fast_fails += 1
                    raise CircuitOpenException(
                        f"Circuit breaker open ({self._consecutive_failures} "
                        "consecutive backend failures); failing fast"
                    )
            if self._state is BreakerState.HALF_OPEN:
                if self._probe_in_flight:
                    self.fast_fails += 1
                    raise CircuitOpenException(
                        "Circuit breaker half-open; probe already in flight"
                    )
                self._probe_in_flight = True

    def on_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            self._transition_locked(BreakerState.CLOSED)

    def on_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            was_probe = self._probe_in_flight
            self._probe_in_flight = False
            if was_probe or self._consecutive_failures >= self._threshold:
                if self._state is not BreakerState.OPEN:
                    self.opens += 1
                self._opened_at = self._now()
                self._transition_locked(BreakerState.OPEN)


class ResilientStorageBackend(StorageBackend):
    """StorageBackend decorator routing every call through a CircuitBreaker."""

    def __init__(self, delegate: StorageBackend, breaker: CircuitBreaker) -> None:
        self._delegate = delegate
        self.breaker = breaker

    @property
    def delegate(self) -> StorageBackend:
        return self._delegate

    def configure(self, configs: Mapping[str, object]) -> None:
        self._delegate.configure(configs)

    def _call(self, fn, *args):
        self.breaker.acquire()
        try:
            result = fn(*args)
        except (KeyNotFoundException, InvalidRangeException):
            # The backend answered; the request was just unsatisfiable.
            self.breaker.on_success()
            raise
        except Exception:
            self.breaker.on_failure()
            raise
        self.breaker.on_success()
        return result

    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        return self._call(self._delegate.upload, input_stream, key)

    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        return self._call(self._delegate.fetch, key, byte_range)

    def delete(self, key: ObjectKey) -> None:
        return self._call(self._delegate.delete, key)

    def delete_all(self, keys) -> None:
        return self._call(self._delegate.delete_all, keys)

    def list_objects(self, prefix: str = ""):
        # Materialized under the breaker so mid-iteration page failures count
        # as backend failures instead of escaping the accounting.
        return iter(self._call(lambda p: list(self._delegate.list_objects(p)), prefix))

    def __str__(self) -> str:
        return f"ResilientStorageBackend{{delegate={self._delegate}}}"
