"""Shared per-backend HTTP request metrics.

Each cloud backend taps the HttpClient observer hook with a collector that
only differs in its metric group and request classifier — the analogue of
the reference's per-SDK MetricCollectors (S3 MetricPublisher, GCS transport
wrapper, Azure pipeline policy — SURVEY §2.9). Sensors per operation:
requests (rate+total), time (avg+max); error classes: throttling (429/503),
server (other 5xx), io (transport failures) — names after
storage/s3/.../MetricRegistry.java:26-70. The HttpClient observer fires per
ATTEMPT, so retried throttles/errors are each counted like the reference's
per-attempt SDK metrics. Beyond the reference's avg/max, every `-time`
family also records into a log-scale `Histogram` (`<op>-time-ms`), so the
Prometheus endpoint serves per-backend request tail latencies as
`_bucket`/`_sum`/`_count` series.
"""

from __future__ import annotations

from typing import Callable, Optional

from tieredstorage_tpu.metrics.core import (
    Avg,
    Histogram,
    Max,
    MetricName,
    MetricsRegistry,
    Rate,
    Total,
)

Classifier = Callable[[str, str], Optional[str]]


class RequestMetricCollector:
    def __init__(
        self,
        group: str,
        classify: Classifier,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.group = group
        self.classify = classify
        self.registry = registry or MetricsRegistry()

    def _requests_sensor(self, op: str):
        group = self.group
        sensor = self.registry.sensor(f"{op}-requests")
        sensor.ensure_stats(
            lambda: [
                (MetricName.of(f"{op}-requests-rate", group), Rate()),
                (MetricName.of(f"{op}-requests-total", group), Total()),
            ]
        )
        return sensor

    def _time_sensor(self, op: str):
        group = self.group
        sensor = self.registry.sensor(f"{op}-time")
        sensor.ensure_stats(
            lambda: [
                (MetricName.of(f"{op}-time-avg", group), Avg()),
                (MetricName.of(f"{op}-time-max", group), Max()),
                (
                    MetricName.of(
                        f"{op}-time-ms", group,
                        f"{op} request latency histogram (ms, per attempt)",
                    ),
                    Histogram(),
                ),
            ]
        )
        return sensor

    def _error_sensor(self, kind: str):
        group = self.group
        sensor = self.registry.sensor(f"{kind}-errors")
        sensor.ensure_stats(
            lambda: [
                (MetricName.of(f"{kind}-errors-rate", group), Rate()),
                (MetricName.of(f"{kind}-errors-total", group), Total()),
            ]
        )
        return sensor

    def observe(
        self,
        method: str,
        path_and_query: str,
        status: int,
        elapsed_s: float,
        error: Optional[BaseException],
    ) -> None:
        op = self.classify(method, path_and_query)
        if op is None:
            return
        self._requests_sensor(op).record(1.0)
        self._time_sensor(op).record(elapsed_s * 1000.0)
        if error is not None:
            self._error_sensor("io").record(1.0)
        elif status in (429, 503):
            self._error_sensor("throttling").record(1.0)
        elif status >= 500:
            self._error_sensor("server").record(1.0)
