"""Replicated multi-backend storage: quorum writes, health-probed failover.

The availability leg of the resilience stack: PR 1 survives hard backend
failures (breaker), PR 3 silent corruption (scrubber), PR 4 slow failures
(deadlines/hedging) — but every one of those still assumes ONE object store.
`ReplicatedStorageBackend` composes N child backends (any mix of
memory/filesystem/S3/GCS/Azure, each independently wrappable by
`ResilientStorageBackend` and `FaultInjectingBackend`) behind the ordinary
`StorageBackend` contract, Dynamo-style (DeCandia et al., SOSP 2007;
KIP-405 deployments replicating across object stores):

- **Writes fan out concurrently** to every replica and succeed at a
  configurable write quorum (`replication.write.quorum`, default all).
  A sub-quorum write **rolls back** the replicas that did succeed before
  raising, so the RSM's upload orphan-cleanup invariant (zero partial
  objects after a failed copy) holds per replica, not just per store.
- **Reads go to the healthiest replica first** — health is an EWMA of
  observed latency and error rate, fed by live traffic and by a cheap
  background prober (`replication.probe.interval.ms`, a one-key
  `list_objects` head call), and consults the replica's circuit breaker
  (an OPEN breaker floors the score) — and **fail over** to the next
  replica on exception, within whatever remains of the caller's
  end-to-end deadline. A contract answer (key-not-found / invalid-range)
  from a healthy replica does not win over another replica that can
  actually serve the bytes: divergent replicas are consulted before the
  contract answer is surfaced.
- **Replica-aware hedging**: `read_fetchers()` exposes the health-ordered
  children so `fetch/hedge.py` can race a straggling primary against a
  *distinct* replica instead of doubling load on the same one.

Anti-entropy repair (diffing replicas by prefix and copying
missing/divergent objects back toward quorum) lives in
`scrub/antientropy.py` and reuses this backend's replica states.

Configured reflectively as ``storage.backend.class`` with::

    storage.replication.replicas=a,b
    storage.replication.replica.a.backend.class=...FileSystemStorage
    storage.replication.replica.a.root=/mnt/a
    storage.replication.replica.b.backend.class=...S3Storage
    storage.replication.replica.b.s3.bucket.name=...
    storage.replication.write.quorum=2
    storage.replication.probe.interval.ms=30000

or composed programmatically: ``ReplicatedStorageBackend([b1, b2], ...)``.
"""

from __future__ import annotations

import io
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import BinaryIO, Callable, Mapping, Optional, Sequence, Union

from tieredstorage_tpu.config.configdef import (
    ConfigDef,
    ConfigKey,
    in_range,
    null_or,
    subset_with_prefix,
)
from tieredstorage_tpu.utils.locks import new_lock
from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
    load_backend_class,
)
from tieredstorage_tpu.utils.deadline import (
    DeadlineExceededException,
    current_deadline,
    deadline_scope,
    remaining_s,
)
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

log = logging.getLogger(__name__)

REPLICATION_PREFIX = "replication."


class QuorumWriteException(StorageBackendException):
    """A fan-out write reached fewer replicas than the write quorum; the
    successful copies were rolled back before this was raised."""


class AllReplicasFailedException(StorageBackendException):
    """Every replica failed the call with a backend error (no replica gave
    even a contract answer)."""


def _definition() -> ConfigDef:
    d = ConfigDef()
    d.define(ConfigKey(
        "replication.replicas", "list", default=[], importance="high",
        doc="Replica names. Each name <n> requires "
            "replication.replica.<n>.backend.class plus that backend's own "
            "keys under the replication.replica.<n>. prefix (passed through "
            "with the prefix stripped). Any mix of backends works, and each "
            "child may itself be a FaultInjectingBackend or sit behind its "
            "own resilience wrapper.",
    ))
    d.define(ConfigKey(
        "replication.write.quorum", "int", default=None,
        validator=null_or(in_range(1, None)), importance="high",
        doc="Replicas a write must reach to succeed (null = all). A "
            "sub-quorum write deletes the copies that did land and raises, "
            "so a failed upload leaves zero orphans on the surviving "
            "replicas.",
    ))
    d.define(ConfigKey(
        "replication.probe.interval.ms", "long", default=30_000,
        validator=null_or(in_range(1, None)), importance="medium",
        doc="Period of the background health prober: one cheap "
            "list_objects head call per replica feeds the latency/error "
            "EWMA that orders reads. Null disables probing (health is then "
            "driven by live traffic only).",
    ))
    return d


class ReplicaState:
    """Health bookkeeping for one child backend.

    The score combines an error-rate EWMA and a latency EWMA (both fed by
    live calls and by the prober) and consults the replica's circuit
    breaker when one is wired anywhere in its delegate chain: an OPEN
    breaker floors the score, so reads route around a tripped replica
    without waiting for its error EWMA to catch up."""

    #: EWMA smoothing factor (weight of the newest observation).
    ALPHA = 0.3
    #: Latency that halves the health score (ms).
    LATENCY_SCALE_MS = 50.0

    def __init__(self, name: str, backend: StorageBackend) -> None:
        self.name = name
        self.backend = backend
        self._lock = new_lock("replicated.ReplicaState._lock")
        self._latency_ms: Optional[float] = None
        self._error_rate = 0.0
        #: Cumulative counters, exported as replication-metrics gauges.
        self.errors = 0
        self.probes = 0
        self.probe_failures = 0

    def record(self, ok: bool, latency_ms: Optional[float] = None) -> None:
        with self._lock:
            a = self.ALPHA
            self._error_rate = (1 - a) * self._error_rate + a * (0.0 if ok else 1.0)
            if not ok:
                self.errors += 1
            if latency_ms is not None:
                self._latency_ms = (
                    latency_ms if self._latency_ms is None
                    else (1 - a) * self._latency_ms + a * latency_ms
                )

    def _breaker_open(self) -> bool:
        b = self.backend
        while b is not None:
            breaker = getattr(b, "breaker", None)
            state_code = getattr(breaker, "state_code", None)
            if state_code is not None and state_code == 2:  # BreakerState.OPEN
                return True
            b = getattr(b, "delegate", None)
        return False

    def health_score(self) -> float:
        """(0, 1]: 1 = fast and error-free; an OPEN breaker floors it."""
        if self._breaker_open():
            return 0.0
        with self._lock:
            latency = self._latency_ms or 0.0
            availability = 1.0 - self._error_rate
        return max(0.001, availability / (1.0 + latency / self.LATENCY_SCALE_MS))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ReplicaState({self.name}, health={self.health_score():.3f})"


class HealthProber:
    """Daemon thread issuing one cheap head probe per replica per period.

    The probe is `list_objects(prefix)` truncated after the first key —
    every backend serves it from a single page (or a single directory
    walk step), so it measures reachability + first-byte latency without
    moving object bytes."""

    def __init__(
        self,
        replicas: Sequence[ReplicaState],
        interval_s: float,
        *,
        prefix: str = "",
        tracer=NOOP_TRACER,
    ) -> None:
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self._replicas = list(replicas)
        self.interval_s = interval_s
        self.prefix = prefix
        self.tracer = tracer
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HealthProber":
        if self._thread is not None:
            raise RuntimeError("HealthProber already started")
        self._thread = threading.Thread(
            target=self._run, name="replica-prober", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def probe_once(self) -> None:
        """One probe round; public so tests and tools can drive it inline."""
        for rep in self._replicas:
            start = time.monotonic()
            try:
                next(iter(rep.backend.list_objects(self.prefix)), None)
            except Exception as e:  # noqa: BLE001 — any failure marks the replica
                rep.probes += 1
                rep.probe_failures += 1
                rep.record(ok=False, latency_ms=(time.monotonic() - start) * 1000.0)
                self.tracer.event(
                    "replication.probe_failed", replica=rep.name,
                    error=type(e).__name__,
                )
            else:
                rep.probes += 1
                rep.record(ok=True, latency_ms=(time.monotonic() - start) * 1000.0)

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            self.probe_once()


class ReplicatedStorageBackend(StorageBackend):
    """N child backends behind one StorageBackend contract.

    See the module docstring for semantics. Thread-safe: fan-out uploads
    run on a private pool, health state is lock-protected per replica."""

    def __init__(
        self,
        replicas: Optional[Sequence[Union[StorageBackend, tuple[str, StorageBackend]]]] = None,
        *,
        write_quorum: Optional[int] = None,
        probe_interval_s: Optional[float] = None,
        probe_prefix: str = "",
        tracer=NOOP_TRACER,
    ) -> None:
        self._replicas: list[ReplicaState] = []
        if replicas:
            for i, rep in enumerate(replicas):
                name, backend = rep if isinstance(rep, tuple) else (f"r{i}", rep)
                self._replicas.append(ReplicaState(name, backend))
        self._write_quorum = write_quorum
        self._probe_interval_s = probe_interval_s
        self._probe_prefix = probe_prefix
        self.tracer = tracer
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_lock = new_lock("replicated.ReplicatedStorageBackend._pool_lock")
        self._prober: Optional[HealthProber] = None
        #: Optional `(elapsed_ms)` hook; the RSM wires it to the
        #: replica-failover-time histogram.
        self.on_failover: Optional[Callable[[float], None]] = None
        #: Cumulative counters, exported as replication-metrics gauges.
        self.failovers = 0
        self.quorum_failures = 0
        self._counter_lock = new_lock("replicated.ReplicatedStorageBackend._counter_lock")
        self._validate_quorum()
        if self._replicas and self._probe_interval_s:
            self.start_prober()

    # ------------------------------------------------------------------ setup
    def configure(self, configs: Mapping[str, object]) -> None:
        values = _definition().parse(configs)
        names = [str(n) for n in values["replication.replicas"]]
        if not names:
            raise ValueError(
                "replication.replicas must name at least one replica"
            )
        if len(set(names)) != len(names):
            raise ValueError(f"replication.replicas has duplicates: {names}")
        self._replicas = []
        for name in names:
            prefix = f"replication.replica.{name}."
            child_configs = subset_with_prefix(configs, prefix)
            class_path = child_configs.pop("backend.class", None)
            if not class_path:
                raise ValueError(
                    f"replication.replica.{name}.backend.class is required"
                )
            backend = load_backend_class(str(class_path))()
            backend.configure(child_configs)
            self._replicas.append(ReplicaState(name, backend))
        self._write_quorum = values["replication.write.quorum"]
        interval_ms = values["replication.probe.interval.ms"]
        self._probe_interval_s = interval_ms / 1000.0 if interval_ms else None
        self._validate_quorum()
        if self._probe_interval_s:
            self.start_prober()

    def _validate_quorum(self) -> None:
        if (
            self._write_quorum is not None
            and self._replicas
            and self._write_quorum > len(self._replicas)
        ):
            raise ValueError(
                f"replication.write.quorum={self._write_quorum} exceeds the "
                f"{len(self._replicas)} configured replicas"
            )

    def start_prober(self) -> None:
        if self._prober is not None or not self._probe_interval_s:
            return
        self._prober = HealthProber(
            self._replicas, self._probe_interval_s,
            prefix=self._probe_prefix, tracer=self.tracer,
        ).start()

    def close(self) -> None:
        if self._prober is not None:
            self._prober.stop()
            self._prober = None
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True, cancel_futures=True)

    # ------------------------------------------------------------- accessors
    @property
    def replica_states(self) -> list[ReplicaState]:
        return list(self._replicas)

    @property
    def prober(self) -> Optional[HealthProber]:
        return self._prober

    @property
    def write_quorum(self) -> int:
        return self._write_quorum or len(self._replicas)

    def replica_health(self) -> dict[str, float]:
        return {rep.name: rep.health_score() for rep in self._replicas}

    def read_fetchers(self) -> list[StorageBackend]:
        """Health-ordered children, for replica-aware hedging: a hedge
        issued against `read_fetchers()[1]` races a DISTINCT replica
        instead of re-hammering the straggler."""
        return [rep.backend for rep in self._by_health()]

    def _by_health(self) -> list[ReplicaState]:
        # Quantized so sub-hundredth score noise (e.g. a few hundred µs of
        # latency EWMA difference between healthy replicas) does not flap the
        # read order; the stable sort keeps configuration order for ties, so
        # the first-listed replica stays the preferred primary until health
        # meaningfully diverges.
        return sorted(
            self._replicas,
            key=lambda rep: round(rep.health_score(), 2),
            reverse=True,
        )

    def _executor(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=max(2, len(self._replicas)),
                    thread_name_prefix="replica-write",
                )
            return self._pool

    # ---------------------------------------------------------------- writes
    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        """Concurrent fan-out write; quorum or rollback.

        The source stream is read ONCE and each replica gets its own
        buffer, so a child that consumes/half-consumes its stream cannot
        starve its siblings."""
        if not self._replicas:
            raise StorageBackendException("No replicas configured")
        data = input_stream.read()
        deadline = current_deadline()

        def write_one(rep: ReplicaState) -> int:
            start = time.monotonic()
            try:
                with deadline_scope(deadline):
                    n = rep.backend.upload(io.BytesIO(data), key)
            except Exception:
                rep.record(ok=False, latency_ms=(time.monotonic() - start) * 1000.0)
                raise
            rep.record(ok=True, latency_ms=(time.monotonic() - start) * 1000.0)
            return n

        pool = self._executor()
        futures = {pool.submit(write_one, rep): rep for rep in self._replicas}
        succeeded: list[ReplicaState] = []
        failures: list[tuple[ReplicaState, BaseException]] = []
        size = len(data)
        for future, rep in futures.items():
            try:
                size = future.result()
                succeeded.append(rep)
            except Exception as e:  # noqa: BLE001 — tallied against the quorum
                failures.append((rep, e))
        quorum = self.write_quorum
        if len(succeeded) < quorum:
            self._rollback(succeeded, key)
            with self._counter_lock:
                self.quorum_failures += 1
            self.tracer.event(
                "storage.quorum_failure", key=key.value,
                succeeded=len(succeeded), quorum=quorum,
                failed=[rep.name for rep, _ in failures],
            )
            detail = "; ".join(
                f"{rep.name}: {type(e).__name__}: {e}" for rep, e in failures
            )
            raise QuorumWriteException(
                f"Write of {key} reached {len(succeeded)}/{len(self._replicas)} "
                f"replicas, quorum is {quorum} ({detail}); successful copies "
                "rolled back"
            ) from (failures[0][1] if failures else None)
        if failures:
            log.warning(
                "Write of %s missed %d replica(s) but met quorum %d: %s",
                key, len(failures), quorum,
                ", ".join(rep.name for rep, _ in failures),
            )
        return size

    def _rollback(self, succeeded: Sequence[ReplicaState], key: ObjectKey) -> None:
        """Delete the sub-quorum copies; best-effort (the upload already
        failed — rollback failures are logged, not raised)."""
        for rep in succeeded:
            try:
                rep.backend.delete(key)
            except Exception:  # noqa: BLE001 — rollback is best-effort
                log.warning(
                    "Sub-quorum rollback failed to delete %s from replica %s",
                    key, rep.name, exc_info=True,
                )

    def delete(self, key: ObjectKey) -> None:
        """Fan-out delete; must converge on EVERY replica.

        Missing keys are fine (deletion is idempotent), but any replica
        that *fails* the delete keeps its copy — raising here lets the
        caller's idempotent retry (rsm._delete_keys sweep) converge
        instead of leaving a copy the anti-entropy pass would faithfully
        resurrect onto the other replicas."""
        if not self._replicas:
            raise StorageBackendException("No replicas configured")
        failures: list[tuple[ReplicaState, BaseException]] = []
        for rep in self._replicas:
            start = time.monotonic()
            try:
                rep.backend.delete(key)
            except KeyNotFoundException:
                rep.record(ok=True)
            except Exception as e:  # noqa: BLE001 — swept, then surfaced as one
                rep.record(ok=False, latency_ms=(time.monotonic() - start) * 1000.0)
                failures.append((rep, e))
            else:
                rep.record(ok=True, latency_ms=(time.monotonic() - start) * 1000.0)
        if failures:
            detail = "; ".join(
                f"{rep.name}: {type(e).__name__}: {e}" for rep, e in failures
            )
            raise StorageBackendException(
                f"Delete of {key} failed on {len(failures)}/"
                f"{len(self._replicas)} replicas: {detail}"
            ) from failures[0][1]

    # ----------------------------------------------------------------- reads
    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        """Healthiest replica first, failing over within the deadline.

        Contract answers are only surfaced once every replica has been
        consulted (a key can be missing on a stale replica but present on
        another); precedence on total failure is
        invalid-range > key-not-found > last backend error."""
        return self._read_failover(
            "fetch", lambda backend: backend.fetch(key, byte_range), key=key.value
        )

    def list_objects(self, prefix: str = ""):
        # Materialized so a mid-iteration page failure fails over instead of
        # escaping after the healthy-looking iterator was already returned.
        return iter(self._read_failover(
            "list", lambda backend: list(backend.list_objects(prefix)), key=prefix
        ))

    def _read_failover(self, op: str, call, *, key: str):
        if not self._replicas:
            raise StorageBackendException("No replicas configured")
        ordered = self._by_health()
        start = time.monotonic()
        not_found: Optional[KeyNotFoundException] = None
        invalid_range: Optional[InvalidRangeException] = None
        last_error: Optional[StorageBackendException] = None
        attempts = 0
        for rep in ordered:
            if attempts:
                budget = remaining_s()
                if budget is not None and budget <= 0:
                    raise DeadlineExceededException(
                        f"Deadline expired after {attempts} replica "
                        f"attempt(s) for {op} of {key}"
                    )
            attempts += 1
            t0 = time.monotonic()
            try:
                result = call(rep.backend)
            except KeyNotFoundException as e:
                rep.record(ok=True, latency_ms=(time.monotonic() - t0) * 1000.0)
                not_found = e
                continue
            except InvalidRangeException as e:
                rep.record(ok=True, latency_ms=(time.monotonic() - t0) * 1000.0)
                invalid_range = e
                continue
            except DeadlineExceededException:
                # Caller impatience, not replica failure: stop failing over.
                raise
            except Exception as e:  # noqa: BLE001 — fail over to the next replica
                rep.record(ok=False, latency_ms=(time.monotonic() - t0) * 1000.0)
                last_error = (
                    e if isinstance(e, StorageBackendException)
                    else StorageBackendException(f"{op} failed on {rep.name}: {e}")
                )
                self.tracer.event(
                    "storage.replica_error", op=op, replica=rep.name,
                    key=key, error=type(e).__name__,
                )
                continue
            rep.record(ok=True, latency_ms=(time.monotonic() - t0) * 1000.0)
            if attempts > 1:
                elapsed_ms = (time.monotonic() - start) * 1000.0
                with self._counter_lock:
                    self.failovers += 1
                self.tracer.event(
                    "storage.failover", op=op, key=key, to_replica=rep.name,
                    attempts=attempts,
                )
                if self.on_failover is not None:
                    self.on_failover(elapsed_ms)
            return result
        if invalid_range is not None:
            raise invalid_range
        if not_found is not None:
            raise not_found
        raise AllReplicasFailedException(
            f"All {len(ordered)} replicas failed {op} of {key}"
        ) from last_error

    def __str__(self) -> str:
        names = ",".join(rep.name for rep in self._replicas)
        return f"ReplicatedStorageBackend{{replicas=[{names}], quorum={self.write_quorum}}}"
