"""SOCKS5 proxy support for storage backends.

Reference: storage/core/.../proxy/ProxyConfig.java:26-105 (keys
`proxy.{host,port,username,password}`) and Socks5ProxyAuthenticator.java:27-82
(JVM-global authenticator registry). This build implements the SOCKS5 client
handshake (RFC 1928, with RFC 1929 username/password auth) directly and hands
backends a socket factory, so no global process state is mutated.
"""

from __future__ import annotations

import dataclasses
import socket
import struct
from typing import Any, Mapping, Optional

from tieredstorage_tpu.config.configdef import ConfigDef, ConfigException, ConfigKey


def _definition() -> ConfigDef:
    d = ConfigDef()
    d.define(ConfigKey("proxy.host", "string", default=None, importance="low", doc="Proxy host"))
    d.define(ConfigKey("proxy.port", "int", default=None, importance="low", doc="Proxy port"))
    d.define(
        ConfigKey(
            "proxy.username", "password", default=None, importance="low", doc="Proxy username"
        )
    )
    d.define(
        ConfigKey(
            "proxy.password", "password", default=None, importance="low", doc="Proxy password"
        )
    )
    return d


@dataclasses.dataclass(frozen=True)
class ProxyConfig:
    host: str
    port: int
    username: Optional[str] = None
    password: Optional[str] = None

    DEFINITION = _definition()

    @staticmethod
    def from_configs(configs: Mapping[str, Any]) -> Optional["ProxyConfig"]:
        """Returns None when no proxy is configured (`proxy.host` absent)."""
        subset = {k: v for k, v in configs.items() if str(k).startswith("proxy.")}
        if not subset:
            return None
        values = ProxyConfig.DEFINITION.parse(subset)
        host = values.get("proxy.host")
        port = values.get("proxy.port")
        if host is None or port is None:
            raise ConfigException("proxy.host and proxy.port must be defined together")
        return ProxyConfig(
            host=host,
            port=port,
            username=values.get("proxy.username"),
            password=values.get("proxy.password"),
        )


class Socks5Error(OSError):
    pass


_REPLY_MESSAGES = {
    0x01: "general SOCKS server failure",
    0x02: "connection not allowed by ruleset",
    0x03: "network unreachable",
    0x04: "host unreachable",
    0x05: "connection refused",
    0x06: "TTL expired",
    0x07: "command not supported",
    0x08: "address type not supported",
}


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise Socks5Error("SOCKS5 proxy closed the connection mid-handshake")
        buf += part
    return buf


def socks5_connect(
    proxy: ProxyConfig, host: str, port: int, timeout: Optional[float] = None
) -> socket.socket:
    """Open a TCP connection to (host, port) through the SOCKS5 proxy."""
    sock = socket.create_connection((proxy.host, proxy.port), timeout=timeout)
    try:
        if proxy.username is not None:
            sock.sendall(b"\x05\x02\x00\x02")  # no-auth and user/pass offered
        else:
            sock.sendall(b"\x05\x01\x00")
        ver, method = _recv_exact(sock, 2)
        if ver != 5:
            raise Socks5Error(f"Not a SOCKS5 proxy (version {ver})")
        if method == 0x02:
            if proxy.username is None:
                raise Socks5Error("Proxy requires username/password auth")
            user = proxy.username.encode("utf-8")
            pwd = (proxy.password or "").encode("utf-8")
            sock.sendall(bytes([1, len(user)]) + user + bytes([len(pwd)]) + pwd)
            aver, status = _recv_exact(sock, 2)
            if status != 0:
                raise Socks5Error("SOCKS5 authentication failed")
        elif method != 0x00:
            raise Socks5Error("No acceptable SOCKS5 auth method")
        # CONNECT with a domain-name address (proxy resolves DNS).
        addr = host.encode("idna")
        sock.sendall(b"\x05\x01\x00\x03" + bytes([len(addr)]) + addr + struct.pack(">H", port))
        ver, reply, _rsv, atyp = _recv_exact(sock, 4)
        if reply != 0:
            raise Socks5Error(
                f"SOCKS5 connect failed: {_REPLY_MESSAGES.get(reply, hex(reply))}"
            )
        if atyp == 0x01:
            _recv_exact(sock, 4 + 2)
        elif atyp == 0x03:
            (ln,) = _recv_exact(sock, 1)
            _recv_exact(sock, ln + 2)
        elif atyp == 0x04:
            _recv_exact(sock, 16 + 2)
        else:
            raise Socks5Error(f"Unknown SOCKS5 address type {atyp}")
        return sock
    except Exception:
        sock.close()
        raise


def socks5_socket_factory(proxy: Optional[ProxyConfig]):
    """Socket factory for HttpClient; None proxy → direct connections."""
    if proxy is None:
        return None

    def factory(host: str, port: int, timeout: Optional[float]) -> socket.socket:
        return socks5_connect(proxy, host, port, timeout)

    return factory
