"""Minimal pooled HTTP client for the cloud storage backends.

The reference's backends ride vendor SDKs (AWS SDK v2 sync HTTP client,
google-cloud-storage's HttpTransport, azure-core's HttpPipeline — see
storage/s3/.../S3ClientBuilder.java, storage/gcs/.../GcsStorage.java:41-88,
storage/azure/.../AzureBlobStorage.java:48-99). This build speaks the three
REST protocols directly over the standard library so the backends carry zero
SDK dependencies; this module is the shared transport: a bounded keep-alive
connection pool, timeouts, an observer hook (the analogue of the reference's
MetricCollector pipeline taps), and a socket factory hook used for SOCKS5
proxying (storage/core/.../proxy/).

Connection management (the fleet-mode enabling refactor, ISSUE 6): each
client holds ONE bounded pool of keep-alive connections to its host —
``max_connections`` in-flight requests at most, idle connections reused by
whichever thread asks next, callers past the bound waiting (deadline-clamped)
for a slot instead of minting sockets. The previous design pinned one
connection per THREAD, so concurrency was only reachable by thread count and
every new worker paid a TCP/TLS handshake; with the pool, a process holds
thousands of logical in-flight fetches over a fixed socket budget, and
streamed bodies return their connection for reuse once fully drained.

Retry ownership is split the same way the reference splits it: the
transport retries only replay-safe requests (ranged GETs, HEAD, deletes,
and calls explicitly marked idempotent), so a failed segment UPLOAD is NOT
retried here — it propagates, the RSM deletes the orphaned objects
(rsm.py orphan cleanup), and Kafka's RemoteLogManager re-schedules the
whole copy, exactly as it does for the reference (whose SDK retry configs
also only replay idempotent calls, S3StorageConfig.java:65-68). Retrying a
non-replay-safe body mid-stream from a pooled connection risks duplicate
side effects on a request the server may have partially processed.
"""

from __future__ import annotations

import dataclasses
import http.client
import io
import random
import socket
import ssl
import time
from datetime import datetime, timezone
from email.utils import parsedate_to_datetime
from typing import BinaryIO, Callable, Mapping, Optional
from urllib.parse import urlsplit

from tieredstorage_tpu.utils.deadline import (
    DeadlineExceededException,
    check_deadline,
    current_deadline,
)
from tieredstorage_tpu.utils.locks import new_condition


class HttpError(Exception):
    """Transport-level failure (connect/read), not an HTTP status."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with full jitter for transient failures.

    The reference inherits retry behavior from the vendor SDKs (AWS SDK v2
    standard retry mode — storage/s3/.../S3StorageConfig.java:65-68 exposes a
    per-attempt timeout precisely because the SDK retries; the GCS and Azure
    SDKs ship equivalent policies). This is the hand-rolled transport's
    equivalent: replay-safe requests are retried on transport failures and on
    throttle/server statuses, sleeping full-jitter exponential backoff
    between attempts and honoring Retry-After within `max_delay_s`.

    `total_deadline_s` bounds the whole call including backoff sleeps (the
    reference's `api.call.timeout` semantics: "including all retries"); the
    per-attempt socket timeout lives on the HttpClient itself
    (`api.call.attempt.timeout`)."""

    max_attempts: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 5.0
    total_deadline_s: Optional[float] = None
    retry_statuses: frozenset = frozenset({429, 500, 502, 503, 504})

    def backoff_s(self, retry_number: int, retry_after_s: Optional[float] = None) -> float:
        """Sleep before retry `retry_number` (0-based): U(0, min(max, base*2^n)),
        raised to the server's Retry-After when given (capped at max_delay_s —
        a server asking for minutes should surface the error, not block the
        fetch path)."""
        cap = min(self.max_delay_s, self.base_delay_s * (2**retry_number))
        delay = random.uniform(0.0, cap)
        if retry_after_s is not None:
            delay = max(delay, min(retry_after_s, self.max_delay_s))
        return delay


#: Disables retries entirely (single attempt) — for tests and callers that
#: layer their own replay logic.
NO_RETRY = RetryPolicy(max_attempts=1)


def _parse_retry_after(value: str) -> Optional[float]:
    """Both RFC 9110 forms: delta-seconds ('Retry-After: 2') and HTTP-date
    ('Retry-After: Fri, 31 Jul 2026 07:28:00 GMT') — a real S3/GCS 503 can
    send either (round-4 verdict). A past or unparsable date yields None
    (the policy's own backoff applies)."""
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        pass
    try:
        when = parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if when is None:
        return None
    if when.tzinfo is None:
        # RFC 5322 parse of an asctime form can come back naive; HTTP dates
        # are GMT by definition.
        when = when.replace(tzinfo=timezone.utc)
    delta = (when - datetime.now(timezone.utc)).total_seconds()
    return max(0.0, delta) if delta > 0 else None


class HttpResponse:
    """A fully materialized or streaming HTTP response.

    `stream()` hands the caller ownership of the underlying response body;
    the connection is returned to the per-thread slot only once the body is
    fully drained and closed.
    """

    def __init__(self, status: int, headers: Mapping[str, str], body: bytes):
        self.status = status
        self.headers = {k.lower(): v for k, v in headers.items()}
        self.body = body

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name.lower(), default)


class _StreamedBody(io.RawIOBase):
    """Wraps an http.client response; the stream owns a pooled connection.

    Closing returns the connection to the pool — for keep-alive REUSE when
    the body was fully drained (the overwhelmingly common case: ranged chunk
    GETs are read to completion), or closed and its slot freed when the
    caller abandoned the body mid-stream (the framing is desynced, the
    socket is useless)."""

    def __init__(
        self,
        resp: http.client.HTTPResponse,
        conn: http.client.HTTPConnection,
        pool: Optional["_ConnectionPool"] = None,
    ):
        self._resp = resp
        self._conn = conn
        self._pool = pool

    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        data = self._resp.read(len(b))
        n = len(data)
        b[:n] = data
        return n

    def read(self, size: int = -1) -> bytes:
        return self._resp.read(None if size is None or size < 0 else size)

    def close(self) -> None:
        if self.closed:
            return
        try:
            try:
                drained = bool(self._resp.isclosed())
            except Exception:  # fakes/tests without isclosed
                drained = False
            try:
                self._resp.close()
            finally:
                if self._pool is None:
                    self._conn.close()
                elif drained:
                    self._conn._tstpu_used = True
                    self._pool.release(self._conn)
                else:
                    self._pool.discard(self._conn)
        finally:
            super().close()


# Observer signature: (method, url_path, status, elapsed_seconds, error) -> None
Observer = Callable[[str, str, int, float, Optional[BaseException]], None]

# Socket factory signature: (host, port, timeout) -> connected socket
SocketFactory = Callable[[str, int, Optional[float]], socket.socket]


class _Connection(http.client.HTTPConnection):
    """HTTPConnection with a pluggable socket factory (SOCKS5 support)."""

    def __init__(self, host: str, port: int, timeout, socket_factory: Optional[SocketFactory]):
        super().__init__(host, port, timeout=timeout)
        self._socket_factory = socket_factory

    def connect(self) -> None:
        if self._socket_factory is None:
            super().connect()
        else:
            self.sock = self._socket_factory(self.host, self.port, self.timeout)


class _SecureConnection(http.client.HTTPSConnection):
    def __init__(self, host, port, timeout, socket_factory, context):
        super().__init__(host, port, timeout=timeout, context=context)
        self._socket_factory = socket_factory

    def connect(self) -> None:
        if self._socket_factory is None:
            super().connect()
        else:
            raw = self._socket_factory(self.host, self.port, self.timeout)
            self.sock = self._context.wrap_socket(raw, server_hostname=self.host)


class _ConnectionPool:
    """Bounded pool of keep-alive connections to one host.

    Invariant: in-flight + idle connections never exceed `max_connections`.
    acquire() prefers an idle keep-alive connection, creates a new one while
    under the bound, and otherwise blocks (bounded by the caller's timeout)
    until release()/discard() frees a slot — so concurrency is a fixed
    socket budget, not a per-thread property."""

    def __init__(self, factory: Callable[[], http.client.HTTPConnection],
                 max_connections: int) -> None:
        if max_connections < 1:
            raise ValueError(f"max_connections must be >= 1, got {max_connections}")
        self._factory = factory
        self.max_connections = max_connections
        self._cond = new_condition("httpclient._ConnectionPool._cond")
        self._idle: list[http.client.HTTPConnection] = []
        self._in_use = 0
        #: Lifetime counters (pool health introspection).
        self.created_total = 0
        self.waited_total = 0
        self.exhausted_total = 0

    @property
    def in_use(self) -> int:
        with self._cond:
            return self._in_use

    @property
    def idle(self) -> int:
        with self._cond:
            return len(self._idle)

    def acquire(self, timeout_s: Optional[float] = None, *, fresh: bool = False):
        """An idle connection, a new one (under the bound), or a bounded
        wait. `fresh=True` skips idle reuse where possible — the
        stale-keepalive replay path must not retry onto another possibly
        stale idle socket (an idle one is closed to keep the bound)."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        create = False
        conn = None
        stale: list[http.client.HTTPConnection] = []
        try:
            with self._cond:
                while True:
                    if self._idle and not fresh:
                        conn = self._idle.pop()
                        self._in_use += 1
                        break
                    if self._in_use + len(self._idle) < self.max_connections:
                        self._in_use += 1
                        create = True
                        break
                    if fresh and self._idle:
                        # Under the fresh policy, trade an idle (possibly
                        # stale) socket for a new one rather than waiting.
                        # Popping it frees the slot immediately; the socket
                        # teardown itself happens outside the lock (lock-order
                        # checker: no blocking calls under _cond).
                        stale.append(self._idle.pop())
                        continue
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        self.exhausted_total += 1
                        raise HttpError(
                            f"connection pool exhausted ({self.max_connections} "
                            f"in flight); no slot within {timeout_s:.1f}s"
                        )
                    self.waited_total += 1
                    self._cond.wait(remaining)
        finally:
            for old in stale:
                try:
                    old.close()
                except OSError:
                    pass
        if not create:
            return conn
        try:
            conn = self._factory()
        except BaseException:
            with self._cond:
                self._in_use -= 1
                self._cond.notify()
            raise
        with self._cond:
            self.created_total += 1
        return conn

    def release(self, conn) -> None:
        """Return a healthy connection for keep-alive reuse."""
        with self._cond:
            self._in_use -= 1
            self._idle.append(conn)
            self._cond.notify()

    def discard(self, conn) -> None:
        """Close a broken/desynced connection and free its slot."""
        try:
            conn.close()
        finally:
            with self._cond:
                self._in_use -= 1
                self._cond.notify()

    def close(self) -> None:
        with self._cond:
            idle, self._idle = self._idle, []
        for conn in idle:
            try:
                conn.close()
            except OSError:
                pass


class HttpClient:
    """Bounded pooled keep-alive connections to a single base URL."""

    def __init__(
        self,
        base_url: str,
        *,
        timeout: Optional[float] = None,
        verify_tls: bool = True,
        socket_factory: Optional[SocketFactory] = None,
        observer: Optional[Observer] = None,
        retry: Optional[RetryPolicy] = None,
        max_connections: int = 32,
        pool_wait_timeout_s: float = 30.0,
    ) -> None:
        parts = urlsplit(base_url)
        if parts.scheme not in ("http", "https"):
            raise ValueError(f"Unsupported scheme in {base_url!r}")
        self.base_url = base_url
        self.scheme = parts.scheme
        self.host = parts.hostname or ""
        self.port = parts.port or (443 if self.scheme == "https" else 80)
        # Path prefix of the endpoint URL (e.g. Azurite's
        # http://host:10000/devstoreaccount1) — callers prepend this to every
        # request path.
        self.base_path = parts.path.rstrip("/")
        self.timeout = timeout
        self.socket_factory = socket_factory
        self.observer = observer
        self.retry = retry if retry is not None else RetryPolicy()
        self.pool_wait_timeout_s = pool_wait_timeout_s
        # Late-bound factory: tests monkeypatch `_new_connection` per
        # instance after construction, and the pool must see the override.
        self._pool = _ConnectionPool(
            lambda: self._new_connection(), max_connections
        )
        if self.scheme == "https":
            self._ssl_context = ssl.create_default_context()
            if not verify_tls:
                self._ssl_context.check_hostname = False
                self._ssl_context.verify_mode = ssl.CERT_NONE
        else:
            self._ssl_context = None

    # ----------------------------------------------------------- connections
    def _new_connection(self) -> http.client.HTTPConnection:
        if self.scheme == "https":
            return _SecureConnection(
                self.host, self.port, self.timeout, self.socket_factory, self._ssl_context
            )
        return _Connection(self.host, self.port, self.timeout, self.socket_factory)

    @property
    def pool(self) -> _ConnectionPool:
        return self._pool

    def _acquire_timeout(self, budget: Optional[float]) -> Optional[float]:
        """Longest a request may wait for a pool slot: the configured pool
        wait, clamped to the remaining call budget."""
        candidates = [self.pool_wait_timeout_s]
        if budget is not None:
            candidates.append(max(0.001, budget))
        return min(candidates)

    # -------------------------------------------------------------- requests
    def request(
        self,
        method: str,
        path_and_query: str,
        *,
        headers: Optional[Mapping[str, str]] = None,
        body: bytes = b"",
        idempotent: Optional[bool] = None,
    ) -> HttpResponse:
        """Issue a request and read the full response body, retrying
        replay-safe requests per the client's RetryPolicy.

        `idempotent` overrides the method-based replay classification for
        calls the caller KNOWS are safe to replay (e.g. S3 DeleteObjects is
        a POST, but deleting already-deleted keys is a no-op). Non-replay-
        safe requests get exactly one attempt (plus `_roundtrip`'s
        stale-keepalive replay when the failure happened before the request
        was fully sent)."""
        policy = self.retry
        replay_safe = (
            idempotent if idempotent is not None else method in self._IDEMPOTENT
        )
        check_deadline(f"{method} {path_and_query}")
        deadline = self._effective_deadline(policy)
        retry_number = 0
        while True:
            try:
                resp = self._request_once(
                    method, path_and_query, headers, body, idempotent,
                    budget=None if deadline is None else deadline - time.monotonic(),
                )
            except HttpError:
                self._raise_if_deadline_spent(method, path_and_query)
                if not replay_safe or retry_number >= policy.max_attempts - 1:
                    raise
                delay = policy.backoff_s(retry_number)
                if deadline is not None and time.monotonic() + delay > deadline:
                    # The remaining budget can't fit the backoff, let alone
                    # another attempt: stop retrying.
                    raise
                time.sleep(delay)
                retry_number += 1
                continue
            if (
                replay_safe
                and resp.status in policy.retry_statuses
                and retry_number < policy.max_attempts - 1
            ):
                delay = policy.backoff_s(
                    retry_number, _parse_retry_after(resp.header("retry-after"))
                )
                if deadline is None or time.monotonic() + delay <= deadline:
                    time.sleep(delay)
                    retry_number += 1
                    continue
            return resp

    def _request_once(
        self, method, path_and_query, headers, body, idempotent, budget=None
    ) -> HttpResponse:
        """One attempt (the retry loop's unit); the observer sees every
        attempt, so per-attempt rates/errors match what went on the wire.

        `budget` is the remaining total-deadline seconds: the attempt's
        socket timeout is capped to it so the CALL honors the deadline
        (reference semantics: api.call.timeout includes all retries — a
        late attempt must not get a full fresh socket timeout)."""
        t0 = time.perf_counter()
        err: Optional[BaseException] = None
        status = 0
        try:
            if budget is not None and budget <= 0:
                raise TimeoutError("api call deadline exceeded before attempt")
            resp, conn = self._roundtrip(
                method, path_and_query, headers, body, idempotent, budget=budget
            )
            status = resp.status
            try:
                data = resp.read()
            except (OSError, http.client.HTTPException):
                self._pool.discard(conn)
                raise
            # Body fully drained: the keep-alive connection goes back to the
            # pool for the next request on any thread.
            self._pool.release(conn)
            return HttpResponse(status, dict(resp.getheaders()), data)
        except (OSError, http.client.HTTPException) as e:
            err = e
            raise HttpError(f"{method} {path_and_query} failed: {e}") from e
        finally:
            if self.observer is not None:
                self.observer(method, path_and_query, status, time.perf_counter() - t0, err)

    def request_stream(
        self,
        method: str,
        path_and_query: str,
        *,
        headers: Optional[Mapping[str, str]] = None,
    ) -> tuple[int, Mapping[str, str], BinaryIO]:
        """Issue a request on a dedicated connection; the returned stream
        owns it. The initial exchange retries per the policy for idempotent
        methods only (a streamed POST must not be blindly replayed); once
        the stream is handed out, a mid-body failure surfaces to the caller
        (the fetch path re-requests with an adjusted Range rather than
        replaying a partially consumed body)."""
        policy = self.retry if method in self._IDEMPOTENT else NO_RETRY
        check_deadline(f"{method} {path_and_query}")
        deadline = self._effective_deadline(policy)
        retry_number = 0
        while True:
            try:
                status, hdrs, stream = self._stream_once(
                    method, path_and_query, headers,
                    budget=None if deadline is None else deadline - time.monotonic(),
                )
            except HttpError:
                self._raise_if_deadline_spent(method, path_and_query)
                if retry_number >= policy.max_attempts - 1:
                    raise
                delay = policy.backoff_s(retry_number)
                if deadline is not None and time.monotonic() + delay > deadline:
                    raise
                time.sleep(delay)
                retry_number += 1
                continue
            if status in policy.retry_statuses and retry_number < policy.max_attempts - 1:
                retry_after = _parse_retry_after(hdrs.get("retry-after", ""))
                delay = policy.backoff_s(retry_number, retry_after)
                if deadline is None or time.monotonic() + delay <= deadline:
                    stream.close()
                    time.sleep(delay)
                    retry_number += 1
                    continue
            return status, hdrs, stream

    def _stream_once(
        self, method, path_and_query, headers, budget=None
    ) -> tuple[int, Mapping[str, str], BinaryIO]:
        t0 = time.perf_counter()
        conn = self._pool.acquire(self._acquire_timeout(budget))
        self._apply_timeout(conn, budget)
        try:
            conn.request(method, path_and_query, body=None, headers=dict(headers or {}))
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            self._pool.discard(conn)
            if self.observer is not None:
                self.observer(method, path_and_query, 0, time.perf_counter() - t0, e)
            raise HttpError(f"{method} {path_and_query} failed: {e}") from e
        if self.observer is not None:
            self.observer(method, path_and_query, resp.status, time.perf_counter() - t0, None)
        hdrs = {k.lower(): v for k, v in resp.getheaders()}
        return resp.status, hdrs, _StreamedBody(resp, conn, self._pool)

    _IDEMPOTENT = frozenset({"GET", "HEAD", "PUT", "DELETE"})

    @staticmethod
    def _effective_deadline(policy: RetryPolicy) -> Optional[float]:
        """Absolute monotonic deadline for the whole call: the tighter of the
        policy's total deadline and the ambient end-to-end Deadline (the
        cross-layer budget installed at the RSM/gateway entry)."""
        candidates = []
        if policy.total_deadline_s is not None:
            candidates.append(time.monotonic() + policy.total_deadline_s)
        ambient = current_deadline()
        if ambient is not None:
            candidates.append(ambient.at_monotonic)
        return min(candidates) if candidates else None

    @staticmethod
    def _raise_if_deadline_spent(method: str, path_and_query: str) -> None:
        """An attempt that failed AFTER the end-to-end deadline expired
        surfaces as DeadlineExceededException, not a transport error: the
        caller's budget is gone, so the distinct type must reach the
        boundary (504 / DEADLINE_EXCEEDED) instead of a generic failure."""
        ambient = current_deadline()
        if ambient is not None and ambient.expired:
            raise DeadlineExceededException(
                f"Deadline exceeded during {method} {path_and_query}"
            )

    def _apply_timeout(self, conn, budget) -> None:
        """Effective per-attempt socket timeout = min(client timeout,
        remaining deadline budget). Always (re)applied — a pooled
        connection must not inherit a clamped timeout from an earlier
        budgeted call."""
        candidates = [t for t in (self.timeout, budget) if t is not None]
        effective = max(0.001, min(candidates)) if candidates else None
        conn.timeout = effective
        sock = getattr(conn, "sock", None)  # None before connect (and on fakes)
        if sock is not None:
            sock.settimeout(effective)

    def _roundtrip(
        self, method, path_and_query, headers, body, idempotent=None, budget=None
    ) -> tuple[http.client.HTTPResponse, http.client.HTTPConnection]:
        """One exchange on a pooled connection; returns (response, conn) —
        the caller reads the body and releases/discards the connection."""
        conn = self._pool.acquire(self._acquire_timeout(budget))
        reused = getattr(conn, "_tstpu_used", False)
        sent = False
        try:
            self._apply_timeout(conn, budget)
            conn.request(method, path_and_query, body=body, headers=dict(headers or {}))
            sent = True
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException):
            self._pool.discard(conn)
            # Retry once ONLY when replay is safe: the first attempt must
            # have been on a reused keep-alive connection (a fresh-connection
            # failure isn't a stale-socket artifact), and for non-idempotent
            # methods (DeleteObjects/CompleteMultipartUpload/PutBlockList
            # POSTs) only when the failure happened while SENDING — once the
            # full request went out, the server may have executed it, and a
            # replay could run it twice.
            replay_safe = (
                idempotent if idempotent is not None else method in self._IDEMPOTENT
            )
            if not reused or (sent and not replay_safe):
                raise
            # The replay must not land on ANOTHER possibly-stale idle
            # socket: acquire fresh.
            conn = self._pool.acquire(self._acquire_timeout(budget), fresh=True)
            try:
                self._apply_timeout(conn, budget)
                conn.request(method, path_and_query, body=body, headers=dict(headers or {}))
                resp = conn.getresponse()
            except (OSError, http.client.HTTPException):
                self._pool.discard(conn)
                raise
        conn._tstpu_used = True
        return resp, conn

    def close(self) -> None:
        self._pool.close()
