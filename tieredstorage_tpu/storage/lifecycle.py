"""Upload intent journal: the durable half of crash-consistent lifecycle.

PR 19 made every *transient* fault survivable; this module (ISSUE 20) makes
the segment lifecycle survive the process dying mid-operation.  The journal
is a tiny append-only JSONL WAL that records *intent* before the first byte
of a segment upload (or delete) touches the object store, and records the
outcome when the operation finishes:

``{"rec": "begin",     "txn": N, "segment": ..., "keys": [...]}``
    Appended (and fsynced) BEFORE ``_storage_upload`` consumes any bytes.
    Names exactly the object keys a crash may strand.
``{"rec": "stage",     "txn": N, "stage": "log-uploaded" | "indexes-uploaded"}``
    Progress marks between the triple's uploads — purely diagnostic; the
    recovery sweeper never trusts them over the store listing.
``{"rec": "commit",    "txn": N}``
    The manifest landed.  Manifest-last stays the SOLE commit point: the
    journal never redefines commit, it only names what an uncommitted crash
    may have left behind.
``{"rec": "rollback",  "txn": N}``
    In-process orphan cleanup already deleted the partial triple.
``{"rec": "tombstone", "txn": N, "segment": ..., "keys": [...]}``
    Delete intent, fsynced before the first delete — a retried or
    crash-interrupted ``delete_log_segment_data`` converges because the
    sweeper finishes what the tombstone names (manifest-unreachable keys
    only) and GCs the tombstone once every named key is gone.
``{"rec": "tombstone-commit", "txn": N}``
    The triple is fully deleted.

Durability policy: records that *gate* store mutations (``begin``,
``tombstone``) are critical — an append failure fails the operation before
any store byte moves, so the store can never hold state the journal does not
name.  Outcome records (``commit``, ``stage``, ``rollback``,
``tombstone-commit``) are best-effort: by the time they are written the
store already reflects the outcome, so a failed append must NOT fail the
(already durable) operation — it leaves the entry pending and the recovery
sweeper re-derives the outcome from manifest reachability on its next pass
(a pending upload whose manifest exists is simply re-committed).  Failed
best-effort appends are still visible: ``append_failures_total`` counts
them (the PR 14 "no invisible swallows" rule).

Replay tolerates a torn trailing line (the crash artifact of dying
mid-append); torn records are counted, never fatal.  ``compact()`` rewrites
the file with only the still-pending entries via a temp file +
``os.replace`` so the journal stays small across long uptimes.

**In-flight tracking** (process-local, never persisted): ``begin_upload``/
``begin_delete`` mark their txn *in flight* until the owning operation
returns — ``commit``/``rollback``/``commit_delete`` clear it, and the RSM's
copy/delete paths call ``release(txn)`` in a ``finally`` so a txn left
pending by a failed rollback cleanup is still released.  A pending entry
whose txn is in flight belongs to an operation running RIGHT NOW in this
process; the recovery sweeper must neither resolve it nor touch its keys
(a paced sweep racing a live upload would otherwise delete objects the
copy is about to commit).  Entries rebuilt by replay are never in flight —
the process that began them is dead.

The ``lifecycle.journal`` fault-plane site (utils/faults.py) fires before
every append, so chaos runs can fail/stall journaling without touching the
store.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from tieredstorage_tpu.utils import faults
from tieredstorage_tpu.utils.locks import new_lock, note_mutation

log = logging.getLogger(__name__)

#: Rewrite threshold: when the file grows past this many bytes AND most of
#: it is resolved history, append() triggers an inline compaction.
DEFAULT_COMPACT_BYTES = 1 << 20

UPLOAD = "upload"
DELETE = "delete"

#: Stage marks recorded between the triple's uploads (diagnostic only).
STAGE_LOG_UPLOADED = "log-uploaded"
STAGE_INDEXES_UPLOADED = "indexes-uploaded"


class JournalAppendError(RuntimeError):
    """A critical journal append (begin/tombstone) could not be made durable."""


@dataclass
class JournalEntry:
    """One pending transaction: an upload intent or a delete tombstone."""

    txn: int
    kind: str  # UPLOAD | DELETE
    segment: str
    keys: List[str]
    stage: Optional[str] = None
    #: The owning operation is running right now in THIS process (snapshot
    #: taken by pending()); such entries are untouchable to the sweeper.
    inflight: bool = False

    def to_json(self) -> dict:
        return {
            "txn": self.txn,
            "kind": self.kind,
            "segment": self.segment,
            "keys": list(self.keys),
            "stage": self.stage,
            "inflight": self.inflight,
        }


@dataclass
class _Counters:
    appends_total: int = 0
    append_failures_total: int = 0
    torn_records_total: int = 0
    compactions_total: int = 0
    commits_total: int = 0
    rollbacks_total: int = 0
    tombstones_total: int = 0
    tombstone_commits_total: int = 0
    replayed_entries: int = 0
    extra: dict = field(default_factory=dict)


class UploadIntentJournal:
    """Durable WAL of segment lifecycle intents (see module docstring).

    Thread-safe: RSM copy/delete threads and the sweeper thread append and
    resolve concurrently under one named lock.  All file writes happen
    under the lock; fsync latency is bounded (records are < 1 KiB) and
    dwarfed by the segment upload the record guards.
    """

    def __init__(
        self, path: Path, *, compact_bytes: int = DEFAULT_COMPACT_BYTES
    ) -> None:
        self.path = Path(path)
        self.compact_bytes = compact_bytes
        self._lock = new_lock("lifecycle.UploadIntentJournal._lock")
        self._pending: Dict[int, JournalEntry] = {}
        #: Txns whose owning operation is running in this process (see the
        #: module docstring); never persisted, never populated by replay.
        self._inflight: set = set()
        self._next_txn = 1
        self._c = _Counters()
        self._closed = False
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._replay()
        # Opened AFTER replay so a compaction during replay doesn't race a
        # stale handle; line-buffered append, fsynced per critical record.
        self._fh = open(self.path, "a", encoding="utf-8")

    # ---------------------------------------------------------------- intents
    def begin_upload(self, segment: str, keys: List[str]) -> int:
        """Record upload intent; MUST be called before the first uploaded
        byte.  Raises JournalAppendError if the record cannot be made
        durable — the copy then fails while the store is still clean."""
        with self._lock:
            txn = self._next_txn
            self._next_txn += 1
            note_mutation("lifecycle.UploadIntentJournal._next_txn")
            entry = JournalEntry(txn, UPLOAD, segment, list(keys))
            self._append(
                {"rec": "begin", "txn": txn, "segment": segment,
                 "keys": list(keys)},
                critical=True,
            )
            self._pending[txn] = entry
            self._inflight.add(txn)
            note_mutation("lifecycle.UploadIntentJournal._pending")
            return txn

    def stage(self, txn: int, stage: str) -> None:
        """Mark upload progress (best-effort, diagnostic)."""
        with self._lock:
            entry = self._pending.get(txn)
            if entry is None:
                return
            entry.stage = stage
            self._append({"rec": "stage", "txn": txn, "stage": stage},
                         critical=False)

    def commit(self, txn: int) -> None:
        """The manifest landed: the transaction is durable in the store."""
        with self._lock:
            if self._pending.pop(txn, None) is None:
                return
            self._inflight.discard(txn)
            note_mutation("lifecycle.UploadIntentJournal._pending")
            self._c.commits_total += 1
            self._append({"rec": "commit", "txn": txn}, critical=False)
            self._maybe_compact()

    def rollback(self, txn: int) -> None:
        """In-process cleanup deleted the partial triple; nothing strands."""
        with self._lock:
            if self._pending.pop(txn, None) is None:
                return
            self._inflight.discard(txn)
            note_mutation("lifecycle.UploadIntentJournal._pending")
            self._c.rollbacks_total += 1
            self._append({"rec": "rollback", "txn": txn}, critical=False)
            self._maybe_compact()

    def begin_delete(self, segment: str, keys: List[str]) -> int:
        """Record a delete tombstone; MUST precede the first store delete."""
        with self._lock:
            txn = self._next_txn
            self._next_txn += 1
            note_mutation("lifecycle.UploadIntentJournal._next_txn")
            entry = JournalEntry(txn, DELETE, segment, list(keys))
            self._append(
                {"rec": "tombstone", "txn": txn, "segment": segment,
                 "keys": list(keys)},
                critical=True,
            )
            self._c.tombstones_total += 1
            self._pending[txn] = entry
            self._inflight.add(txn)
            note_mutation("lifecycle.UploadIntentJournal._pending")
            return txn

    def commit_delete(self, txn: int) -> None:
        """Every key the tombstone names is gone; GC the tombstone."""
        with self._lock:
            if self._pending.pop(txn, None) is None:
                return
            self._inflight.discard(txn)
            note_mutation("lifecycle.UploadIntentJournal._pending")
            self._c.tombstone_commits_total += 1
            self._append({"rec": "tombstone-commit", "txn": txn},
                         critical=False)
            self._maybe_compact()

    def release(self, txn: int) -> None:
        """The operation owning ``txn`` has returned (committed, rolled
        back, or failed with its entry left pending): clear the in-flight
        mark so the recovery sweeper may act on whatever it left behind.
        Called from a ``finally`` on the RSM copy/delete paths; idempotent,
        a no-op for resolved or unknown txns.  Appends nothing — in-flight
        is process-local state, meaningless across restarts."""
        with self._lock:
            self._inflight.discard(txn)
            note_mutation("lifecycle.UploadIntentJournal._inflight")

    # ---------------------------------------------------------------- queries
    def pending(self) -> List[JournalEntry]:
        with self._lock:
            return [
                JournalEntry(e.txn, e.kind, e.segment, list(e.keys), e.stage,
                             inflight=e.txn in self._inflight)
                for e in self._pending.values()
            ]

    def pending_uploads(self) -> List[JournalEntry]:
        return [e for e in self.pending() if e.kind == UPLOAD]

    def pending_tombstones(self) -> List[JournalEntry]:
        return [e for e in self.pending() if e.kind == DELETE]

    @property
    def pending_upload_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._pending.values() if e.kind == UPLOAD)

    @property
    def pending_tombstone_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._pending.values() if e.kind == DELETE)

    @property
    def appends_total(self) -> int:
        return self._c.appends_total

    @property
    def append_failures_total(self) -> int:
        return self._c.append_failures_total

    @property
    def torn_records_total(self) -> int:
        return self._c.torn_records_total

    @property
    def compactions_total(self) -> int:
        return self._c.compactions_total

    @property
    def commits_total(self) -> int:
        return self._c.commits_total

    @property
    def rollbacks_total(self) -> int:
        return self._c.rollbacks_total

    @property
    def tombstones_total(self) -> int:
        return self._c.tombstones_total

    @property
    def tombstone_commits_total(self) -> int:
        return self._c.tombstone_commits_total

    def status(self) -> dict:
        with self._lock:
            return {
                "path": str(self.path),
                "pending_uploads": sum(
                    1 for e in self._pending.values() if e.kind == UPLOAD
                ),
                "pending_tombstones": sum(
                    1 for e in self._pending.values() if e.kind == DELETE
                ),
                "inflight": len(self._inflight),
                "appends_total": self._c.appends_total,
                "append_failures_total": self._c.append_failures_total,
                "torn_records_total": self._c.torn_records_total,
                "compactions_total": self._c.compactions_total,
                "commits_total": self._c.commits_total,
                "rollbacks_total": self._c.rollbacks_total,
                "tombstones_total": self._c.tombstones_total,
                "tombstone_commits_total": self._c.tombstone_commits_total,
            }

    # -------------------------------------------------------------- internals
    def _append(self, record: dict, *, critical: bool) -> None:
        """Append one JSONL record; fsync.  Critical failures raise
        JournalAppendError (the guarded store mutation must not proceed);
        best-effort failures are counted and logged — the sweeper
        re-derives the lost outcome from manifest reachability."""
        self._c.appends_total += 1
        note_mutation("lifecycle.UploadIntentJournal._c")
        try:
            faults.fire("lifecycle.journal", record.get("rec", ""))
            self._fh.write(json.dumps(record, sort_keys=True) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except Exception as e:
            self._c.append_failures_total += 1
            if critical:
                raise JournalAppendError(
                    f"journal append failed for {record.get('rec')}: {e}"
                ) from e
            log.warning(
                "Best-effort journal append failed (%s txn=%s); the recovery "
                "sweeper will re-derive the outcome",
                record.get("rec"), record.get("txn"), exc_info=True,
            )

    def _replay(self) -> None:
        """Rebuild pending state from the file; torn trailing data (a crash
        mid-append) is tolerated and counted.  Runs under the lock (only
        from __init__, but the counters' guard must be uniform)."""
        with self._lock:
            self._replay_locked()

    def _replay_locked(self) -> None:
        if not self.path.exists():
            return
        raw = self.path.read_bytes()
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
                kind = rec["rec"]
                txn = int(rec["txn"])
            except (ValueError, KeyError, UnicodeDecodeError):
                self._c.torn_records_total += 1
                continue
            if kind == "begin":
                self._pending[txn] = JournalEntry(
                    txn, UPLOAD, str(rec.get("segment", "")),
                    [str(k) for k in rec.get("keys", [])],
                )
            elif kind == "tombstone":
                # Replay only rebuilds pending state; tombstones_total was
                # already counted by the begin_delete that wrote the record
                # (re-counting here would skew the metric on every restart
                # or compact-then-replay cycle).
                self._pending[txn] = JournalEntry(
                    txn, DELETE, str(rec.get("segment", "")),
                    [str(k) for k in rec.get("keys", [])],
                )
            elif kind == "stage":
                entry = self._pending.get(txn)
                if entry is not None:
                    entry.stage = str(rec.get("stage"))
            elif kind in ("commit", "rollback", "tombstone-commit"):
                self._pending.pop(txn, None)
            else:
                self._c.torn_records_total += 1
                continue
            self._next_txn = max(self._next_txn, txn + 1)
        self._c.replayed_entries = len(self._pending)
        if self._pending:
            log.info(
                "Lifecycle journal replay: %d pending entrie(s) "
                "(a prior process may have crashed mid-operation)",
                len(self._pending),
            )

    def _maybe_compact(self) -> None:
        """Inline compaction once the file outgrows compact_bytes (called
        under the lock after an entry resolves)."""
        try:
            if self.path.stat().st_size < self.compact_bytes:
                return
        except OSError:
            return
        self._compact_locked()

    def compact(self) -> None:
        """Rewrite the journal with only the pending entries."""
        with self._lock:
            self._compact_locked()

    def _compact_locked(self) -> None:
        tmp = self.path.with_suffix(self.path.suffix + ".compact")
        try:
            with open(tmp, "w", encoding="utf-8") as out:
                for entry in self._pending.values():
                    rec = "begin" if entry.kind == UPLOAD else "tombstone"
                    out.write(json.dumps(
                        {"rec": rec, "txn": entry.txn,
                         "segment": entry.segment, "keys": list(entry.keys)},
                        sort_keys=True,
                    ) + "\n")
                out.flush()
                os.fsync(out.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
            self._c.compactions_total += 1
        except OSError:
            log.warning("Journal compaction failed; keeping the long file",
                        exc_info=True)
            try:
                tmp.unlink(missing_ok=True)
            except OSError:
                pass
            if self._fh.closed:
                self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            note_mutation("lifecycle.UploadIntentJournal._closed")
            try:
                self._fh.close()
            except OSError:  # pragma: no cover — close failure is terminal anyway
                pass

    def __enter__(self) -> "UploadIntentJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
