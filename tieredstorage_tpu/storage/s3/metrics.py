"""S3 backend request metrics.

Reference: storage/s3/.../MetricCollector.java implements the AWS SDK
`MetricPublisher`; metric names in storage/s3/.../MetricRegistry.java:26-70:
{get,put,delete,delete-objects,upload-part,create-multipart-upload,
complete-multipart-upload,abort-multipart-upload}-requests (+-rate/-total) and
-time (-avg/-max), plus error classes (throttling/server/io/configured-timeout).
Here the collector is an HttpClient observer classifying calls by method +
query shape instead of SDK execution interceptors.
"""

from __future__ import annotations

from typing import Optional

from tieredstorage_tpu.metrics.core import (
    Avg,
    Max,
    MetricName,
    MetricsRegistry,
    Rate,
    Total,
)

GROUP = "s3-client-metrics"
CONTEXT = "aiven.kafka.server.tieredstorage.s3"


def _classify(method: str, path_and_query: str) -> Optional[str]:
    query = path_and_query.partition("?")[2]
    params = {p.partition("=")[0] for p in query.split("&") if p}
    if method == "GET":
        return "get-object"
    if method == "PUT":
        return "upload-part" if "partNumber" in params else "put-object"
    if method == "DELETE":
        if "uploadId" in params:
            return "abort-multipart-upload"
        return "delete-object"
    if method == "POST":
        if "delete" in params:
            return "delete-objects"
        if "uploads" in params:
            return "create-multipart-upload"
        if "uploadId" in params:
            return "complete-multipart-upload"
    return None


class S3MetricCollector:
    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()

    def _sensor(self, op: str):
        sensor = self.registry.sensor(f"{op}-requests")
        sensor.ensure_stats(
            lambda: [
                (MetricName.of(f"{op}-requests-rate", GROUP), Rate()),
                (MetricName.of(f"{op}-requests-total", GROUP), Total()),
            ]
        )
        return sensor

    def _time_sensor(self, op: str):
        sensor = self.registry.sensor(f"{op}-time")
        sensor.ensure_stats(
            lambda: [
                (MetricName.of(f"{op}-time-avg", GROUP), Avg()),
                (MetricName.of(f"{op}-time-max", GROUP), Max()),
            ]
        )
        return sensor

    def _error_sensor(self, kind: str):
        sensor = self.registry.sensor(f"{kind}-errors")
        sensor.ensure_stats(
            lambda: [
                (MetricName.of(f"{kind}-errors-rate", GROUP), Rate()),
                (MetricName.of(f"{kind}-errors-total", GROUP), Total()),
            ]
        )
        return sensor

    def observe(
        self,
        method: str,
        path_and_query: str,
        status: int,
        elapsed_s: float,
        error: Optional[BaseException],
    ) -> None:
        op = _classify(method, path_and_query)
        if op is None:
            return
        self._sensor(op).record(1.0)
        self._time_sensor(op).record(elapsed_s * 1000.0)
        # Error classes mirror MetricRegistry.java: throttling (503/SlowDown),
        # server errors (5xx), io errors (transport failures).
        if error is not None:
            self._error_sensor("io").record(1.0)
        elif status == 503:
            self._error_sensor("throttling").record(1.0)
        elif status >= 500:
            self._error_sensor("server").record(1.0)
