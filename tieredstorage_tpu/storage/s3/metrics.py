"""S3 backend request metrics.

Reference: storage/s3/.../MetricCollector.java implements the AWS SDK
`MetricPublisher`; metric names in storage/s3/.../MetricRegistry.java:26-70.
Requests are classified by method + query shape instead of SDK execution
interceptors; sensor shapes come from the shared RequestMetricCollector.
"""

from __future__ import annotations

from typing import Optional

from tieredstorage_tpu.storage.request_metrics import RequestMetricCollector

GROUP = "s3-client-metrics"
CONTEXT = "aiven.kafka.server.tieredstorage.s3"


def _classify(method: str, path_and_query: str) -> Optional[str]:
    query = path_and_query.partition("?")[2]
    params = {p.partition("=")[0] for p in query.split("&") if p}
    if method == "GET":
        return "get-object"
    if method == "PUT":
        return "upload-part" if "partNumber" in params else "put-object"
    if method == "DELETE":
        if "uploadId" in params:
            return "abort-multipart-upload"
        return "delete-object"
    if method == "POST":
        if "delete" in params:
            return "delete-objects"
        if "uploads" in params:
            return "create-multipart-upload"
        if "uploadId" in params:
            return "complete-multipart-upload"
    return None


class S3MetricCollector(RequestMetricCollector):
    def __init__(self, registry=None):
        super().__init__(GROUP, _classify, registry)
