"""S3 StorageBackend implementation.

Reference: storage/s3/.../S3Storage.java:40-151 — upload streams through the
multipart output stream, ranged GET via the Range header, native multi-object
delete, 404 → KeyNotFoundException and 416 → InvalidRangeException mapping.
"""

from __future__ import annotations

from typing import BinaryIO, Iterable, Mapping, Optional

from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
)
from tieredstorage_tpu.storage.httpclient import HttpError, RetryPolicy
from tieredstorage_tpu.storage.proxy import ProxyConfig, socks5_socket_factory
from tieredstorage_tpu.storage.s3.client import S3ApiError, S3Client
from tieredstorage_tpu.storage.s3.config import S3StorageConfig
from tieredstorage_tpu.storage.s3.multipart import S3MultiPartOutputStream

_COPY_BUFFER = 1024 * 1024


class S3Storage(StorageBackend):
    def __init__(self) -> None:
        self.client: Optional[S3Client] = None
        self.part_size = 0
        self._metric_collector = None

    def configure(self, configs: Mapping[str, object]) -> None:
        config = S3StorageConfig(configs)
        proxy = ProxyConfig.from_configs(configs)
        from tieredstorage_tpu.storage.s3.metrics import S3MetricCollector

        self._metric_collector = S3MetricCollector()
        # Reference semantics (S3StorageConfig.java:65-68 / AWS SDK): the
        # call timeout covers the whole call INCLUDING retries, the attempt
        # timeout covers one attempt. Map the former onto the retry policy's
        # total deadline and the latter onto the per-attempt socket timeout
        # (falling back to the call timeout when only that one is set).
        call_timeout_s = (
            config.api_call_timeout_ms / 1000.0
            if config.api_call_timeout_ms is not None
            else None
        )
        attempt_timeout_s = (
            config.api_call_attempt_timeout_ms / 1000.0
            if config.api_call_attempt_timeout_ms is not None
            else call_timeout_s
        )
        retry = RetryPolicy(total_deadline_s=call_timeout_s)
        self.part_size = config.part_size
        self.client = S3Client(
            config.bucket_name,
            config.region,
            endpoint_url=config.endpoint_url,
            path_style=config.path_style_access,
            access_key=config.access_key_id,
            secret_key=config.secret_access_key,
            timeout=attempt_timeout_s,
            verify_tls=config.certificate_check_enabled,
            checksum_check=config.checksum_check_enabled,
            socket_factory=socks5_socket_factory(proxy),
            observer=self._metric_collector.observe,
            retry=retry,
        )

    def _require_client(self) -> S3Client:
        if self.client is None:
            raise StorageBackendException("S3Storage is not configured")
        return self.client

    # --------------------------------------------------------------- upload
    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        client = self._require_client()
        out = S3MultiPartOutputStream(client, key.value, self.part_size)
        try:
            while True:
                block = input_stream.read(_COPY_BUFFER)
                if not block:
                    break
                out.write(block)
            out.close()
        except (S3ApiError, HttpError) as e:
            out.abort()
            raise StorageBackendException(f"Failed to upload {key}") from e
        return out.processed_bytes

    # ---------------------------------------------------------------- fetch
    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        client = self._require_client()
        rng = (
            (byte_range.from_position, byte_range.to_position)
            if byte_range is not None
            else None
        )
        try:
            status, headers, stream = client.get_object_stream(key.value, rng)
        except HttpError as e:
            raise StorageBackendException(f"Failed to fetch {key}") from e
        if status in (200, 206):
            return stream
        body = stream.read()
        stream.close()
        if status == 404:
            raise KeyNotFoundException(self, key)
        if status == 416:
            raise InvalidRangeException(
                f"Failed to fetch {key}: Invalid range {byte_range}"
            )
        raise StorageBackendException(
            f"Failed to fetch {key}: HTTP {status}: {body[:200]!r}"
        )

    # --------------------------------------------------------------- delete
    def delete(self, key: ObjectKey) -> None:
        client = self._require_client()
        try:
            client.delete_object(key.value)
        except (S3ApiError, HttpError) as e:
            raise StorageBackendException(f"Failed to delete {key}") from e

    def delete_all(self, keys: Iterable[ObjectKey]) -> None:
        client = self._require_client()
        key_list = [k.value for k in keys]
        if not key_list:
            return
        try:
            # S3 caps DeleteObjects at 1000 keys per call.
            for i in range(0, len(key_list), 1000):
                client.delete_objects(key_list[i : i + 1000])
        except (S3ApiError, HttpError) as e:
            raise StorageBackendException(f"Failed to delete {key_list}") from e

    # ----------------------------------------------------------------- list
    def list_objects(self, prefix: str = ""):
        """ListObjectsV2 pages (1000 keys each) chained via continuation
        tokens; S3 returns keys in lexicographic (UTF-8 binary) order."""
        client = self._require_client()
        token: Optional[str] = None
        while True:
            try:
                keys, token = client.list_objects_v2(prefix, token)
            except (S3ApiError, HttpError) as e:
                raise StorageBackendException(
                    f"Failed to list objects with prefix {prefix!r}"
                ) from e
            for key in keys:
                yield ObjectKey(key)
            if token is None:
                return

    @property
    def metrics(self):
        return self._metric_collector

    def close(self) -> None:
        if self.client is not None:
            self.client.close()

    def __str__(self) -> str:
        bucket = self.client.bucket if self.client else None
        return f"S3Storage{{bucket={bucket}, partSize={self.part_size}}}"
