"""S3 storage backend (REST + SigV4, no SDK).

Reference module: storage/s3 (S3Storage.java, S3StorageConfig.java,
S3ClientBuilder.java, S3MultiPartOutputStream.java, MetricCollector.java).
"""

from tieredstorage_tpu.storage.s3.config import S3StorageConfig
from tieredstorage_tpu.storage.s3.storage import S3Storage

__all__ = ["S3Storage", "S3StorageConfig"]
