"""Multipart upload output stream.

Reference: storage/s3/.../S3MultiPartOutputStream.java:40-211 — buffer up to
`part_size` bytes, lazily create the multipart upload on the first flushed
part, upload each full buffer as a part, complete on close, abort on any
error; `processed_bytes()` is the upload-size accounting surfaced through
ObjectUploader.upload.
"""

from __future__ import annotations

import io
import logging

from tieredstorage_tpu.storage.s3.client import S3Client

log = logging.getLogger(__name__)


class S3MultiPartOutputStream(io.RawIOBase):
    def __init__(self, client: S3Client, key: str, part_size: int):
        self.client = client
        self.key = key
        self.part_size = part_size
        self._buffer = bytearray()
        self._upload_id: str | None = None
        self._etags: list[tuple[int, str]] = []
        self._part_number = 0
        self._processed = 0
        self._aborted = False

    def writable(self) -> bool:
        return True

    @property
    def processed_bytes(self) -> int:
        return self._processed

    def write(self, data) -> int:
        if self.closed or self._aborted:
            raise ValueError("Stream is closed")
        view = memoryview(bytes(data))
        n = len(view)
        try:
            self._buffer.extend(view)
            while len(self._buffer) >= self.part_size:
                self._flush_part(self._buffer[: self.part_size])
                del self._buffer[: self.part_size]
        except Exception:
            self.abort()
            raise
        self._processed += n
        return n

    def _flush_part(self, data: bytes | bytearray) -> None:
        if self._upload_id is None:
            self._upload_id = self.client.create_multipart_upload(self.key)
        self._part_number += 1
        etag = self.client.upload_part(self.key, self._upload_id, self._part_number, bytes(data))
        self._etags.append((self._part_number, etag))

    def abort(self) -> None:
        """Best-effort abort; safe to call repeatedly
        (reference: S3MultiPartOutputStream.java:124-146)."""
        if self._aborted:
            return
        self._aborted = True
        if self._upload_id is not None:
            try:
                self.client.abort_multipart_upload(self.key, self._upload_id)
            except Exception:  # noqa: BLE001 — abort is best-effort by contract
                # Logged, not raised: the caller is already unwinding an
                # upload failure, but a leaked multipart upload accrues
                # storage until lifecycle cleanup, so leave a trace.
                log.warning(
                    "Failed to abort multipart upload %s for %s",
                    self._upload_id, self.key, exc_info=True,
                )
        self._buffer.clear()

    def close(self) -> None:
        if self.closed:
            return
        try:
            if not self._aborted:
                if self._upload_id is None:
                    # Whole object fit in one buffer: plain PutObject
                    # (cheaper than a 1-part multipart round trip).
                    self.client.put_object(self.key, bytes(self._buffer))
                else:
                    if self._buffer:
                        self._flush_part(self._buffer)
                        self._buffer.clear()
                    self.client.complete_multipart_upload(self.key, self._upload_id, self._etags)
        except Exception:
            self.abort()
            raise
        finally:
            super().close()
