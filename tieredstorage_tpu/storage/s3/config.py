"""S3 backend configuration.

Reference: storage/s3/.../S3StorageConfig.java:44-88 — bucket/endpoint/region,
path-style access, multipart part size (min 5 MiB), API call timeouts, static
credentials (both-or-neither validation), certificate/checksum toggles.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

from tieredstorage_tpu.config.configdef import (
    ConfigDef,
    ConfigException,
    ConfigKey,
    in_range,
    non_empty_string,
    null_or,
)

# The reference enforces the S3 API's 5 MiB floor
# (S3StorageConfig.java: S3_MULTIPART_UPLOAD_PART_SIZE_MIN).
MULTIPART_MIN_PART_SIZE = 5 * 1024 * 1024
DEFAULT_PART_SIZE = MULTIPART_MIN_PART_SIZE


def _definition() -> ConfigDef:
    d = ConfigDef()
    d.define(
        ConfigKey(
            "s3.bucket.name",
            "string",
            validator=non_empty_string,
            importance="high",
            doc="S3 bucket to store log segments",
        )
    )
    d.define(
        ConfigKey(
            "s3.region",
            "string",
            default="us-east-1",
            importance="medium",
            doc="AWS region where S3 bucket is placed",
        )
    )
    d.define(
        ConfigKey(
            "s3.endpoint.url",
            "string",
            default=None,
            importance="low",
            doc="Custom S3 endpoint URL. To be used with custom S3-compatible backends",
        )
    )
    d.define(
        ConfigKey(
            "s3.path.style.access.enabled",
            "bool",
            default=None,
            importance="low",
            doc="Whether to use path style access or virtual hosts. "
            "By default, path style is used with custom endpoints",
        )
    )
    d.define(
        ConfigKey(
            "s3.multipart.upload.part.size",
            "int",
            default=DEFAULT_PART_SIZE,
            validator=in_range(min_value=MULTIPART_MIN_PART_SIZE),
            importance="medium",
            doc="Size of parts in bytes to use when uploading. All parts but the last one will "
            "have this size. The smaller the part size, the more calls to S3 are needed to "
            "upload a file; increasing the size reduces calls but means buffering more bytes",
        )
    )
    d.define(
        ConfigKey(
            "s3.api.call.timeout",
            "long",
            default=None,
            validator=null_or(in_range(min_value=1)),
            importance="low",
            doc="AWS API call timeout in milliseconds, including all retries",
        )
    )
    d.define(
        ConfigKey(
            "s3.api.call.attempt.timeout",
            "long",
            default=None,
            validator=null_or(in_range(min_value=1)),
            importance="low",
            doc="AWS API call attempt (single retry) timeout in milliseconds",
        )
    )
    d.define(
        ConfigKey(
            "aws.access.key.id",
            "password",
            default=None,
            importance="medium",
            doc="AWS access key ID. To be used when static credentials are provided",
        )
    )
    d.define(
        ConfigKey(
            "aws.secret.access.key",
            "password",
            default=None,
            importance="medium",
            doc="AWS secret access key. To be used when static credentials are provided",
        )
    )
    d.define(
        ConfigKey(
            "aws.certificate.check.enabled",
            "bool",
            default=True,
            importance="low",
            doc="Enable TLS certificate verification of HTTPS connections",
        )
    )
    d.define(
        ConfigKey(
            "aws.checksum.check.enabled",
            "bool",
            default=False,
            importance="medium",
            doc="Enable checksum validation of uploaded objects (ETag/MD5 verification "
            "of each part on upload)",
        )
    )
    return d


class S3StorageConfig:
    DEFINITION = _definition()

    def __init__(self, props: Mapping[str, Any]):
        self._values = self.DEFINITION.parse(props)
        access = self._values.get("aws.access.key.id")
        secret = self._values.get("aws.secret.access.key")
        # Reference validates static credentials come as a pair
        # (S3StorageConfig.java validate(): both-or-neither).
        if (access is None) != (secret is None):
            raise ConfigException(
                "aws.access.key.id and aws.secret.access.key must be defined together"
            )

    @property
    def bucket_name(self) -> str:
        return self._values["s3.bucket.name"]

    @property
    def region(self) -> str:
        return self._values["s3.region"]

    @property
    def endpoint_url(self) -> Optional[str]:
        return self._values.get("s3.endpoint.url")

    @property
    def path_style_access(self) -> bool:
        v = self._values.get("s3.path.style.access.enabled")
        if v is None:
            # Default to path-style when a custom endpoint is set (emulators),
            # virtual-host style against real AWS endpoints.
            return self.endpoint_url is not None
        return bool(v)

    @property
    def part_size(self) -> int:
        return self._values["s3.multipart.upload.part.size"]

    @property
    def api_call_timeout_ms(self) -> Optional[int]:
        return self._values.get("s3.api.call.timeout")

    @property
    def api_call_attempt_timeout_ms(self) -> Optional[int]:
        return self._values.get("s3.api.call.attempt.timeout")

    @property
    def access_key_id(self) -> Optional[str]:
        return self._values.get("aws.access.key.id")

    @property
    def secret_access_key(self) -> Optional[str]:
        return self._values.get("aws.secret.access.key")

    @property
    def certificate_check_enabled(self) -> bool:
        return self._values["aws.certificate.check.enabled"]

    @property
    def checksum_check_enabled(self) -> bool:
        return self._values["aws.checksum.check.enabled"]
