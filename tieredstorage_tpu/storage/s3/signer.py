"""AWS Signature Version 4 request signing.

The reference delegates signing to the AWS SDK v2 (wired up in
storage/s3/.../S3ClientBuilder.java via static or provider credentials,
S3StorageConfig.java:44-88); this build signs requests itself so the backend
runs on the standard library alone. Implements the canonical-request /
string-to-sign / derived-key HMAC chain for service "s3" with the
x-amz-content-sha256 payload hash header (signed payloads throughout).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
from typing import Mapping, Optional
from urllib.parse import quote


EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


def uri_encode(value: str, *, encode_slash: bool) -> str:
    safe = "-._~" + ("" if encode_slash else "/")
    return quote(value, safe=safe)


class SigV4Signer:
    def __init__(self, access_key: str, secret_key: str, region: str, service: str = "s3"):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.service = service

    def sign(
        self,
        method: str,
        path: str,
        query: Mapping[str, str],
        headers: dict[str, str],
        payload: bytes,
        *,
        now: Optional[datetime.datetime] = None,
    ) -> dict[str, str]:
        """Returns `headers` extended with x-amz-date, x-amz-content-sha256
        and Authorization. `headers` must already contain Host.

        `path` must be the path exactly as it will be sent on the wire,
        percent-encoded once by the caller: for S3 the canonical URI is that
        wire path verbatim (re-encoding here would turn '%' into '%25' and
        break signatures for keys with spaces/'+'/'=' etc.)."""
        t = now or datetime.datetime.now(datetime.timezone.utc)
        amz_date = t.strftime("%Y%m%dT%H%M%SZ")
        datestamp = t.strftime("%Y%m%d")
        payload_hash = hashlib.sha256(payload).hexdigest() if payload else EMPTY_SHA256

        headers = dict(headers)
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_hash

        canonical_query = "&".join(
            f"{uri_encode(k, encode_slash=True)}={uri_encode(str(v), encode_slash=True)}"
            for k, v in sorted(query.items())
        )
        lower = {k.lower(): str(v).strip() for k, v in headers.items()}
        signed_headers = ";".join(sorted(lower))
        canonical_headers = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))
        canonical_request = "\n".join(
            [
                method,
                path or "/",
                canonical_query,
                canonical_headers,
                signed_headers,
                payload_hash,
            ]
        )
        scope = f"{datestamp}/{self.region}/{self.service}/aws4_request"
        string_to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical_request.encode("utf-8")).hexdigest(),
            ]
        )
        k_date = _hmac(("AWS4" + self.secret_key).encode("utf-8"), datestamp)
        k_region = _hmac(k_date, self.region)
        k_service = _hmac(k_region, self.service)
        k_signing = _hmac(k_service, "aws4_request")
        signature = hmac.new(
            k_signing, string_to_sign.encode("utf-8"), hashlib.sha256
        ).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        )
        return headers
