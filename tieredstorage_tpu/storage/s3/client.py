"""Thin S3 REST client: request shaping, signing, XML, error mapping.

Replaces the reference's AWS SDK v2 client (built in
storage/s3/.../S3ClientBuilder.java — region/endpoint/path-style/credentials/
timeouts); the operations implemented are exactly the ones S3Storage.java
uses: PutObject, GetObject (ranged), DeleteObject, DeleteObjects,
CreateMultipartUpload, UploadPart, CompleteMultipartUpload,
AbortMultipartUpload.
"""

from __future__ import annotations

import hashlib
import xml.etree.ElementTree as ET
from typing import BinaryIO, Mapping, Optional
from urllib.parse import quote

from tieredstorage_tpu.storage.httpclient import (
    HttpClient,
    HttpResponse,
    Observer,
    RetryPolicy,
    SocketFactory,
)
from tieredstorage_tpu.storage.s3.signer import SigV4Signer


class S3ApiError(Exception):
    def __init__(self, status: int, code: str, message: str):
        super().__init__(f"S3 error {status} {code}: {message}")
        self.status = status
        self.code = code
        self.message = message


def _parse_error(resp: HttpResponse) -> S3ApiError:
    code, message = "", ""
    try:
        root = ET.fromstring(resp.body)
        code = root.findtext("Code") or ""
        message = root.findtext("Message") or ""
    except ET.ParseError:
        pass
    return S3ApiError(resp.status, code, message)


class S3Client:
    def __init__(
        self,
        bucket: str,
        region: str,
        *,
        endpoint_url: Optional[str] = None,
        path_style: bool = True,
        access_key: Optional[str] = None,
        secret_key: Optional[str] = None,
        timeout: Optional[float] = None,
        verify_tls: bool = True,
        checksum_check: bool = False,
        socket_factory: Optional[SocketFactory] = None,
        observer: Optional[Observer] = None,
        retry: Optional[RetryPolicy] = None,
    ) -> None:
        self.bucket = bucket
        self.checksum_check = checksum_check
        if endpoint_url is None:
            host = (
                f"{bucket}.s3.{region}.amazonaws.com"
                if not path_style
                else f"s3.{region}.amazonaws.com"
            )
            endpoint_url = f"https://{host}"
            self.path_style = path_style
        else:
            self.path_style = path_style
        self.http = HttpClient(
            endpoint_url,
            timeout=timeout,
            verify_tls=verify_tls,
            socket_factory=socket_factory,
            observer=observer,
            retry=retry,
        )
        self.signer = (
            SigV4Signer(access_key, secret_key, region)
            if access_key is not None and secret_key is not None
            else None
        )

    # --------------------------------------------------------------- shaping
    def _path(self, key: str) -> str:
        encoded = quote(key, safe="/-._~")
        if self.path_style:
            return f"{self.http.base_path}/{self.bucket}/{encoded}"
        return f"{self.http.base_path}/{encoded}"

    def _host_header(self) -> str:
        default_port = 443 if self.http.scheme == "https" else 80
        if self.http.port != default_port:
            return f"{self.http.host}:{self.http.port}"
        return self.http.host

    def _headers(
        self,
        method: str,
        path: str,
        query: Mapping[str, str],
        payload: bytes,
        extra: Optional[Mapping[str, str]] = None,
    ) -> dict[str, str]:
        headers: dict[str, str] = {"Host": self._host_header()}
        if extra:
            headers.update(extra)
        if self.signer is not None:
            headers = self.signer.sign(method, path, query, headers, payload)
        return headers

    @staticmethod
    def _query_string(query: Mapping[str, str]) -> str:
        if not query:
            return ""
        parts = []
        for k, v in sorted(query.items()):
            parts.append(f"{quote(k, safe='-._~')}={quote(str(v), safe='-._~')}" if v != "" else k)
        return "?" + "&".join(parts)

    def _call(
        self,
        method: str,
        key: str,
        *,
        query: Optional[Mapping[str, str]] = None,
        body: bytes = b"",
        extra_headers: Optional[Mapping[str, str]] = None,
        ok: tuple[int, ...] = (200,),
        idempotent: Optional[bool] = None,
    ) -> HttpResponse:
        query = dict(query or {})
        path = self._path(key)
        headers = self._headers(method, path, query, body, extra_headers)
        resp = self.http.request(
            method,
            path + self._query_string(query),
            headers=headers,
            body=body,
            idempotent=idempotent,
        )
        if resp.status not in ok:
            raise _parse_error(resp)
        return resp

    # ------------------------------------------------------------ operations
    def put_object(self, key: str, data: bytes) -> None:
        extra = {"Content-Length": str(len(data))}
        if self.checksum_check:
            import base64

            extra["Content-MD5"] = base64.b64encode(hashlib.md5(data).digest()).decode()
        self._call("PUT", key, body=data, extra_headers=extra)

    def get_object_stream(
        self, key: str, byte_range: Optional[tuple[int, int]] = None
    ) -> tuple[int, Mapping[str, str], BinaryIO]:
        path = self._path(key)
        extra: dict[str, str] = {}
        if byte_range is not None:
            extra["Range"] = f"bytes={byte_range[0]}-{byte_range[1]}"
        headers = self._headers("GET", path, {}, b"", extra)
        return self.http.request_stream("GET", path, headers=headers)

    def delete_object(self, key: str) -> None:
        self._call("DELETE", key, ok=(204, 200))

    def list_objects_v2(
        self,
        prefix: str = "",
        continuation_token: Optional[str] = None,
        max_keys: Optional[int] = None,
    ) -> tuple[list[str], Optional[str]]:
        """One ListObjectsV2 page: (keys, next continuation token or None).

        S3 caps pages at 1000 keys; callers loop while a token comes back
        (S3Storage.list_objects does)."""
        query: dict[str, str] = {"list-type": "2"}
        if prefix:
            query["prefix"] = prefix
        if continuation_token:
            query["continuation-token"] = continuation_token
        if max_keys is not None:
            query["max-keys"] = str(max_keys)
        resp = self._call("GET", "", query=query)
        root = ET.fromstring(resp.body)
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        keys = [
            contents.findtext(f"{ns}Key") or ""
            for contents in root.findall(f"{ns}Contents")
        ]
        truncated = (root.findtext(f"{ns}IsTruncated") or "").lower() == "true"
        token = root.findtext(f"{ns}NextContinuationToken") if truncated else None
        return keys, token

    def delete_objects(self, keys: list[str]) -> None:
        """Native bulk delete — one DeleteObjects call for up to 1000 keys
        (reference: S3Storage.java:82-97)."""
        root = ET.Element("Delete")
        ET.SubElement(root, "Quiet").text = "true"
        for k in keys:
            obj = ET.SubElement(root, "Object")
            ET.SubElement(obj, "Key").text = k
        body = ET.tostring(root, encoding="utf-8", xml_declaration=True)
        import base64

        extra = {
            "Content-MD5": base64.b64encode(hashlib.md5(body).digest()).decode(),
            "Content-Type": "application/xml",
        }
        # Replay-safe despite being a POST: re-deleting deleted keys is a
        # no-op, so a stale pooled connection (e.g. through a SOCKS proxy)
        # may retry once.
        resp = self._call(
            "POST", "", query={"delete": ""}, body=body, extra_headers=extra,
            idempotent=True,
        )
        # Non-quiet errors come back per-key; surface the first one.
        try:
            root = ET.fromstring(resp.body)
        except ET.ParseError:
            return
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        err = root.find(f"{ns}Error")
        if err is not None:
            raise S3ApiError(
                200, err.findtext(f"{ns}Code") or "", err.findtext(f"{ns}Message") or ""
            )

    def create_multipart_upload(self, key: str) -> str:
        # Replay-safe despite being a POST: a duplicate CreateMultipartUpload
        # just opens a second upload id whose parts are never completed, and
        # the abort-on-error path (multipart.py) cleans the one we keep a
        # handle to; the AWS SDK retries this call for the same reason.
        resp = self._call("POST", key, query={"uploads": ""}, idempotent=True)
        root = ET.fromstring(resp.body)
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        upload_id = root.findtext(f"{ns}UploadId")
        if not upload_id:
            raise S3ApiError(resp.status, "MalformedResponse", "no UploadId in response")
        return upload_id

    def upload_part(self, key: str, upload_id: str, part_number: int, data: bytes) -> str:
        extra = {"Content-Length": str(len(data))}
        if self.checksum_check:
            import base64

            extra["Content-MD5"] = base64.b64encode(hashlib.md5(data).digest()).decode()
        resp = self._call(
            "PUT",
            key,
            query={"partNumber": str(part_number), "uploadId": upload_id},
            body=data,
            extra_headers=extra,
        )
        etag = resp.header("etag", "")
        if not etag:
            # Fail here, not at CompleteMultipartUpload, where a blank ETag
            # surfaces as a confusing MalformedXML-style error far from the
            # cause (some proxies/S3-compatible stores omit the header).
            raise S3ApiError(
                resp.status, "MissingETag", f"no ETag returned for part {part_number}"
            )
        return etag

    def complete_multipart_upload(
        self, key: str, upload_id: str, etags: list[tuple[int, str]]
    ) -> None:
        root = ET.Element("CompleteMultipartUpload")
        for number, etag in etags:
            part = ET.SubElement(root, "Part")
            ET.SubElement(part, "PartNumber").text = str(number)
            ET.SubElement(part, "ETag").text = etag
        body = ET.tostring(root, encoding="utf-8", xml_declaration=True)
        resp = self._call("POST", key, query={"uploadId": upload_id}, body=body)
        # Complete can return 200 with an error document.
        try:
            doc = ET.fromstring(resp.body)
        except ET.ParseError:
            return
        if doc.tag.endswith("Error"):
            raise _parse_error(resp)

    def abort_multipart_upload(self, key: str, upload_id: str) -> None:
        self._call("DELETE", key, query={"uploadId": upload_id}, ok=(204, 200))

    def close(self) -> None:
        self.http.close()
