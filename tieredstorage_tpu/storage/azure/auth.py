"""Azure Storage SharedKey request signing.

The reference delegates to azure-storage-blob's StorageSharedKeyCredential
(AzureBlobStorage.java:63-70); this build signs the Blob REST requests
itself: HMAC-SHA256 over the 2015+ string-to-sign layout (verb, standard
headers, canonicalized x-ms-* headers, canonicalized resource with sorted
lowercase query params).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
from typing import Mapping
from urllib.parse import unquote


class SharedKeyAuth:
    def __init__(self, account: str, key_base64: str):
        self.account = account
        self.key = base64.b64decode(key_base64)

    def sign(
        self,
        method: str,
        path: str,
        query: Mapping[str, str],
        headers: dict[str, str],
        content_length: int,
    ) -> dict[str, str]:
        """Returns `headers` extended with Authorization. Requires x-ms-date
        and x-ms-version already present."""
        lower = {k.lower(): str(v).strip() for k, v in headers.items()}
        canonical_headers = "".join(
            f"{k}:{lower[k]}\n" for k in sorted(lower) if k.startswith("x-ms-")
        )
        canonical_resource = f"/{self.account}{unquote(path)}"
        for k in sorted(query, key=str.lower):
            canonical_resource += f"\n{k.lower()}:{query[k]}"
        string_to_sign = "\n".join(
            [
                method,
                lower.get("content-encoding", ""),
                lower.get("content-language", ""),
                str(content_length) if content_length else "",
                lower.get("content-md5", ""),
                lower.get("content-type", ""),
                "",  # Date — empty because x-ms-date is set
                lower.get("if-modified-since", ""),
                lower.get("if-match", ""),
                lower.get("if-none-match", ""),
                lower.get("if-unmodified-since", ""),
                lower.get("range", ""),
                canonical_headers + canonical_resource,
            ]
        )
        signature = base64.b64encode(
            hmac.new(self.key, string_to_sign.encode("utf-8"), hashlib.sha256).digest()
        ).decode()
        out = dict(headers)
        out["Authorization"] = f"SharedKey {self.account}:{signature}"
        return out
