"""Azure backend request metrics.

Reference: storage/azure/.../MetricCollector.java + MetricRegistry.java —
an HTTP pipeline policy classifying requests into blob-get / blob-upload /
blob-delete / block-upload / block-list. Same classes here, with sensor
shapes from the shared RequestMetricCollector.
"""

from __future__ import annotations

from typing import Optional

from tieredstorage_tpu.storage.request_metrics import RequestMetricCollector

GROUP = "azure-blob-client-metrics"
CONTEXT = "aiven.kafka.server.tieredstorage.azure"


def _classify(method: str, path_and_query: str) -> Optional[str]:
    query = path_and_query.partition("?")[2]
    params = dict(p.partition("=")[::2] for p in query.split("&") if p)
    comp = params.get("comp")
    if method == "GET":
        return "blob-get"
    if method == "PUT":
        if comp == "block":
            return "block-upload"
        if comp == "blocklist":
            return "block-list"
        return "blob-upload"
    if method == "DELETE":
        return "blob-delete"
    return None


class AzureMetricCollector(RequestMetricCollector):
    def __init__(self, registry=None):
        super().__init__(GROUP, _classify, registry)
