"""Azure backend request metrics.

Reference: storage/azure/.../MetricCollector.java + MetricRegistry.java —
an HTTP pipeline policy classifying requests into blob-get / blob-upload /
blob-delete / block-upload / block-list. Same classes here, fed by the
HttpClient observer.
"""

from __future__ import annotations

from typing import Optional

from tieredstorage_tpu.metrics.core import (
    Avg,
    Max,
    MetricName,
    MetricsRegistry,
    Rate,
    Total,
)

GROUP = "azure-blob-client-metrics"
CONTEXT = "aiven.kafka.server.tieredstorage.azure"


def _classify(method: str, path_and_query: str) -> Optional[str]:
    query = path_and_query.partition("?")[2]
    params = dict(
        p.partition("=")[::2] for p in query.split("&") if p
    )
    comp = params.get("comp")
    if method == "GET":
        return "blob-get"
    if method == "PUT":
        if comp == "block":
            return "block-upload"
        if comp == "blocklist":
            return "block-list"
        return "blob-upload"
    if method == "DELETE":
        return "blob-delete"
    return None


class AzureMetricCollector:
    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()

    def observe(
        self,
        method: str,
        path_and_query: str,
        status: int,
        elapsed_s: float,
        error: Optional[BaseException],
    ) -> None:
        op = _classify(method, path_and_query)
        if op is None:
            return
        requests = self.registry.sensor(f"{op}-requests")
        requests.ensure_stats(
            lambda: [
                (MetricName.of(f"{op}-requests-rate", GROUP), Rate()),
                (MetricName.of(f"{op}-requests-total", GROUP), Total()),
            ]
        )
        requests.record(1.0)
        timing = self.registry.sensor(f"{op}-time")
        timing.ensure_stats(
            lambda: [
                (MetricName.of(f"{op}-time-avg", GROUP), Avg()),
                (MetricName.of(f"{op}-time-max", GROUP), Max()),
            ]
        )
        timing.record(elapsed_s * 1000.0)
