"""Azure Blob StorageBackend over the Blob REST API.

Reference: storage/azure/.../AzureBlobStorage.java:48-170 — auth from
connection string / SharedKey / SAS / default credential; upload through a
block-blob output stream with `azure.upload.block.size` blocks (small bodies
use single PutBlob — the reference sets maxSingleUploadSize=blockSize so the
same threshold applies); ranged GetBlob; DeleteBlob. 404 BlobNotFound →
KeyNotFoundException, 416 → InvalidRangeException.
"""

from __future__ import annotations

import base64
import email.utils
import itertools
import secrets
import xml.etree.ElementTree as ET
from typing import BinaryIO, Mapping, Optional
from urllib.parse import parse_qsl, quote

from tieredstorage_tpu.storage.azure.auth import SharedKeyAuth
from tieredstorage_tpu.storage.azure.config import AzureBlobStorageConfig
from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
    iter_chunks,
)
from tieredstorage_tpu.storage.httpclient import HttpClient, HttpError
from tieredstorage_tpu.storage.proxy import ProxyConfig, socks5_socket_factory

API_VERSION = "2021-08-06"
_COPY_BUFFER = 1024 * 1024


class AzureBlobStorage(StorageBackend):
    def __init__(self) -> None:
        self.http: Optional[HttpClient] = None
        self.container = ""
        self.block_size = 0
        self._auth: Optional[SharedKeyAuth] = None
        self._sas_params: list[tuple[str, str]] = []
        self._metric_collector = None

    def configure(self, configs: Mapping[str, object]) -> None:
        config = AzureBlobStorageConfig(configs)
        proxy = ProxyConfig.from_configs(configs)
        endpoint, account, key, sas = config.resolve()
        from tieredstorage_tpu.storage.azure.metrics import AzureMetricCollector

        self._metric_collector = AzureMetricCollector()
        self.http = HttpClient(
            endpoint,
            socket_factory=socks5_socket_factory(proxy),
            observer=self._metric_collector.observe,
        )
        self.container = config.container_name
        self.block_size = config.upload_block_size
        self._auth = SharedKeyAuth(account, key) if account and key else None
        self._sas_params = list(parse_qsl(sas.lstrip("?"))) if sas else []

    # ------------------------------------------------------------- plumbing
    def _require_http(self) -> HttpClient:
        if self.http is None:
            raise StorageBackendException("AzureBlobStorage is not configured")
        return self.http

    def _request(
        self,
        method: str,
        key_value: Optional[str],
        query: dict[str, str],
        *,
        body: bytes = b"",
        extra_headers: Optional[dict[str, str]] = None,
        stream: bool = False,
    ):
        http = self._require_http()
        # key_value=None addresses the container itself (List Blobs).
        if key_value is None:
            path = f"{http.base_path}/{self.container}"
        else:
            path = f"{http.base_path}/{self.container}/" + quote(key_value, safe="/-._~")
        headers = {
            "Host": f"{http.host}:{http.port}",
            # RFC 1123 date, locale-independent (strftime %a/%b would break
            # signing under a non-English LC_TIME).
            "x-ms-date": email.utils.formatdate(usegmt=True),
            "x-ms-version": API_VERSION,
        }
        if body:
            headers["Content-Length"] = str(len(body))
        if extra_headers:
            headers.update(extra_headers)
        all_query = dict(query)
        for k, v in self._sas_params:
            all_query.setdefault(k, v)
        if self._auth is not None:
            headers = self._auth.sign(method, path, all_query, headers, len(body))
        qs = "&".join(
            f"{quote(k, safe='-._~')}={quote(str(v), safe='-._~')}" for k, v in all_query.items()
        )
        target = path + ("?" + qs if qs else "")
        if stream:
            return http.request_stream(method, target, headers=headers)
        return http.request(method, target, headers=headers, body=body)

    # --------------------------------------------------------------- upload
    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        try:
            chunks = iter_chunks(input_stream, self.block_size, read_size=_COPY_BUFFER)
            first = next(chunks, b"")
            second = next(chunks, None)
            if second is None:
                # Fits in one block → single PutBlob (the reference's
                # maxSingleUploadSize=blockSize path).
                resp = self._request(
                    "PUT",
                    key.value,
                    {},
                    body=first,
                    extra_headers={"x-ms-blob-type": "BlockBlob"},
                )
                if resp.status not in (201, 200):
                    raise StorageBackendException(
                        f"Failed to upload {key}: HTTP {resp.status}"
                    )
                return len(first)
            # Block upload: PutBlock per block, then PutBlockList.
            block_ids: list[str] = []
            total = 0
            prefix = secrets.token_hex(8)
            for chunk in itertools.chain([first, second], chunks):
                block_id = base64.b64encode(
                    f"{prefix}-{len(block_ids):06d}".encode()
                ).decode()
                resp = self._request(
                    "PUT", key.value, {"comp": "block", "blockid": block_id}, body=chunk
                )
                if resp.status not in (201, 200):
                    raise StorageBackendException(
                        f"Failed to upload block for {key}: HTTP {resp.status}"
                    )
                block_ids.append(block_id)
                total += len(chunk)
            root = ET.Element("BlockList")
            for bid in block_ids:
                ET.SubElement(root, "Latest").text = bid
            body = ET.tostring(root, encoding="utf-8", xml_declaration=True)
            resp = self._request(
                "PUT",
                key.value,
                {"comp": "blocklist"},
                body=body,
                extra_headers={"Content-Type": "application/xml"},
            )
            if resp.status not in (201, 200):
                raise StorageBackendException(
                    f"Failed to commit block list for {key}: HTTP {resp.status}"
                )
            return total
        except HttpError as e:
            raise StorageBackendException(f"Failed to upload {key}") from e

    # ---------------------------------------------------------------- fetch
    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        extra = {}
        if byte_range is not None:
            extra["x-ms-range"] = f"bytes={byte_range.from_position}-{byte_range.to_position}"
        try:
            status, headers, stream = self._request(
                "GET", key.value, {}, extra_headers=extra, stream=True
            )
        except HttpError as e:
            raise StorageBackendException(f"Failed to fetch {key}") from e
        if status in (200, 206):
            return stream
        body = stream.read()
        stream.close()
        if status == 404:
            raise KeyNotFoundException(self, key)
        if status == 416:
            raise InvalidRangeException(f"Failed to fetch {key}: Invalid range {byte_range}")
        raise StorageBackendException(f"Failed to fetch {key}: HTTP {status}: {body[:200]!r}")

    # ----------------------------------------------------------------- list
    def list_objects(self, prefix: str = ""):
        """List Blobs (restype=container&comp=list), paged via markers; the
        service returns names in lexicographic order."""
        marker = ""
        while True:
            query = {"restype": "container", "comp": "list"}
            if prefix:
                query["prefix"] = prefix
            if marker:
                query["marker"] = marker
            try:
                resp = self._request("GET", None, query)
            except HttpError as e:
                raise StorageBackendException(
                    f"Failed to list blobs with prefix {prefix!r}"
                ) from e
            if resp.status != 200:
                raise StorageBackendException(
                    f"Failed to list blobs with prefix {prefix!r}: HTTP {resp.status}"
                )
            root = ET.fromstring(resp.body)
            blobs = root.find("Blobs")
            for blob in blobs.findall("Blob") if blobs is not None else ():
                name = blob.findtext("Name")
                if name:
                    yield ObjectKey(name)
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return

    # --------------------------------------------------------------- delete
    def delete(self, key: ObjectKey) -> None:
        try:
            resp = self._request("DELETE", key.value, {})
        except HttpError as e:
            raise StorageBackendException(f"Failed to delete {key}") from e
        if resp.status not in (202, 200, 404):  # missing keys are not an error
            raise StorageBackendException(f"Failed to delete {key}: HTTP {resp.status}")

    @property
    def metrics(self):
        return self._metric_collector

    def close(self) -> None:
        if self.http is not None:
            self.http.close()

    def __str__(self) -> str:
        return f"AzureBlobStorage{{container={self.container}}}"
