"""Azure Blob backend configuration.

Reference: storage/azure/.../AzureBlobStorageConfig.java:30-170 — account
name/key, SAS token, container, endpoint, connection string (mutually
exclusive with name/key/endpoint), upload block size 100 KiB..2 GiB
(default 5 MiB), plus `proxy.*` sub-config.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

from tieredstorage_tpu.config.configdef import (
    ConfigDef,
    ConfigException,
    ConfigKey,
    in_range,
    non_empty_string,
    null_or,
)

UPLOAD_BLOCK_SIZE_DEFAULT = 5 * 1024 * 1024
UPLOAD_BLOCK_SIZE_MIN = 100 * 1024
UPLOAD_BLOCK_SIZE_MAX = 2**31 - 1


def _valid_url(name: str, value) -> None:
    from urllib.parse import urlsplit

    parts = urlsplit(str(value))
    if parts.scheme not in ("http", "https") or not parts.netloc:
        raise ConfigException(f"Invalid value {value} for configuration {name}: must be a valid URL")


def _definition() -> ConfigDef:
    d = ConfigDef()
    d.define(
        ConfigKey(
            "azure.account.name",
            "string",
            default=None,
            validator=null_or(non_empty_string),
            importance="high",
            doc="Azure account name",
        )
    )
    d.define(
        ConfigKey(
            "azure.account.key",
            "password",
            default=None,
            validator=null_or(non_empty_string),
            importance="medium",
            doc="Azure account key",
        )
    )
    d.define(
        ConfigKey(
            "azure.sas.token",
            "password",
            default=None,
            validator=null_or(non_empty_string),
            importance="medium",
            doc="Azure SAS token",
        )
    )
    d.define(
        ConfigKey(
            "azure.container.name",
            "string",
            validator=non_empty_string,
            importance="high",
            doc="Azure container to store log segments",
        )
    )
    d.define(
        ConfigKey(
            "azure.endpoint.url",
            "string",
            default=None,
            validator=null_or(_valid_url),
            importance="low",
            doc="Custom Azure Blob Storage endpoint URL",
        )
    )
    d.define(
        ConfigKey(
            "azure.connection.string",
            "password",
            default=None,
            validator=null_or(non_empty_string),
            importance="medium",
            doc="Azure connection string. Cannot be used together with azure.account.name, "
            "azure.account.key, and azure.endpoint.url",
        )
    )
    d.define(
        ConfigKey(
            "azure.upload.block.size",
            "int",
            default=UPLOAD_BLOCK_SIZE_DEFAULT,
            validator=in_range(UPLOAD_BLOCK_SIZE_MIN, UPLOAD_BLOCK_SIZE_MAX),
            importance="medium",
            doc="Size of blocks to use when uploading objects to Azure",
        )
    )
    return d


def parse_connection_string(conn: str) -> dict[str, str]:
    parts: dict[str, str] = {}
    for piece in conn.split(";"):
        piece = piece.strip()
        if not piece:
            continue
        k, _, v = piece.partition("=")
        parts[k] = v
    return parts


class AzureBlobStorageConfig:
    DEFINITION = _definition()

    def __init__(self, props: Mapping[str, Any]):
        self._values = self.DEFINITION.parse(props)
        # Mutual-exclusion rules (AzureBlobStorageConfig.validate()).
        if self.connection_string is not None:
            for other in ("azure.account.name", "azure.account.key", "azure.sas.token",
                          "azure.endpoint.url"):
                if self._values.get(other) is not None:
                    raise ConfigException(
                        f'"azure.connection.string" cannot be set together with "{other}".'
                    )
        else:
            if self.account_name is None:
                raise ConfigException(
                    '"azure.account.name" must be set if "azure.connection.string" is not set.'
                )
            if self.account_key is not None and self.sas_token is not None:
                raise ConfigException(
                    '"azure.account.key" and "azure.sas.token" cannot be set together.'
                )

    @property
    def account_name(self) -> Optional[str]:
        return self._values.get("azure.account.name")

    @property
    def account_key(self) -> Optional[str]:
        return self._values.get("azure.account.key")

    @property
    def sas_token(self) -> Optional[str]:
        return self._values.get("azure.sas.token")

    @property
    def container_name(self) -> str:
        return self._values["azure.container.name"]

    @property
    def endpoint_url(self) -> Optional[str]:
        return self._values.get("azure.endpoint.url")

    @property
    def connection_string(self) -> Optional[str]:
        return self._values.get("azure.connection.string")

    @property
    def upload_block_size(self) -> int:
        return self._values["azure.upload.block.size"]

    def resolve(self) -> tuple[str, Optional[str], Optional[str], Optional[str]]:
        """→ (endpoint, account_name, account_key, sas_token), from either the
        connection string or the individual keys (AzureBlobStorage.endpointUrl)."""
        if self.connection_string is not None:
            parts = parse_connection_string(self.connection_string)
            account = parts.get("AccountName")
            key = parts.get("AccountKey")
            endpoint = parts.get("BlobEndpoint")
            if endpoint is None:
                protocol = parts.get("DefaultEndpointsProtocol", "https")
                suffix = parts.get("EndpointSuffix", "core.windows.net")
                if account is None:
                    raise ConfigException("Connection string has no AccountName or BlobEndpoint")
                endpoint = f"{protocol}://{account}.blob.{suffix}"
            return endpoint, account, key, parts.get("SharedAccessSignature")
        endpoint = self.endpoint_url or f"https://{self.account_name}.blob.core.windows.net"
        return endpoint, self.account_name, self.account_key, self.sas_token
