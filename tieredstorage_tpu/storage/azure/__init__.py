"""Azure Blob storage backend (Blob REST API over stdlib HTTP, no SDK).

Reference module: storage/azure (AzureBlobStorage.java,
AzureBlobStorageConfig.java, MetricCollector.java).
"""

from tieredstorage_tpu.storage.azure.config import AzureBlobStorageConfig
from tieredstorage_tpu.storage.azure.storage import AzureBlobStorage

__all__ = ["AzureBlobStorage", "AzureBlobStorageConfig"]
