"""Local-filesystem storage backend (testing/demo, like the reference's).

Reference: storage/filesystem/.../FileSystemStorage.java:38-115 and
FileSystemStorageConfig.java (`root`, `overwrite.enabled`).
"""

from __future__ import annotations

import io
import os
import shutil
from pathlib import Path
from typing import BinaryIO, Mapping, Optional

from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
)
from tieredstorage_tpu.utils.streams import BoundedStream, copy_stream


class FileSystemStorage(StorageBackend):
    """Objects are plain files under `root`; key path separators map to dirs."""

    def __init__(self) -> None:
        self.fs_root: Path | None = None
        self.overwrite_enabled = False

    def configure(self, configs: Mapping[str, object]) -> None:
        root = configs.get("root")
        if root is None:
            raise ValueError("root must be provided")
        self.fs_root = Path(str(root))
        if not self.fs_root.is_dir() or not os.access(self.fs_root, os.W_OK):
            # Reference validates root is an existing writable directory.
            raise ValueError(f"root {self.fs_root} must be a writable directory")
        self.overwrite_enabled = _as_bool(configs.get("overwrite.enabled", False))

    def _path(self, key: ObjectKey) -> Path:
        assert self.fs_root is not None, "backend not configured"
        p = (self.fs_root / key.value).resolve()
        if self.fs_root.resolve() not in p.parents and p != self.fs_root.resolve():
            raise StorageBackendException(f"Key {key} escapes storage root")
        return p

    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        path = self._path(key)
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            if not self.overwrite_enabled and path.exists():
                raise StorageBackendException(
                    f"File {path} already exists and overwriting is disabled"
                )
            tmp = path.with_name(path.name + ".part")
            try:
                with open(tmp, "wb") as out:
                    written = copy_stream(input_stream, out)
                os.replace(tmp, path)
            finally:
                if tmp.exists():
                    tmp.unlink(missing_ok=True)
            return written
        except OSError as e:
            raise StorageBackendException(f"Failed to upload {key}", ) from e

    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        path = self._path(key)
        try:
            file_size = path.stat().st_size
        except FileNotFoundError as e:
            raise KeyNotFoundException(self, key, e) from e
        try:
            if byte_range is None:
                return open(path, "rb")
            # Reference semantics (FileSystemStorage.java:69-92): start beyond
            # EOF is InvalidRange; a range overrunning EOF returns the suffix.
            if byte_range.from_position >= file_size:
                raise InvalidRangeException(
                    f"Range start position {byte_range.from_position} is outside file content. "
                    f"file size = {file_size}, range = {byte_range}"
                )
            f = open(path, "rb")
            f.seek(byte_range.from_position)
            size = min(byte_range.size, file_size - byte_range.from_position)
            return BoundedStream(f, size)
        except OSError as e:
            raise StorageBackendException(f"Failed to fetch {key}") from e

    def delete(self, key: ObjectKey) -> None:
        path = self._path(key)
        try:
            path.unlink(missing_ok=True)
            # Prune now-empty parent directories up to the root
            # (reference: FileSystemStorage.java:95-109).
            assert self.fs_root is not None
            parent = path.parent
            root = self.fs_root.resolve()
            while parent.resolve() != root:
                try:
                    parent.rmdir()
                except OSError:
                    break
                parent = parent.parent
        except OSError as e:
            raise StorageBackendException(f"Failed to delete {key}") from e

    def list_objects(self, prefix: str = ""):
        assert self.fs_root is not None, "backend not configured"
        root = self.fs_root.resolve()
        keys: list[str] = []
        try:
            for dirpath, _dirnames, filenames in os.walk(root):
                for name in filenames:
                    rel = os.path.relpath(os.path.join(dirpath, name), root)
                    key = rel.replace(os.sep, "/")
                    if key.startswith(prefix):
                        keys.append(key)
        except OSError as e:
            raise StorageBackendException("Failed to list storage root") from e
        for key in sorted(keys):
            yield ObjectKey(key)

    def __str__(self) -> str:
        return f"FileSystemStorage{{root={self.fs_root}, overwriteEnabled={self.overwrite_enabled}}}"


def _as_bool(v: object) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("true", "1", "yes")
