"""Storage backend SPI: keys, byte ranges, upload/fetch/delete contracts.

Behavior parity with the reference's storage-core module
(reference: storage/core/src/main/java/io/aiven/kafka/tieredstorage/storage/
 StorageBackend.java:21, ObjectFetcher.java:21-35, ObjectUploader.java:21-27,
 ObjectDeleter.java:21-38, BytesRange.java:21-101, ObjectKey.java:18-20),
re-designed as Python protocols so backends are duck-typed and reflectively
instantiable from config (`storage.backend.class`).
"""

from __future__ import annotations

import abc
import dataclasses
import importlib
from typing import BinaryIO, Iterable, Mapping, Optional


class StorageBackendException(Exception):
    """Base error for storage backend failures.

    Reference: storage/core/.../StorageBackendException.java.
    """


class KeyNotFoundException(StorageBackendException):
    """Requested object key does not exist in the backend.

    Reference: storage/core/.../KeyNotFoundException.java (S3 404 mapping at
    storage/s3/.../S3Storage.java:115-141).
    """

    def __init__(self, backend: object, key: "ObjectKey", cause: Exception | None = None):
        super().__init__(f"Key {key} does not exists in storage {backend}")
        self.key = key
        self.__cause__ = cause


class InvalidRangeException(StorageBackendException):
    """Requested byte range cannot be satisfied (e.g. offset beyond object size).

    Reference: storage/core/.../InvalidRangeException.java (S3 416 mapping).
    """


@dataclasses.dataclass(frozen=True)
class ObjectKey:
    """Opaque object key; `value` is the full key string in the store.

    Reference: storage/core/.../ObjectKey.java:18-20.
    """

    value: str

    def __str__(self) -> str:  # match reference's ObjectKey.value() display
        return self.value


@dataclasses.dataclass(frozen=True)
class BytesRange:
    """Inclusive byte range [from_position, to_position].

    Reference: storage/core/.../BytesRange.java:21-101 (inclusive semantics,
    `ofFromPositionAndSize` constructor, validation).
    """

    from_position: int
    to_position: int

    def __post_init__(self) -> None:
        if self.from_position < 0:
            raise ValueError(f"from cannot be negative, {self.from_position} given")
        if self.to_position < self.from_position:
            raise ValueError(
                f"to cannot be less than from, from={self.from_position}, to={self.to_position} given"
            )

    @staticmethod
    def of(from_position: int, to_position: int) -> "BytesRange":
        return BytesRange(from_position, to_position)

    @staticmethod
    def of_from_position_and_size(position: int, size: int) -> "BytesRange":
        if size <= 0:
            raise ValueError(f"size must be positive, {size} given")
        return BytesRange(position, position + size - 1)

    @property
    def size(self) -> int:
        return self.to_position - self.from_position + 1

    def __str__(self) -> str:
        return f"BytesRange{{{self.from_position}..{self.to_position}}}"


def iter_chunks(stream: BinaryIO, chunk_size: int, *, read_size: int = 1 << 20):
    """Yield successive `chunk_size` slices of `stream` (last may be short).

    Single-sources the accumulate-and-slice EOF handling used by the block/
    resumable upload paths of the cloud backends.
    """
    pending = b""
    eof = False
    while True:
        while len(pending) < chunk_size and not eof:
            block = stream.read(read_size)
            if not block:
                eof = True
                break
            pending += block
        if eof and not pending:
            return
        chunk, pending = pending[:chunk_size], pending[chunk_size:]
        if chunk:
            yield chunk
        if eof and not pending:
            return


class ObjectUploader(abc.ABC):
    """Reference: storage/core/.../ObjectUploader.java:21-27."""

    @abc.abstractmethod
    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        """Upload the stream under `key`; returns the number of bytes stored."""


class ObjectFetcher(abc.ABC):
    """Reference: storage/core/.../ObjectFetcher.java:21-35."""

    @abc.abstractmethod
    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        """Open a stream over the object (optionally a ranged read).

        Raises KeyNotFoundException for missing keys and InvalidRangeException
        when the range start is beyond the object size. Like the reference,
        a range extending past the end returns the available suffix.
        """


class ObjectDeleter(abc.ABC):
    """Reference: storage/core/.../ObjectDeleter.java:21-38."""

    @abc.abstractmethod
    def delete(self, key: ObjectKey) -> None:
        """Delete one object; missing keys are not an error."""

    def delete_all(self, keys: Iterable[ObjectKey]) -> None:
        """Default multi-delete loops over `delete`; backends with a native
        bulk call (S3 DeleteObjects) override. Reference: ObjectDeleter.java:30-37."""
        for key in keys:
            self.delete(key)


class ObjectLister:
    """Key enumeration — the foundation of the integrity scrubber (scrub/).

    No reference counterpart: the reference never enumerates the store (it
    trusts uploads forever), which is exactly the gap the scrubber closes.
    """

    def list_objects(self, prefix: str = "") -> Iterable[ObjectKey]:
        """Yield every object key starting with `prefix`, in lexicographic
        order. An empty store (or unmatched prefix) yields nothing — never
        KeyNotFoundException. Cloud backends page internally (S3
        ListObjectsV2 continuation tokens, GCS pageToken, Azure marker), so
        iteration over millions of keys stays O(page) in memory."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support object listing"
        )


class StorageBackend(ObjectUploader, ObjectFetcher, ObjectDeleter, ObjectLister):
    """A configurable uploader+fetcher+deleter+lister.

    Reference: storage/core/.../StorageBackend.java:21 (Configurable +
    ObjectUploader + ObjectFetcher + ObjectDeleter); `list_objects` is this
    build's extension for the background scrubber.
    """

    def configure(self, configs: Mapping[str, object]) -> None:  # noqa: B027
        """Configure from the `storage.`-prefixed config subset."""


def load_backend_class(class_path: str) -> type:
    """Resolve a `module:Class` or dotted `module.Class` path to a class.

    The reflective analogue of the reference's `storage.backend.class`
    instantiation (core/.../config/RemoteStorageManagerConfig.java:315-320).
    """
    if ":" in class_path:
        module_name, _, class_name = class_path.partition(":")
    else:
        module_name, _, class_name = class_path.rpartition(".")
    if not module_name:
        raise ValueError(f"Invalid backend class path: {class_path!r}")
    module = importlib.import_module(module_name)
    try:
        cls = getattr(module, class_name)
    except AttributeError as e:
        raise ValueError(f"Class {class_name!r} not found in {module_name!r}") from e
    return cls
