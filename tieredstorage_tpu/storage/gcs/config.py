"""GCS backend configuration.

Reference: storage/gcs/.../GcsStorageConfig.java:34-135 — bucket/endpoint,
resumable upload chunk size, and the three mutually exclusive credential
sources (json / path / default; exactly one — CredentialsBuilder.java).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Mapping, Optional

from tieredstorage_tpu.config.configdef import (
    ConfigDef,
    ConfigException,
    ConfigKey,
    in_range,
    non_empty_string,
    null_or,
)

# Google's recommended minimum is 8 MiB; the client library default the
# reference inherits is 15 MiB (GcsStorageConfig.java:41-48).
DEFAULT_RESUMABLE_CHUNK_SIZE = 15 * 1024 * 1024
_CHUNK_QUANTUM = 256 * 1024  # resumable uploads require 256 KiB multiples


def _valid_chunk_size(name: str, value) -> None:
    in_range(min_value=_CHUNK_QUANTUM)(name, value)
    if value % _CHUNK_QUANTUM != 0:
        raise ConfigException(
            f"Invalid value {value} for configuration {name}: "
            f"must be a multiple of 256 KiB"
        )


def _definition() -> ConfigDef:
    d = ConfigDef()
    d.define(
        ConfigKey(
            "gcs.bucket.name",
            "string",
            validator=non_empty_string,
            importance="high",
            doc="GCS bucket to store log segments",
        )
    )
    d.define(
        ConfigKey(
            "gcs.endpoint.url",
            "string",
            default=None,
            importance="low",
            doc="Custom GCS endpoint URL. To be used with custom GCS-compatible backends "
            "(e.g. fake-gcs-server)",
        )
    )
    d.define(
        ConfigKey(
            "gcs.resumable.upload.chunk.size",
            "int",
            default=DEFAULT_RESUMABLE_CHUNK_SIZE,
            validator=null_or(_valid_chunk_size),
            importance="medium",
            doc="The chunk size in bytes used for resumable uploads. Larger chunk sizes "
            "mean better performance for bigger objects but more memory per upload; "
            "must be a multiple of 256 KiB, recommended minimum 8 MiB",
        )
    )
    d.define(
        ConfigKey(
            "gcs.credentials.json",
            "password",
            default=None,
            importance="medium",
            doc="GCP credentials as a JSON string. "
            'Cannot be set together with "gcs.credentials.path" or "gcs.credentials.default"',
        )
    )
    d.define(
        ConfigKey(
            "gcs.credentials.path",
            "string",
            default=None,
            importance="medium",
            doc="GCP credentials as a file path. "
            'Cannot be set together with "gcs.credentials.json" or "gcs.credentials.default"',
        )
    )
    d.define(
        ConfigKey(
            "gcs.credentials.default",
            "bool",
            default=None,
            importance="medium",
            doc="Use the default GCP credentials. "
            'Cannot be set together with "gcs.credentials.json" or "gcs.credentials.path"',
        )
    )
    return d


class GcsStorageConfig:
    DEFINITION = _definition()

    def __init__(self, props: Mapping[str, Any]):
        self._values = self.DEFINITION.parse(props)
        # Exactly-one-of validation (CredentialsBuilder.java: "all-null
        # means default", more than one non-null is an error).
        provided = [
            k
            for k in ("gcs.credentials.json", "gcs.credentials.path", "gcs.credentials.default")
            if self._values.get(k) is not None
        ]
        if len(provided) > 1:
            raise ConfigException(
                "Only one of gcs.credentials.json, gcs.credentials.path, "
                f"gcs.credentials.default can be provided, got {provided}"
            )

    @property
    def bucket_name(self) -> str:
        return self._values["gcs.bucket.name"]

    @property
    def endpoint_url(self) -> Optional[str]:
        return self._values.get("gcs.endpoint.url")

    @property
    def resumable_upload_chunk_size(self) -> int:
        return self._values["gcs.resumable.upload.chunk.size"]

    def credentials_json(self) -> Optional[dict]:
        """The parsed service-account JSON, or None for default credentials."""
        raw = self._values.get("gcs.credentials.json")
        if raw is not None:
            try:
                return json.loads(raw)
            except json.JSONDecodeError as e:
                raise ConfigException(f"gcs.credentials.json is not valid JSON: {e}") from e
        path = self._values.get("gcs.credentials.path")
        if path is not None:
            try:
                return json.loads(Path(path).read_text())
            except (OSError, json.JSONDecodeError) as e:
                raise ConfigException(f"Failed to read credentials from {path}: {e}") from e
        return None
