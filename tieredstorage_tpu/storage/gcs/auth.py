"""Service-account auth: self-signed RS256 JWTs as Bearer tokens.

The reference builds `GoogleCredentials` from json/path/default
(storage/gcs/.../CredentialsBuilder.java). Google Cloud Storage accepts
self-signed service-account JWTs directly as Bearer tokens (no OAuth
token-exchange round trip), which is what this module mints; default
credentials (emulators, workload identity with no key material) send no
Authorization header.
"""

from __future__ import annotations

import base64
import json
import time
from typing import Optional

try:  # Optional dependency: only service-account JWT signing needs it;
    # default credentials (emulators, workload identity) send no token.
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding
except ImportError:  # pragma: no cover - exercised only without cryptography
    hashes = serialization = padding = None


def _b64url(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


class ServiceAccountTokenProvider:
    """Mints and caches a self-signed JWT for the storage scope."""

    LIFETIME_S = 3600
    REFRESH_MARGIN_S = 300

    def __init__(self, credentials: dict):
        try:
            self.client_email = credentials["client_email"]
            key_pem = credentials["private_key"]
        except KeyError as e:
            raise ValueError(f"Service account JSON missing field: {e}") from e
        if serialization is None:
            raise ModuleNotFoundError(
                "The 'cryptography' package is required for GCS "
                "service-account credentials but is not installed"
            )
        self._key = serialization.load_pem_private_key(key_pem.encode(), password=None)
        self._token: Optional[str] = None
        self._expires_at = 0.0

    def token(self) -> str:
        # Expiry bookkeeping rides the monotonic clock (an NTP step must not
        # refresh early or, worse, serve a token past its real lifetime).
        if self._token is None or time.monotonic() >= self._expires_at - self.REFRESH_MARGIN_S:
            self._token = self._mint(time.time())
            self._expires_at = time.monotonic() + self.LIFETIME_S
        return self._token

    def _mint(self, now: float) -> str:
        # `now` is wall-clock epoch seconds by protocol: JWT iat/exp are
        # absolute times the server compares against ITS clock (the one
        # suppressed monotonic-clock finding, tools/analysis_suppressions.txt).
        header = _b64url(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
        claims = _b64url(
            json.dumps(
                {
                    "iss": self.client_email,
                    "sub": self.client_email,
                    "aud": "https://storage.googleapis.com/",
                    "iat": int(now),
                    "exp": int(now) + self.LIFETIME_S,
                    "scope": "https://www.googleapis.com/auth/devstorage.read_write",
                }
            ).encode()
        )
        signing_input = header + b"." + claims
        signature = self._key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
        return (signing_input + b"." + _b64url(signature)).decode()
