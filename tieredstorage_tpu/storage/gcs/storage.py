"""GCS StorageBackend over the JSON API.

Reference: storage/gcs/.../GcsStorage.java:41-160 — resumable upload with a
configurable chunk size (`storage.createFrom(blobInfo, stream, chunkSize)`),
fetch via blob metadata + ReadChannel seek/limit (here: a metadata GET for
the size check, then a ranged media download), 404 → KeyNotFoundException,
client-side range validation against the blob size.
"""

from __future__ import annotations

import time
from typing import BinaryIO, Mapping, Optional
from urllib.parse import quote, urlsplit

from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
    StorageBackendException,
    iter_chunks,
)
from tieredstorage_tpu.storage.gcs.auth import ServiceAccountTokenProvider
from tieredstorage_tpu.storage.gcs.config import GcsStorageConfig
from tieredstorage_tpu.storage.httpclient import HttpClient, HttpError
from tieredstorage_tpu.storage.proxy import ProxyConfig, socks5_socket_factory

_COPY_BUFFER = 1024 * 1024

#: Statuses a resumable chunk PUT recovers from by probing the committed
#: offset (mirrors the transport RetryPolicy statuses, but the recovery is
#: protocol-level — see _upload_session).
_RECOVERABLE_STATUSES = frozenset({429, 500, 502, 503, 504})
_MAX_CHUNK_RECOVERIES = 3


def _committed_bytes(range_header: str) -> int:
    """Bytes the server has persisted, from a 308's 'Range: bytes=0-N'.
    Per the resumable protocol, a 308 with no Range header means the server
    has persisted nothing."""
    import re

    m = re.fullmatch(r"bytes=0-(\d+)", range_header.strip()) if range_header else None
    return int(m.group(1)) + 1 if m else 0


class GcsStorage(StorageBackend):
    def __init__(self) -> None:
        self.http: Optional[HttpClient] = None
        self.bucket = ""
        self.chunk_size = 0
        self._token_provider: Optional[ServiceAccountTokenProvider] = None
        self._metric_collector = None

    def configure(self, configs: Mapping[str, object]) -> None:
        config = GcsStorageConfig(configs)
        proxy = ProxyConfig.from_configs(configs)
        endpoint = config.endpoint_url or "https://storage.googleapis.com"
        from tieredstorage_tpu.storage.gcs.metrics import GcsMetricCollector

        self._metric_collector = GcsMetricCollector()
        self.http = HttpClient(
            endpoint,
            socket_factory=socks5_socket_factory(proxy),
            observer=self._metric_collector.observe,
        )
        self.bucket = config.bucket_name
        self.chunk_size = config.resumable_upload_chunk_size
        credentials = config.credentials_json()
        self._token_provider = (
            ServiceAccountTokenProvider(credentials) if credentials is not None else None
        )

    # ------------------------------------------------------------- plumbing
    def _require_http(self) -> HttpClient:
        if self.http is None:
            raise StorageBackendException("GcsStorage is not configured")
        return self.http

    def _headers(self, extra: Optional[dict] = None) -> dict[str, str]:
        headers = {"Host": f"{self.http.host}:{self.http.port}"}
        if self._token_provider is not None:
            headers["Authorization"] = f"Bearer {self._token_provider.token()}"
        if extra:
            headers.update(extra)
        return headers

    def _object_path(self, key: ObjectKey, *, media: bool = False) -> str:
        # Object names are a single path element in the JSON API: '/' must be
        # percent-encoded (safe="" below).
        encoded = quote(key.value, safe="")
        base = f"{self.http.base_path}/storage/v1/b/{self.bucket}/o/{encoded}"
        return base + "?alt=media" if media else base

    # --------------------------------------------------------------- upload
    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        http = self._require_http()
        name = quote(key.value, safe="")
        try:
            resp = http.request(
                "POST",
                f"{http.base_path}/upload/storage/v1/b/{self.bucket}/o"
                f"?uploadType=resumable&name={name}",
                headers=self._headers({"Content-Type": "application/json"}),
                body=b"{}",
            )
            if resp.status != 200:
                raise StorageBackendException(
                    f"Failed to initiate resumable upload for {key}: HTTP {resp.status}"
                )
            location = resp.header("location")
            if not location:
                raise StorageBackendException(
                    f"No resumable session URI returned for {key}"
                )
            session = urlsplit(location)
            session_path = session.path + ("?" + session.query if session.query else "")
            return self._upload_session(http, session_path, input_stream, key)
        except HttpError as e:
            raise StorageBackendException(f"Failed to upload {key}") from e

    def _upload_session(
        self, http: HttpClient, session_path: str, input_stream: BinaryIO, key: ObjectKey
    ) -> int:
        # One-chunk lookahead so the last data chunk carries the known total
        # (a chunk sent with total '*' must NOT be the final one: an object
        # whose size is an exact chunk multiple must finalize with its last
        # data chunk or 'bytes */total', never an empty 'N-(N-1)' range).
        offset = 0
        chunks = iter_chunks(input_stream, self.chunk_size, read_size=_COPY_BUFFER)
        current = next(chunks, None)
        if current is None:
            # Empty object: finalize with a zero-length total.
            resp = http.request(
                "PUT",
                session_path,
                headers=self._headers({"Content-Range": "bytes */0"}),
            )
            if resp.status not in (200, 201):
                raise StorageBackendException(
                    f"Failed to finalize empty upload for {key}: HTTP {resp.status}"
                )
            return 0
        upcoming = next(chunks, None)
        stalls = 0
        recoveries = 0
        while current is not None:
            final = upcoming is None
            total = str(offset + len(current)) if final else "*"
            content_range = f"bytes {offset}-{offset + len(current) - 1}/{total}"
            # idempotent=False: a resumable chunk PUT is ORDER-STATEFUL — a
            # blind transport replay after the server committed the bytes
            # would collide with the advanced session offset. Recovery is
            # protocol-level instead: probe the committed offset
            # ('bytes */total', per the resumable spec) and resume from it,
            # which is what the reference's google-cloud-storage SDK does.
            try:
                resp = http.request(
                    "PUT",
                    session_path,
                    headers=self._headers({"Content-Range": content_range}),
                    body=current,
                    idempotent=False,
                )
                transport_error = None
            except HttpError as e:
                resp = None
                transport_error = e
            if resp is not None and final and resp.status in (200, 201):
                return offset + len(current)
            if resp is None or resp.status in _RECOVERABLE_STATUSES:
                recoveries += 1
                if recoveries > _MAX_CHUNK_RECOVERIES:
                    if transport_error is not None:
                        raise StorageBackendException(
                            f"Resumable upload for {key} failed"
                        ) from transport_error
                    raise StorageBackendException(
                        f"Resumable chunk for {key} not accepted after "
                        f"{recoveries} recoveries: HTTP {resp.status}"
                    )
                time.sleep(http.retry.backoff_s(recoveries - 1))
                resp = self._probe_session(http, session_path, total)
                if final and resp.status in (200, 201):
                    # The lost response had finalized the object.
                    return offset + len(current)
            if resp.status != 308:
                raise StorageBackendException(
                    f"Resumable {'finalize' if final else 'chunk'} for {key} "
                    f"not accepted: HTTP {resp.status}"
                )
            # A 308 (on any chunk, final included) may report fewer bytes
            # committed than sent; resume from the server's offset.
            committed = _committed_bytes(resp.header("range"))
            if committed < offset + len(current):
                if committed <= offset:
                    stalls += 1
                    if stalls > 2:
                        raise StorageBackendException(
                            f"Resumable upload for {key} made no progress "
                            f"(committed={committed}, offset={offset})"
                        )
                else:
                    stalls = 0
                    recoveries = 0  # forward progress, like the stall counter
                    current = current[committed - offset :]
                    offset = committed
                continue
            if final:
                raise StorageBackendException(
                    f"Upload for {key} fully committed but not finalized "
                    f"(HTTP 308 at committed={committed})"
                )
            stalls = 0
            recoveries = 0
            offset += len(current)
            current, upcoming = upcoming, next(chunks, None)
        raise AssertionError("unreachable: final chunk returns inside the loop")

    def _probe_session(self, http: HttpClient, session_path: str, total: str):
        """Query a resumable session's committed offset: an empty-body PUT
        with 'Content-Range: bytes */<total>' ('*' when unknown). Replay-safe
        by construction, so the transport may retry it."""
        return http.request(
            "PUT",
            session_path,
            headers=self._headers({"Content-Range": f"bytes */{total}"}),
            idempotent=True,
        )

    # ---------------------------------------------------------------- fetch
    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        http = self._require_http()
        extra: dict[str, str] = {}
        if byte_range is not None:
            # Out-of-range starts surface as 416 from the media GET below;
            # no separate metadata round trip on the hot ranged-fetch path.
            extra["Range"] = f"bytes={byte_range.from_position}-{byte_range.to_position}"
        try:
            status, headers, stream = http.request_stream(
                "GET", self._object_path(key, media=True), headers=self._headers(extra)
            )
        except HttpError as e:
            raise StorageBackendException(f"Failed to fetch {key}") from e
        if status in (200, 206):
            return stream
        body = stream.read()
        stream.close()
        if status == 404:
            raise KeyNotFoundException(self, key)
        if status == 416:
            raise InvalidRangeException(f"Failed to fetch {key}: Invalid range {byte_range}")
        raise StorageBackendException(f"Failed to fetch {key}: HTTP {status}: {body[:200]!r}")

    # ----------------------------------------------------------------- list
    def list_objects(self, prefix: str = ""):
        """JSON-API object listing (GET /o?prefix=...), paged via pageToken;
        GCS returns names in lexicographic order."""
        import json

        http = self._require_http()
        page_token: Optional[str] = None
        while True:
            query = f"?prefix={quote(prefix, safe='')}"
            if page_token:
                query += f"&pageToken={quote(page_token, safe='')}"
            try:
                resp = http.request(
                    "GET",
                    f"{http.base_path}/storage/v1/b/{self.bucket}/o{query}",
                    headers=self._headers(),
                )
            except HttpError as e:
                raise StorageBackendException(
                    f"Failed to list objects with prefix {prefix!r}"
                ) from e
            if resp.status != 200:
                raise StorageBackendException(
                    f"Failed to list objects with prefix {prefix!r}: HTTP {resp.status}"
                )
            doc = json.loads(resp.body)
            for item in doc.get("items", []):
                yield ObjectKey(str(item["name"]))
            page_token = doc.get("nextPageToken")
            if not page_token:
                return

    # --------------------------------------------------------------- delete
    def delete(self, key: ObjectKey) -> None:
        http = self._require_http()
        try:
            resp = http.request("DELETE", self._object_path(key), headers=self._headers())
        except HttpError as e:
            raise StorageBackendException(f"Failed to delete {key}") from e
        if resp.status not in (204, 200, 404):  # missing keys are not an error
            raise StorageBackendException(f"Failed to delete {key}: HTTP {resp.status}")

    @property
    def metrics(self):
        return self._metric_collector

    def close(self) -> None:
        if self.http is not None:
            self.http.close()

    def __str__(self) -> str:
        return f"GcsStorage{{bucket={self.bucket}}}"
