"""GCS backend request metrics.

Reference: storage/gcs/.../MetricCollector.java:66-83,146-160 wraps the HTTP
transport and classifies requests by URL regex into object-metadata /
object-download / object-upload (+ resumable-chunk detail). Same
classification here, applied as an HttpClient observer.
"""

from __future__ import annotations

from typing import Optional

from tieredstorage_tpu.metrics.core import (
    Avg,
    Max,
    MetricName,
    MetricsRegistry,
    Rate,
    Total,
)

GROUP = "gcs-client-metrics"
CONTEXT = "aiven.kafka.server.tieredstorage.gcs"


def _classify(method: str, path_and_query: str) -> Optional[str]:
    path = path_and_query.partition("?")[0]
    if path.startswith("/upload/storage/"):
        return "object-upload"
    if "alt=media" in path_and_query or path.startswith("/download/"):
        return "object-download"
    if "/storage/v1/b/" in path and "/o/" in path:
        if method == "GET":
            return "object-metadata-get"
        if method == "DELETE":
            return "object-delete"
    return None


class GcsMetricCollector:
    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()

    def observe(
        self,
        method: str,
        path_and_query: str,
        status: int,
        elapsed_s: float,
        error: Optional[BaseException],
    ) -> None:
        op = _classify(method, path_and_query)
        if op is None:
            return
        requests = self.registry.sensor(f"{op}-requests")
        requests.ensure_stats(
            lambda: [
                (MetricName.of(f"{op}-requests-rate", GROUP), Rate()),
                (MetricName.of(f"{op}-requests-total", GROUP), Total()),
            ]
        )
        requests.record(1.0)
        timing = self.registry.sensor(f"{op}-time")
        timing.ensure_stats(
            lambda: [
                (MetricName.of(f"{op}-time-avg", GROUP), Avg()),
                (MetricName.of(f"{op}-time-max", GROUP), Max()),
            ]
        )
        timing.record(elapsed_s * 1000.0)
