"""GCS backend request metrics.

Reference: storage/gcs/.../MetricCollector.java:66-83,146-160 wraps the HTTP
transport and classifies requests by URL regex into object-metadata /
object-download / object-upload. Same classification here, with sensor
shapes from the shared RequestMetricCollector.
"""

from __future__ import annotations

from typing import Optional

from tieredstorage_tpu.storage.request_metrics import RequestMetricCollector

GROUP = "gcs-client-metrics"
CONTEXT = "aiven.kafka.server.tieredstorage.gcs"


def _classify(method: str, path_and_query: str) -> Optional[str]:
    path = path_and_query.partition("?")[0]
    if "/upload/storage/" in path:
        return "object-upload"
    if "alt=media" in path_and_query or "/download/" in path:
        return "object-download"
    if "/storage/v1/b/" in path and "/o/" in path:
        if method == "GET":
            return "object-metadata-get"
        if method == "DELETE":
            return "object-delete"
    return None


class GcsMetricCollector(RequestMetricCollector):
    def __init__(self, registry=None):
        super().__init__(GROUP, _classify, registry)
