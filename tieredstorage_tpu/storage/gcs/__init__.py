"""GCS storage backend (JSON API over stdlib HTTP, no SDK).

Reference module: storage/gcs (GcsStorage.java, GcsStorageConfig.java,
CredentialsBuilder.java, MetricCollector.java).
"""

from tieredstorage_tpu.storage.gcs.config import GcsStorageConfig
from tieredstorage_tpu.storage.gcs.storage import GcsStorage

__all__ = ["GcsStorage", "GcsStorageConfig"]
