"""In-memory storage backend for tests and as a fast local fake.

The analogue of the reference test tree's fake backends
(reference: core/src/test/java/.../config/NoopStorageBackend.java:30-60 is a
no-op used for config plumbing; this one actually stores bytes so the full
contract suite and the RSM lifecycle tests can run in-process).
"""

from __future__ import annotations

import io
from typing import BinaryIO, Dict, Mapping, Optional

from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectKey,
    StorageBackend,
)
from tieredstorage_tpu.utils.locks import new_lock


class InMemoryStorage(StorageBackend):
    def __init__(self) -> None:
        self._objects: Dict[str, bytes] = {}
        self._lock = new_lock("memory.InMemoryStorage._lock")

    def configure(self, configs: Mapping[str, object]) -> None:
        pass

    def upload(self, input_stream: BinaryIO, key: ObjectKey) -> int:
        data = input_stream.read()
        with self._lock:
            self._objects[key.value] = data
        return len(data)

    def fetch(self, key: ObjectKey, byte_range: Optional[BytesRange] = None) -> BinaryIO:
        with self._lock:
            data = self._objects.get(key.value)
        if data is None:
            raise KeyNotFoundException(self, key)
        if byte_range is None:
            return io.BytesIO(data)
        if byte_range.from_position >= len(data):
            raise InvalidRangeException(
                f"Range start position {byte_range.from_position} is outside object, "
                f"size = {len(data)}, range = {byte_range}"
            )
        return io.BytesIO(data[byte_range.from_position : byte_range.to_position + 1])

    def delete(self, key: ObjectKey) -> None:
        with self._lock:
            self._objects.pop(key.value, None)

    def list_objects(self, prefix: str = ""):
        with self._lock:
            matched = sorted(k for k in self._objects if k.startswith(prefix))
        for k in matched:
            yield ObjectKey(k)

    # --- test helpers ---
    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._objects)

    def object(self, key: str) -> bytes:
        with self._lock:
            return self._objects[key]

    def __str__(self) -> str:
        return "InMemoryStorage"
