"""Storage backend SPI and concrete backends (reference L6/L6a).

Reference: storage/core/src/main/java/io/aiven/kafka/tieredstorage/storage/.
"""

from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectDeleter,
    ObjectFetcher,
    ObjectKey,
    ObjectUploader,
    StorageBackend,
    StorageBackendException,
)
from tieredstorage_tpu.storage.replicated import (
    AllReplicasFailedException,
    QuorumWriteException,
    ReplicatedStorageBackend,
)

__all__ = [
    "AllReplicasFailedException",
    "BytesRange",
    "InvalidRangeException",
    "KeyNotFoundException",
    "ObjectDeleter",
    "ObjectFetcher",
    "ObjectKey",
    "ObjectUploader",
    "QuorumWriteException",
    "ReplicatedStorageBackend",
    "StorageBackend",
    "StorageBackendException",
]
