"""Storage backend SPI and concrete backends (reference L6/L6a).

Reference: storage/core/src/main/java/io/aiven/kafka/tieredstorage/storage/.
"""

from tieredstorage_tpu.storage.core import (
    BytesRange,
    InvalidRangeException,
    KeyNotFoundException,
    ObjectDeleter,
    ObjectFetcher,
    ObjectKey,
    ObjectUploader,
    StorageBackend,
    StorageBackendException,
)
# The replicated backend re-exports are LAZY (PEP 562): replicated.py
# imports utils/deadline.py, which imports storage.core — an eager import
# here made `tieredstorage_tpu.utils.deadline` (and everything that loads
# it first, e.g. utils/flightrecorder.py) unimportable as the process's
# first project import. Deferring breaks the cycle without changing the
# public surface.
_REPLICATED_EXPORTS = (
    "AllReplicasFailedException",
    "QuorumWriteException",
    "ReplicatedStorageBackend",
)


def __getattr__(name: str):
    if name in _REPLICATED_EXPORTS:
        from tieredstorage_tpu.storage import replicated

        return getattr(replicated, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "AllReplicasFailedException",
    "BytesRange",
    "InvalidRangeException",
    "KeyNotFoundException",
    "ObjectDeleter",
    "ObjectFetcher",
    "ObjectKey",
    "ObjectUploader",
    "QuorumWriteException",
    "ReplicatedStorageBackend",
    "StorageBackend",
    "StorageBackendException",
]
