"""Stream helpers: bounded reads, chunked copy, lazy concatenation.

Host-side equivalents of the reference's commons-io BoundedInputStream usage
(core/.../fetch/FetchChunkEnumeration.java:100-131) and SequenceInputStream
composition (core/.../transform/DetransformFinisher.java:48-53).
"""

from __future__ import annotations

import io
from typing import BinaryIO, Callable, Iterator, Optional

_COPY_BUF = 1024 * 1024


class BoundedStream(io.RawIOBase):
    """Caps reads from an inner stream at `limit` bytes; closes inner on close."""

    def __init__(self, inner: BinaryIO, limit: int):
        self._inner = inner
        self._remaining = max(0, limit)

    def readable(self) -> bool:
        return True

    def read(self, size: int = -1) -> bytes:
        if self._remaining <= 0:
            return b""
        if size is None or size < 0 or size > self._remaining:
            size = self._remaining
        data = self._inner.read(size)
        self._remaining -= len(data)
        return data

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def close(self) -> None:
        try:
            self._inner.close()
        finally:
            super().close()


class LazyConcatStream(io.RawIOBase):
    """Concatenates streams produced on demand by an iterator of factories.

    The analogue of the reference's LazySequenceInputStream
    (core/.../fetch/FetchChunkEnumeration.java:160-175): the iterator is only
    advanced when more bytes are requested, and closing the stream early stops
    the iteration (the broker rarely drains a whole fetch).
    """

    def __init__(self, parts: Iterator[BinaryIO]):
        self._parts = parts
        self._current: Optional[BinaryIO] = None

    def readable(self) -> bool:
        return True

    def read(self, size: int = -1) -> bytes:
        if size == 0:
            return b""
        out = bytearray()
        while size < 0 or len(out) < size:
            if self._current is None:
                try:
                    self._current = next(self._parts)
                except StopIteration:
                    break
            want = -1 if size < 0 else size - len(out)
            data = self._current.read(want)
            if not data:
                self._current.close()
                self._current = None
                continue
            out += data
        return bytes(out)

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def close(self) -> None:
        try:
            if self._current is not None:
                self._current.close()
                self._current = None
            close_all = getattr(self._parts, "close", None)
            if close_all is not None:
                close_all()
        finally:
            super().close()


def copy_stream(src: BinaryIO, dst: BinaryIO, buf_size: int = _COPY_BUF) -> int:
    total = 0
    while True:
        data = src.read(buf_size)
        if not data:
            break
        dst.write(data)
        total += len(data)
    return total


def read_exactly(stream: BinaryIO, n: int) -> bytes:
    """Read exactly n bytes or raise EOFError (reference:
    BaseDetransformChunkEnumeration.fillChunkIfNeeded errors on short streams,
    core/.../transform/BaseDetransformChunkEnumeration.java:78-113)."""
    out = bytearray()
    while len(out) < n:
        data = stream.read(n - len(out))
        if not data:
            raise EOFError(f"Stream has fewer than expected bytes: wanted {n}, got {len(out)}")
        out += data
    return bytes(out)


class ClosableStreamHolder:
    """Collects opened streams and best-effort closes them all.

    Reference: core/.../ClosableInputStreamHolder.java:28-48 (prevents fd
    leaks during multi-stream index upload).
    """

    def __init__(self) -> None:
        self._streams: list[BinaryIO] = []

    def add(self, stream: BinaryIO) -> BinaryIO:
        self._streams.append(stream)
        return stream

    def __enter__(self) -> "ClosableStreamHolder":
        return self

    def __exit__(self, *exc) -> None:
        for s in self._streams:
            try:
                s.close()
            except Exception:
                pass
