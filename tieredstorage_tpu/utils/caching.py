"""Single-flight async loading cache — the Caffeine-equivalent primitive.

The reference leans on Caffeine `AsyncCache`s for all three fetch-side caches
(chunks: core/.../fetch/cache/ChunkCache.java:76-157; segment indexes:
fetch/index/MemorySegmentIndexesCache.java:93-120; manifests:
fetch/manifest/MemorySegmentManifestCache.java:67-117). This module provides
the same semantics natively:

- single-flight population: concurrent `get`s of one key share one load
  (Caffeine's `asMap().compute` atomicity, ChunkCache.java:85-112);
- weigher + maximum total weight with LRU eviction;
- expire-after-access retention;
- removal listener with the eviction cause (SIZE / EXPIRED / EXPLICIT /
  REPLACED) — the disk cache deletes files from it;
- a stats counter (hits/misses/load success+failure/evictions by cause)
  mirroring Caffeine's `StatsCounter` so the metrics layer can export the
  same families (core/.../metrics/CaffeineStatsCounter.java).

Loads run on a caller-supplied executor; `get` blocks up to `timeout`
(ChunkCache `get.timeout.ms`, config/CacheConfig.java:120-138).
"""

from __future__ import annotations

import enum
import time
from collections import OrderedDict
from concurrent.futures import Executor, Future
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, Hashable, Optional, TypeVar
from tieredstorage_tpu.utils.locks import new_lock, note_mutation

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class RemovalCause(enum.Enum):
    EXPLICIT = "explicit"
    REPLACED = "replaced"
    SIZE = "size"
    EXPIRED = "expired"


@dataclass
class CacheStats:
    """Mutable counter set in the shape of Caffeine's StatsCounter."""

    hits: int = 0
    misses: int = 0
    load_successes: int = 0
    load_failures: int = 0
    total_load_time_ns: int = 0
    evictions: dict[RemovalCause, int] = field(
        default_factory=lambda: {c: 0 for c in RemovalCause}
    )
    eviction_weight: int = 0
    #: Removal-listener callbacks that raised (must not poison the cache,
    #: but must not vanish either — swallowed-exception checker).
    listener_failures: int = 0


class _Entry(Generic[V]):
    __slots__ = ("future", "weight", "last_access")

    def __init__(self, future: "Future[V]", now: float) -> None:
        self.future = future
        self.weight = 0
        self.last_access = now


class LoadingCache(Generic[K, V]):
    def __init__(
        self,
        *,
        executor: Executor,
        max_weight: Optional[int] = None,
        weigher: Callable[[V], int] = lambda v: 1,
        expire_after_access_s: Optional[float] = None,
        removal_listener: Optional[Callable[[K, V, RemovalCause], None]] = None,
        time_source: Callable[[], float] = time.monotonic,
    ) -> None:
        if max_weight is not None and max_weight < 0:
            max_weight = None  # -1 ⇒ unbounded (CacheConfig.java `size`)
        self._executor = executor
        self._max_weight = max_weight
        self._weigher = weigher
        self._expire = expire_after_access_s
        self._listener = removal_listener
        self._now = time_source
        self._lock = new_lock("caching.LoadingCache._lock")
        # Ordered oldest-access-first for LRU eviction.
        self._entries: "OrderedDict[K, _Entry[V]]" = OrderedDict()
        self._total_weight = 0
        self.stats = CacheStats()

    # ------------------------------------------------------------------ reads
    def get(
        self, key: K, loader: Callable[[], V], timeout: Optional[float] = None
    ) -> V:
        """Return the cached value, loading it at most once across threads."""
        return self.get_future(key, loader).result(timeout)

    def get_future(self, key: K, loader: Callable[[], V]) -> "Future[V]":
        load: Optional[tuple] = None
        with self._lock:
            expired = self._expire_stale_locked()
            entry = self._entries.get(key)
            if entry is not None:
                entry.last_access = self._now()
                self._entries.move_to_end(key)
                self.stats.hits += 1
                future = entry.future
            else:
                self.stats.misses += 1
                future = Future()
                self._entries[key] = _Entry(future, self._now())
                # Dispatch AFTER release: Executor.submit synchronizes on the
                # pool's own queue lock, and an inline executor (tests) would
                # run the whole load under _lock. Concurrent getters of the
                # key already share this future, so only the creator submits.
                load = (key, loader, future)
        self._dispatch_expired(expired)
        if load is not None:
            self._executor.submit(self._load, *load)
        return future

    def get_if_present(self, key: K) -> Optional["Future[V]"]:
        with self._lock:
            expired = self._expire_stale_locked()
            entry = self._entries.get(key)
            if entry is None:
                self.stats.misses += 1
                future = None
            else:
                entry.last_access = self._now()
                self._entries.move_to_end(key)
                self.stats.hits += 1
                future = entry.future
        self._dispatch_expired(expired)
        return future

    def peek(self, key: K) -> Optional["Future[V]"]:
        """Presence probe that records NO stats and does not refresh recency —
        for internal prefetch/window planning, so exported hit rates reflect
        only real accesses."""
        with self._lock:
            expired = self._expire_stale_locked()
            entry = self._entries.get(key)
            future = None if entry is None else entry.future
        self._dispatch_expired(expired)
        return future

    # ----------------------------------------------------------------- writes
    def _load(self, key: K, loader: Callable[[], V], future: "Future[V]") -> None:
        start = time.monotonic_ns()
        try:
            value = loader()
        except BaseException as e:  # noqa: BLE001 — failure recorded, then surfaced
            with self._lock:
                self.stats.load_failures += 1
                self.stats.total_load_time_ns += time.monotonic_ns() - start
                entry = self._entries.get(key)
                if entry is not None and entry.future is future:
                    del self._entries[key]
            future.set_exception(e)
            return
        evicted: list[tuple[K, V, RemovalCause]] = []
        orphaned = False
        with self._lock:
            self.stats.load_successes += 1
            self.stats.total_load_time_ns += time.monotonic_ns() - start
            entry = self._entries.get(key)
            if entry is not None and entry.future is future:
                entry.weight = self._weigher(value)
                self._total_weight += entry.weight
                evicted = self._evict_over_weight_locked(keep=key)
            else:
                # The entry was invalidated while loading: the value was never
                # accounted, so clean it up (disk caches unlink the file here).
                orphaned = True
        future.set_result(value)
        self._notify(evicted)
        if orphaned:
            self._notify([(key, value, RemovalCause.EXPLICIT)])

    def invalidate(self, key: K) -> None:
        self._remove(key, RemovalCause.EXPLICIT)

    def invalidate_all(self) -> None:
        for key in list(self._entries):
            self._remove(key, RemovalCause.EXPLICIT)

    def _remove(self, key: K, cause: RemovalCause) -> None:
        removed = None
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._total_weight -= entry.weight
                removed = entry
                self.stats.evictions[cause] += 1
                self.stats.eviction_weight += entry.weight
        if removed is not None:
            self._notify([(key, removed.future, cause)])

    # --------------------------------------------------------------- internal
    def _evict_over_weight_locked(self, keep: K) -> list[tuple[K, Any, RemovalCause]]:
        if self._max_weight is None or self._total_weight <= self._max_weight:
            return []  # under weight: skip the O(n) key-list materialization
        evicted: list[tuple[K, Any, RemovalCause]] = []
        for key in list(self._entries):
            if self._total_weight <= self._max_weight:
                break
            if key == keep:
                continue
            entry = self._entries[key]
            if not entry.future.done():
                continue  # weight of in-flight loads is 0; nothing to reclaim
            del self._entries[key]
            self._total_weight -= entry.weight
            self.stats.evictions[RemovalCause.SIZE] += 1
            self.stats.eviction_weight += entry.weight
            evicted.append((key, entry.future, RemovalCause.SIZE))
        return evicted

    def _expire_stale_locked(self) -> list[tuple[K, Any, RemovalCause]]:
        """Drop expired entries; returns them for the CALLER to hand to
        `_dispatch_expired` after releasing `_lock` (Executor.submit takes
        the pool's queue lock — nothing blocking may run under `_lock`,
        lock-order checker)."""
        if self._expire is None:
            return []
        deadline = self._now() - self._expire
        # `_entries` is recency-ordered (insertion stamps `last_access`,
        # every read refreshes it via move_to_end, and nothing else mutates
        # the stamp), so `last_access` is nondecreasing along the dict:
        # stop at the first fresh entry instead of scanning the whole
        # table. Without the early break this scan is O(entries) on EVERY
        # get — under a cold sequential replay that pre-admits tens of
        # thousands of chunks (fetch/readahead.py) it was the dominant
        # per-read cost, serialized under `_lock`. In-flight loads (future
        # not done) are skipped, not expired, exactly as before.
        stale = []
        for key, entry in self._entries.items():
            if entry.last_access >= deadline:
                break
            if entry.future.done():
                stale.append(key)
        expired = []
        for key in stale:
            entry = self._entries.pop(key)
            self._total_weight -= entry.weight
            self.stats.evictions[RemovalCause.EXPIRED] += 1
            self.stats.eviction_weight += entry.weight
            expired.append((key, entry.future, RemovalCause.EXPIRED))
        return expired

    def _dispatch_expired(self, expired: list) -> None:
        """Enqueue expiry notifications (outside `_lock`; listeners run on
        pool threads as before)."""
        if expired:
            self._executor.submit(self._notify, expired)

    def _notify(self, removed: list) -> None:
        if self._listener is None:
            return
        for key, future_or_value, cause in removed:
            value = future_or_value
            if isinstance(future_or_value, Future):
                if not future_or_value.done() or future_or_value.exception() is not None:
                    continue
                value = future_or_value.result()
            try:
                self._listener(key, value, cause)
            except Exception:  # noqa: BLE001 — listener failures must not poison the cache
                with self._lock:
                    self.stats.listener_failures += 1
                    note_mutation("caching.LoadingCache.stats")

    # ------------------------------------------------------------- inspection
    @property
    def total_weight(self) -> int:
        with self._lock:
            return self._total_weight

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
