"""Operation tracing: spans around RSM operations and kernel launches.

The reference has no tracing (SURVEY §5 — only SLF4J boundary logs,
RemoteStorageManager.java:218,549,598); this build adds a real span system:
lightweight nested spans with wall-time accounting, optional forwarding into
jax.profiler traces (so spans show up in XProf/TensorBoard timelines next to
the device kernels they launched), and an in-memory recorder for tests and
the demo.

Usage:
    tracer = Tracer(enabled=True)
    with tracer.span("copy_log_segment_data", topic="t", partition=3):
        with tracer.span("transform"):
            ...
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Iterator, Optional


@dataclasses.dataclass
class Span:
    name: str
    start_s: float
    end_s: float = 0.0
    depth: int = 0
    attributes: dict = dataclasses.field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return max(0.0, self.end_s - self.start_s)


class Tracer:
    """Nested span recorder; thread-safe, cheap when disabled."""

    def __init__(self, enabled: bool = False, *, use_jax_profiler: bool = False,
                 max_spans: int = 10_000):
        self.enabled = enabled
        self.use_jax_profiler = use_jax_profiler
        self.max_spans = max_spans
        self._spans: list[Span] = []
        self._lock = threading.Lock()
        self._local = threading.local()

    @contextlib.contextmanager
    def span(self, name: str, **attributes) -> Iterator[Optional[Span]]:
        if not self.enabled:
            yield None
            return
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        s = Span(name=name, start_s=time.perf_counter(), depth=depth,
                 attributes=attributes)
        ctx = None
        if self.use_jax_profiler:
            try:
                import jax.profiler

                ctx = jax.profiler.TraceAnnotation(name)
                ctx.__enter__()
            except Exception:
                ctx = None
        try:
            yield s
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
            s.end_s = time.perf_counter()
            self._local.depth = depth
            with self._lock:
                if len(self._spans) < self.max_spans:
                    self._spans.append(s)

    def event(self, name: str, **attributes) -> Optional[Span]:
        """Record an instantaneous (zero-duration) span — state transitions
        like circuit-breaker trips or upload rollbacks that have no useful
        extent but must show up on the timeline."""
        if not self.enabled:
            return None
        now = time.perf_counter()
        s = Span(name=name, start_s=now, end_s=now,
                 depth=getattr(self._local, "depth", 0), attributes=attributes)
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(s)
        return s

    def spans(self, name: Optional[str] = None) -> list[Span]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def summary(self) -> dict[str, dict[str, float]]:
        """Per-name count/total/avg/max durations (seconds)."""
        agg: dict[str, list[float]] = {}
        for s in self.spans():
            agg.setdefault(s.name, []).append(s.duration_s)
        return {
            name: {
                "count": len(ds),
                "total_s": sum(ds),
                "avg_s": sum(ds) / len(ds),
                "max_s": max(ds),
            }
            for name, ds in agg.items()
        }


#: Process-wide default tracer; RSM wires it from `tracing.enabled` config.
NOOP_TRACER = Tracer(enabled=False)
