"""Distributed tracing: Dapper-style spans across the RSM, fetch, and sidecar tiers.

The reference has no tracing (SURVEY §5 — only SLF4J boundary logs,
RemoteStorageManager.java:218,549,598); this build adds a real span system:

- nested spans with wall-time accounting and `trace_id`/`span_id`/`parent_id`
  identity, propagated through a thread-local context stack;
- W3C ``traceparent`` propagation (`current_traceparent` / `continue_trace`)
  so one request shows up as a single tree spanning
  client → sidecar gateway → RSM → storage backend;
- optional forwarding into jax.profiler traces (so spans show up in
  XProf/TensorBoard timelines next to the device kernels they launched);
- a bounded ring-buffer recorder (newest spans win; evictions are counted in
  `dropped_spans`) with per-name p50/p95/p99 summaries and a Chrome
  trace-event JSON exporter (loadable in Perfetto / ``chrome://tracing``,
  interleavable with `jax.profiler` device timelines).

Usage:
    tracer = Tracer(enabled=True)
    with tracer.span("copy_log_segment_data", topic="t", partition=3):
        with tracer.span("transform"):
            ...
    tracer.write_chrome_trace("artifacts/trace.json")
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import math
import os
import pathlib
import threading
import time
from typing import Iterator, Optional
from tieredstorage_tpu.utils.locks import new_lock

#: Header/metadata key carrying W3C trace context across process boundaries.
TRACEPARENT_HEADER = "traceparent"

_TRACEPARENT_VERSION = "00"
_HEX = set("0123456789abcdef")


def _gen_trace_id() -> str:
    return os.urandom(16).hex()


def _gen_span_id() -> str:
    return os.urandom(8).hex()


def format_traceparent(trace_id: str, span_id: str) -> str:
    """W3C trace-context header value (always sampled: this tracer records
    everything it is enabled for)."""
    return f"{_TRACEPARENT_VERSION}-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[tuple[str, str]]:
    """(trace_id, parent_span_id) from a ``traceparent`` value, or None.

    Lenient per the W3C spec: unknown versions are accepted as long as the
    00-version prefix fields parse; malformed values are ignored (tracing
    must never fail a request)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or not set(version) <= _HEX or version == "ff":
        return None
    if len(trace_id) != 32 or not set(trace_id) <= _HEX or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not set(span_id) <= _HEX or span_id == "0" * 16:
        return None
    return trace_id, span_id


@dataclasses.dataclass
class Span:
    name: str
    start_s: float
    end_s: float = 0.0
    depth: int = 0
    attributes: dict = dataclasses.field(default_factory=dict)
    trace_id: str = ""
    span_id: str = ""
    parent_id: Optional[str] = None
    thread_id: int = 0

    @property
    def duration_s(self) -> float:
        return max(0.0, self.end_s - self.start_s)


def _percentile(sorted_durations: list[float], q: float) -> float:
    """Nearest-rank percentile over an ascending-sorted NON-EMPTY list.

    Part of the degenerate-case contract (ISSUE 14): an empty sample set
    has NO percentile — callers must not see a fabricated 0.0 — so the
    empty list is a programming error here (``summary()`` never builds an
    entry without at least one span). A single sample is every percentile
    of itself (nearest rank: rank 1 of 1)."""
    if not sorted_durations:
        raise ValueError("percentile of an empty sample set is undefined")
    rank = max(1, math.ceil(q * len(sorted_durations)))
    return sorted_durations[min(rank, len(sorted_durations)) - 1]


class Tracer:
    """Nested span recorder; thread-safe, cheap when disabled.

    Spans recorded while another span is active on the same thread (or while
    a remote context installed by `continue_trace` is active) are parented
    under it and share its `trace_id`; otherwise a span starts a new trace.
    The recorder is a ring buffer: once `max_spans` is reached the OLDEST
    span is evicted (and counted in `dropped_spans`), so long soak runs keep
    the newest spans instead of silently freezing the recorder."""

    def __init__(self, enabled: bool = False, *, use_jax_profiler: bool = False,
                 max_spans: int = 10_000):
        self.enabled = enabled
        self.use_jax_profiler = use_jax_profiler
        self.max_spans = max_spans
        self._spans: collections.deque[Span] = collections.deque(maxlen=max_spans)
        #: Spans evicted from the ring buffer (exported as a counter metric).
        self.dropped_spans = 0
        self._lock = new_lock("tracing.Tracer._lock")
        self._local = threading.local()
        # Pinned once so Chrome-trace timestamps from several tracers in one
        # process (client + sidecar in tests/demos) land on one shared
        # timeline. Monotonic, not wall clock: Perfetto only needs a
        # consistent epoch, and an NTP step mid-run would skew span starts
        # against their perf_counter-measured durations.
        self._epoch_perf = time.perf_counter()
        self._epoch_mono = time.monotonic()

    # ---------------------------------------------------------------- context
    def _stack(self) -> list[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _parent_context(self) -> tuple[str, Optional[str]]:
        """(trace_id, parent_span_id) for a new span on this thread."""
        stack = self._stack()
        if stack:
            return stack[-1].trace_id, stack[-1].span_id
        remote = getattr(self._local, "remote", None)
        if remote is not None:
            return remote
        return _gen_trace_id(), None

    def current_traceparent(self) -> Optional[str]:
        """``traceparent`` value for the active context, for injection into
        outgoing HTTP headers / gRPC metadata; None when there is nothing to
        propagate (tracing disabled or no active span)."""
        if not self.enabled:
            return None
        stack = self._stack()
        if stack:
            return format_traceparent(stack[-1].trace_id, stack[-1].span_id)
        remote = getattr(self._local, "remote", None)
        if remote is not None:
            return format_traceparent(remote[0], remote[1])
        return None

    @contextlib.contextmanager
    def continue_trace(self, traceparent: Optional[str]) -> Iterator[None]:
        """Adopt a remote parent context for the duration of the block: spans
        opened inside join the caller's trace instead of starting a new one.
        Malformed/absent headers degrade to a no-op (new root trace)."""
        parsed = parse_traceparent(traceparent) if self.enabled else None
        if parsed is None:
            yield
            return
        prior = getattr(self._local, "remote", None)
        self._local.remote = parsed
        try:
            yield
        finally:
            self._local.remote = prior

    # ---------------------------------------------------------------- record
    def _record(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped_spans += 1
            self._spans.append(span)

    @contextlib.contextmanager
    def span(self, name: str, **attributes) -> Iterator[Optional[Span]]:
        if not self.enabled:
            yield None
            return
        stack = self._stack()
        trace_id, parent_id = self._parent_context()
        s = Span(
            name=name, start_s=time.perf_counter(), depth=len(stack),
            attributes=attributes, trace_id=trace_id, span_id=_gen_span_id(),
            parent_id=parent_id, thread_id=threading.get_ident(),
        )
        stack.append(s)
        ctx = None
        if self.use_jax_profiler:
            try:
                import jax.profiler

                ctx = jax.profiler.TraceAnnotation(name)
                ctx.__enter__()
            except Exception:
                ctx = None
        try:
            yield s
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
            s.end_s = time.perf_counter()
            stack.pop()
            self._record(s)

    def event(self, name: str, **attributes) -> Optional[Span]:
        """Record an instantaneous (zero-duration) span — state transitions
        like circuit-breaker trips or upload rollbacks that have no useful
        extent but must show up on the timeline."""
        if not self.enabled:
            return None
        now = time.perf_counter()
        trace_id, parent_id = self._parent_context()
        s = Span(
            name=name, start_s=now, end_s=now, depth=len(self._stack()),
            attributes=attributes, trace_id=trace_id, span_id=_gen_span_id(),
            parent_id=parent_id, thread_id=threading.get_ident(),
        )
        if self.use_jax_profiler:
            # Zero-duration annotation: timeline parity with span() so events
            # land in XProf next to the kernels they interleave with.
            try:
                import jax.profiler

                with jax.profiler.TraceAnnotation(name):
                    pass
            except Exception:
                pass
        self._record(s)
        return s

    # --------------------------------------------------------------- readers
    def spans(self, name: Optional[str] = None) -> list[Span]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    @property
    def recorded_spans(self) -> int:
        with self._lock:
            return len(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped_spans = 0

    def summary(self) -> dict[str, dict[str, float]]:
        """Per-name count/total/avg/max plus p50/p95/p99 durations (seconds).

        Degenerate-case contract (ISSUE 14): no recorded spans means an
        EMPTY dict — a name never appears with fabricated zero percentiles,
        so consumers (``/varz``, the SLO engine's evidence path) can treat
        "absent" as "no data" without a sentinel check. A name with exactly
        one span reports that span's duration as count=1, avg, max, and
        every percentile (nearest-rank: one sample is every quantile of
        itself)."""
        agg: dict[str, list[float]] = {}
        for s in self.spans():
            agg.setdefault(s.name, []).append(s.duration_s)
        out: dict[str, dict[str, float]] = {}
        for name, ds in agg.items():
            ds.sort()
            out[name] = {
                "count": len(ds),
                "total_s": sum(ds),
                "avg_s": sum(ds) / len(ds),
                "max_s": ds[-1],
                "p50_s": _percentile(ds, 0.50),
                "p95_s": _percentile(ds, 0.95),
                "p99_s": _percentile(ds, 0.99),
            }
        return out

    # ---------------------------------------------------------------- export
    def _ts_us(self, perf_s: float) -> float:
        return (self._epoch_mono + (perf_s - self._epoch_perf)) * 1e6

    def chrome_trace_events(self) -> list[dict]:
        """Spans as Chrome trace-event dicts: complete events (``ph: "X"``)
        for timed spans, instant events (``ph: "i"``) for zero-duration
        events; `args` carries the span identity so trees survive the export."""
        events: list[dict] = []
        pid = os.getpid()
        for s in self.spans():
            args = {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                **{k: str(v) for k, v in s.attributes.items()},
            }
            base = {
                "name": s.name,
                "cat": "tieredstorage",
                "ts": self._ts_us(s.start_s),
                "pid": pid,
                "tid": s.thread_id,
                "args": args,
            }
            if s.duration_s > 0.0:
                events.append({**base, "ph": "X", "dur": s.duration_s * 1e6})
            else:
                events.append({**base, "ph": "i", "s": "t"})
        return events

    def export_chrome_trace(self) -> dict:
        """JSON-object-format Chrome trace (Perfetto / ``chrome://tracing``)."""
        return {
            "traceEvents": self.chrome_trace_events(),
            "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": self.dropped_spans},
        }

    def write_chrome_trace(self, path) -> pathlib.Path:
        out = pathlib.Path(path)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(self.export_chrome_trace(), indent=1))
        return out


#: Process-wide default tracer; RSM wires it from `tracing.enabled` config.
NOOP_TRACER = Tracer(enabled=False)
