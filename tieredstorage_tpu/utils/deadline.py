"""End-to-end request deadlines: one absolute budget for the whole call tree.

The reference has per-call socket timeouts (`api.call.attempt.timeout`) and a
per-request total (`api.call.timeout`), but nothing that spans layers: a
broker fetch that has already burned its patience in the chunk cache still
gets a full fresh timeout at the storage transport, so the slowest requests
are exactly the ones that hold resources the longest. Dean & Barroso ("The
Tail at Scale", CACM 2013) call the cure cross-layer deadlines: the entry
point fixes an absolute budget, every layer below clamps its own waiting to
what is left, and an expired budget fails *before* touching the network.

Mechanics mirror the tracing context (utils/tracing.py):

- a ``Deadline`` is an absolute point on the monotonic clock, created at the
  RSM/gateway entry (``deadline.default.ms``) or adopted from the caller;
- it propagates through a thread-local scope (``deadline_scope`` /
  ``current_deadline``) so the storage transport and the chunk path consume
  it without plumbing an argument through every signature;
- across the sidecar boundary it rides the ``x-deadline-ms`` HTTP header /
  gRPC invocation metadata as *remaining milliseconds* (absolute monotonic
  time is process-local, so the wire carries the budget, not the instant —
  the same scheme gRPC itself uses for deadline propagation);
- expired deadlines raise ``DeadlineExceededException`` — a distinct type so
  the sidecar boundaries map it to 504 / ``DEADLINE_EXCEEDED`` instead of a
  generic 500, and so the breaker can treat it as caller impatience rather
  than backend failure.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import threading
import time
from typing import Iterator, Optional

from tieredstorage_tpu.storage.core import StorageBackendException
from tieredstorage_tpu.utils.locks import new_lock

#: Header / gRPC-metadata key carrying the remaining budget in integer
#: milliseconds (the deadline twin of the ``traceparent`` key).
DEADLINE_HEADER = "x-deadline-ms"

_local = threading.local()
_exceeded_lock = new_lock("deadline._exceeded_lock")
_exceeded_total = 0


class DeadlineExceededException(StorageBackendException):
    """The end-to-end deadline expired: the request fails fast, before (or
    instead of) another network attempt. Subclasses StorageBackendException
    so it propagates through the storage stack, but stays distinct so the
    boundaries map it to 504 / DEADLINE_EXCEEDED and the circuit breaker
    does not count caller impatience as a backend failure."""

    def __init__(self, message: str):
        super().__init__(message)
        global _exceeded_total
        with _exceeded_lock:
            _exceeded_total += 1


def exceeded_total() -> int:
    """Process-wide count of DeadlineExceededException raises (exported as
    the `deadline-exceeded-total` resilience gauge)."""
    with _exceeded_lock:
        return _exceeded_total


@dataclasses.dataclass(frozen=True)
class Deadline:
    """An absolute point on the monotonic clock."""

    at_monotonic: float

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + seconds)

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls.after(ms / 1000.0)

    def remaining_s(self) -> float:
        return self.at_monotonic - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def header_value(self) -> str:
        """Remaining budget as the wire form (integer ms, floored at 0)."""
        return str(max(0, int(math.ceil(self.remaining_s() * 1000.0))))


def parse_deadline_ms(value: Optional[str]) -> Optional[Deadline]:
    """A ``Deadline`` from an ``x-deadline-ms`` wire value, or None.

    Strict ASCII-digit grammar (the gateway's Content-Length precedent:
    int() alone accepts '+5'/'1_0'/non-ASCII digits); malformed values are
    ignored — deadline propagation must never fail a request. '0' parses to
    an already-expired deadline (the fast-fail path)."""
    if value is None:
        return None
    text = value.strip()
    if not text or not all(c in "0123456789" for c in text):
        return None
    return Deadline.after_ms(int(text))


def current_deadline() -> Optional[Deadline]:
    return getattr(_local, "deadline", None)


def remaining_s() -> Optional[float]:
    """Remaining budget of the ambient deadline, or None when unconstrained."""
    deadline = current_deadline()
    return None if deadline is None else deadline.remaining_s()


@contextlib.contextmanager
def deadline_scope(deadline: Optional[Deadline]) -> Iterator[Optional[Deadline]]:
    """Install `deadline` as the ambient deadline for the block.

    A nested scope can only tighten: the effective deadline is the minimum of
    the new and any enclosing one (a sub-operation must not outlive its
    parent's budget). `None` is a no-op (keeps the enclosing scope)."""
    prior = current_deadline()
    if deadline is None:
        yield prior
        return
    effective = (
        deadline
        if prior is None or deadline.at_monotonic < prior.at_monotonic
        else prior
    )
    _local.deadline = effective
    try:
        yield effective
    finally:
        _local.deadline = prior


@contextlib.contextmanager
def ensure_deadline(default_s: Optional[float]) -> Iterator[Optional[Deadline]]:
    """Entry-point helper: adopt the ambient deadline if one exists, else
    install a fresh one of `default_s` (None ⇒ unconstrained). The caller's
    explicit deadline always wins over the configured default."""
    if default_s is None or current_deadline() is not None:
        yield current_deadline()
        return
    with deadline_scope(Deadline.after(default_s)) as d:
        yield d


def check_deadline(what: str) -> None:
    """Fail fast when the ambient deadline has expired — called at layer
    entries so a doomed request never reaches the network."""
    deadline = current_deadline()
    if deadline is not None and deadline.expired:
        raise DeadlineExceededException(
            f"Deadline exceeded before {what} "
            f"(over budget by {-deadline.remaining_s() * 1000.0:.0f} ms)"
        )
