"""Kafka-protocol varint primitives (unsigned varint, zigzag varlong).

Used by the custom-metadata tagged-field serde; byte-compatible with Kafka's
ByteUtils encoding (the reference delegates to Kafka's protocol types,
core/.../metadata/SegmentCustomMetadataSerde.java:28-58).
"""

from __future__ import annotations


def write_unsigned_varint(value: int, out: bytearray) -> None:
    if value < 0:
        raise ValueError("unsigned varint cannot be negative")
    while (value & ~0x7F) != 0:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)


def read_unsigned_varint(data: bytes, pos: int) -> tuple[int, int]:
    value = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("Truncated varint")
        b = data[pos]
        pos += 1
        value |= (b & 0x7F) << shift
        if not (b & 0x80):
            return value, pos
        shift += 7
        if shift > 63:
            raise ValueError("Varint too long")


def write_varlong(value: int, out: bytearray) -> None:
    """Zigzag-encoded signed varlong (Kafka Type.VARLONG)."""
    zz = (value << 1) ^ (value >> 63)
    write_unsigned_varint(zz & 0xFFFFFFFFFFFFFFFF, out)


def read_varlong(data: bytes, pos: int) -> tuple[int, int]:
    zz, pos = read_unsigned_varint(data, pos)
    return (zz >> 1) ^ -(zz & 1), pos
