"""Token-bucket rate limiting for upload streams.

Reference: core/.../transform/RateLimitedInputStream.java — bucket capacity =
rate/s with greedy refill, reads block until tokens are available, and tokens
acquired beyond the actual read are refunded (:57-85); MIN_RATE floor.
The reference uses bucket4j's lock-free bucket; here a monotonic-clock bucket
under a lock suffices (uploads are a handful of threads, not a hot loop).
"""

from __future__ import annotations

import io
import time
from typing import BinaryIO
from tieredstorage_tpu.utils.locks import new_lock

MIN_RATE = 16 * 1024  # bytes/s floor (reference: JDK>=21 value)


class TokenBucket:
    def __init__(self, rate_bytes_per_second: int):
        if rate_bytes_per_second < MIN_RATE:
            raise ValueError(
                f"Upload rate {rate_bytes_per_second} must be at least {MIN_RATE} bytes/s"
            )
        self.capacity = rate_bytes_per_second
        self._tokens = float(rate_bytes_per_second)
        self._rate = float(rate_bytes_per_second)
        self._last = time.monotonic()
        self._lock = new_lock("ratelimit.TokenBucket._lock")

    def _refill_locked(self) -> None:
        now = time.monotonic()
        self._tokens = min(self.capacity, self._tokens + (now - self._last) * self._rate)
        self._last = now

    def consume(self, tokens: int) -> None:
        """Blocks until `tokens` are available (greedy refill)."""
        tokens = min(tokens, self.capacity)
        while True:
            with self._lock:
                self._refill_locked()
                if self._tokens >= tokens:
                    self._tokens -= tokens
                    return
                deficit = tokens - self._tokens
            time.sleep(deficit / self._rate)

    def refund(self, tokens: int) -> None:
        with self._lock:
            self._tokens = min(self.capacity, self._tokens + tokens)


class RateLimitedStream(io.RawIOBase):
    """Wraps a stream; each read first acquires tokens, refunding short reads."""

    def __init__(self, inner: BinaryIO, bucket: TokenBucket):
        self._inner = inner
        self._bucket = bucket

    def readable(self) -> bool:
        return True

    def read(self, size: int = -1) -> bytes:
        if size is None or size < 0:
            # Unbounded reads are chunked so the bucket still paces them.
            out = bytearray()
            while True:
                part = self.read(64 * 1024)
                if not part:
                    return bytes(out)
                out += part
        if size == 0:
            return b""
        want = min(size, self._bucket.capacity)
        self._bucket.consume(want)
        data = self._inner.read(want)
        if len(data) < want:
            self._bucket.refund(want - len(data))
        return data

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def close(self) -> None:
        try:
            self._inner.close()
        finally:
            super().close()
