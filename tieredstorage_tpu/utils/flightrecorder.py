"""Per-request flight recorder: Dapper-style request-scoped evidence.

A trace tells you *that* a fetch was slow; the flight recorder tells you
*why this one* was: which cache tier served each chunk window (chunk cache /
device hot tier / fleet peer / remote backend), whether a hedge fired and
won, how many replica failover hops the storage layer took, what the GCM
window accounting looked like (``dispatches``/``hbm_roundtrips`` per
window), and how much of the end-to-end deadline budget remained at each
stage (Sigelman et al., "Dapper", 2010 — the per-request annotation model;
the aggregate half lives in metrics/slo.py).

Mechanics mirror the deadline and tracing contexts (utils/deadline.py,
utils/tracing.py):

- a ``RequestRecord`` is installed in a thread-local by
  ``FlightRecorder.request(...)`` at the request entry (RSM ``_traced``
  operations, the sidecar gateway, the fleet ``/chunk`` serve path);
- layers below enrich the ambient record through the module-level ``note``
  / ``stage`` helpers without plumbing an argument through every
  signature — no active record means the helpers return after one
  thread-local read;
- pool hops that stay within one request (the chunk cache's bounded window
  load) re-install the record explicitly via ``bound`` (the prefetch
  deliberately does NOT — it outlives the request that triggered it);
- the record is keyed by the request's ``trace_id``, so a histogram
  exemplar (metrics/core.py) or an SLO breach (metrics/slo.py) resolves to
  the full per-request evidence via ``FlightRecorder.find``.

Retention is a bounded ring: the ``ring_size`` SLOWEST completed requests
(min-heap on duration — a fast request never evicts a slow one) plus the
``ring_size`` most recent FAILED requests. Disabled mode is zero-work like
``LockWitness``: ``request`` yields without allocating and the module
helpers see no ambient record.
"""

from __future__ import annotations

import contextlib
import dataclasses
import heapq
import threading
import time
from collections import deque
from typing import Iterator, Optional

from tieredstorage_tpu.utils.locks import new_lock, note_mutation

_local = threading.local()


def _deadline_remaining_s() -> Optional[float]:
    # Deferred: utils.deadline pulls in the storage package (its exception
    # base class), and this module must stay importable from metrics/core.py
    # before any storage module has loaded.
    from tieredstorage_tpu.utils import deadline as deadline_util

    return deadline_util.remaining_s()


@dataclasses.dataclass
class RequestRecord:
    """One request's evidence. Mutated only by the request's own thread and
    the pool workers it explicitly ``bound`` the record to while it blocks
    on them; counters are best-effort by design (a torn increment from a
    worker that outlived its window deadline under-counts one tier serve,
    it never corrupts the ring)."""

    name: str
    trace_id: str
    start_s: float
    end_s: float = 0.0
    error: Optional[str] = None
    #: Deadline budget remaining at entry/exit (ms); None = unconstrained.
    deadline_entry_ms: Optional[float] = None
    deadline_exit_ms: Optional[float] = None
    #: Accumulated evidence counters ("tier.chunk_cache", "hedge.won", ...).
    counters: dict = dataclasses.field(default_factory=dict)
    #: (stage name, ms since request start, deadline remaining ms | None).
    stages: list = dataclasses.field(default_factory=list)

    @property
    def duration_ms(self) -> float:
        return max(0.0, (self.end_s - self.start_s) * 1000.0)

    def tier_breakdown(self) -> dict[str, float]:
        """Chunks served per cache tier (the ``tier.*`` counter family)."""
        return {
            k[len("tier."):]: v
            for k, v in self.counters.items()
            if k.startswith("tier.")
        }

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "trace_id": self.trace_id,
            #: perf_counter at entry — CLOCK_MONOTONIC on Linux, the same
            #: clock the device-scheduler timeline stamps, so an exported
            #: record lands on the launch slices' time axis (ISSUE 17).
            "start_s": round(self.start_s, 6),
            "duration_ms": round(self.duration_ms, 3),
            "error": self.error,
            "deadline_entry_ms": self.deadline_entry_ms,
            "deadline_exit_ms": self.deadline_exit_ms,
            "tiers": self.tier_breakdown(),
            "counters": dict(self.counters),
            "stages": [list(s) for s in self.stages],
        }
        windows = self.counters.get("gcm.windows", 0.0)
        if windows:
            out["gcm_dispatches_per_window"] = round(
                self.counters.get("gcm.dispatches", 0.0) / windows, 3
            )
            out["gcm_hbm_roundtrips_per_window"] = round(
                self.counters.get("gcm.hbm_roundtrips", 0.0) / windows, 3
            )
        batched = self.counters.get("gcm.batched_windows", 0.0)
        if batched:
            # Mean occupancy of the shared launches this request's windows
            # rode (ISSUE 15); the per-launch identity is the
            # `gcm.batch:<id>` stage marker.
            out["gcm_batch_occupancy"] = round(
                self.counters.get("gcm.batch_occupancy", 0.0) / batched, 3
            )
        return out


# ------------------------------------------------------------ ambient record
def current_record() -> Optional[RequestRecord]:
    return getattr(_local, "record", None)


def current_trace_id() -> Optional[str]:
    """Trace id of the ambient request record, or None — the exemplar
    source for Histogram buckets (metrics/core.py)."""
    record = current_record()
    return record.trace_id or None if record is not None else None


def note(counter: str, n: float = 1.0) -> None:
    """Add ``n`` to a counter on the ambient record (no-op without one)."""
    record = current_record()
    if record is None:
        return
    record.counters[counter] = record.counters.get(counter, 0.0) + n


def stage(name: str) -> None:
    """Mark a stage on the ambient record: elapsed ms since request start
    and the deadline budget remaining at this point (no-op without one)."""
    record = current_record()
    if record is None:
        return
    remaining = _deadline_remaining_s()
    record.stages.append((
        name,
        round((time.perf_counter() - record.start_s) * 1000.0, 3),
        None if remaining is None else round(remaining * 1000.0, 3),
    ))


@contextlib.contextmanager
def bound(record: Optional[RequestRecord]) -> Iterator[None]:
    """Re-install ``record`` as the ambient record for the block — the
    cross-thread hop for pool work that stays within one request (the chunk
    cache's window load). ``None`` is a no-op, so call sites can pass
    ``current_record()`` captured on the request thread unconditionally."""
    if record is None:
        yield
        return
    prior = current_record()
    _local.record = record
    try:
        yield
    finally:
        _local.record = prior


class FlightRecorder:
    """Bounded recorder of the slowest and the failed requests.

    All shared state (rings + counters) mutates under one lock; records
    themselves are owned by their request thread until archived. Disabled
    recorders never install a record, so every module helper is a single
    thread-local read on the hot path."""

    def __init__(
        self,
        enabled: bool = False,
        *,
        ring_size: int = 64,
        time_source=time.perf_counter,
    ) -> None:
        if ring_size < 1:
            raise ValueError(f"ring_size must be >= 1, got {ring_size}")
        self.enabled = enabled
        self.ring_size = ring_size
        self._now = time_source
        self._lock = new_lock("flightrecorder.FlightRecorder._lock")
        #: min-heap of (duration_ms, seq, record): the ROOT is the fastest
        #: retained record, so a new slow request evicts it in O(log n).
        self._slow: list[tuple[float, int, RequestRecord]] = []
        self._failed: deque[RequestRecord] = deque(maxlen=ring_size)
        self._seq = 0
        #: Requests archived (exported in /varz's flight section).
        self.requests_seen = 0
        self.requests_failed = 0

    # ------------------------------------------------------------ recording
    @contextlib.contextmanager
    def request(
        self, name: str, *, trace_id: Optional[str] = None
    ) -> Iterator[Optional[RequestRecord]]:
        """Install a fresh record for the block (the request entry point).

        Reentrant like ``ensure_deadline``: when a record is already
        ambient (the gateway opened one and the RSM operation under it
        enters again) the existing record is yielded untouched, so one
        request is one record regardless of how many layers enter."""
        if not self.enabled or current_record() is not None:
            yield current_record()
            return
        record = RequestRecord(
            name=name, trace_id=trace_id or "", start_s=self._now()
        )
        remaining = _deadline_remaining_s()
        if remaining is not None:
            record.deadline_entry_ms = round(remaining * 1000.0, 3)
        _local.record = record
        try:
            yield record
        except BaseException as e:
            record.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            _local.record = None
            record.end_s = self._now()
            remaining = _deadline_remaining_s()
            if remaining is not None:
                record.deadline_exit_ms = round(remaining * 1000.0, 3)
            self._archive(record)

    def _archive(self, record: RequestRecord) -> None:
        with self._lock:
            self.requests_seen += 1
            note_mutation("flightrecorder.FlightRecorder.requests_seen")
            if record.error is not None:
                self.requests_failed += 1
                note_mutation("flightrecorder.FlightRecorder.requests_failed")
                self._failed.append(record)  # deque maxlen = ring semantics
            entry = (record.duration_ms, self._seq, record)
            self._seq += 1
            if len(self._slow) < self.ring_size:
                heapq.heappush(self._slow, entry)
            elif entry[0] > self._slow[0][0]:
                heapq.heappushpop(self._slow, entry)

    # -------------------------------------------------------------- readers
    def slowest(self, n: Optional[int] = None) -> list[RequestRecord]:
        """Retained records, slowest first."""
        with self._lock:
            ordered = sorted(self._slow, key=lambda e: (-e[0], e[1]))
        records = [record for _, _, record in ordered]
        return records if n is None else records[:n]

    def failures(self) -> list[RequestRecord]:
        """Retained failed records, most recent last."""
        with self._lock:
            return list(self._failed)

    def find(self, trace_id: str) -> Optional[RequestRecord]:
        """Resolve an exemplar/breach trace id to its retained record."""
        matches = self.find_all(trace_id)
        return matches[0] if matches else None

    def find_all(self, trace_id: str) -> list[RequestRecord]:
        """EVERY retained record carrying ``trace_id`` (slow ring first,
        then failed), deduplicated. One trace id can own several records on
        one instance — a gateway request plus the peer ``/chunk`` serves it
        triggered land in the same recorder when the instances share a
        process — and the fleet stitcher wants all of them."""
        if not trace_id:
            return []
        out: list[RequestRecord] = []
        with self._lock:
            for _, _, record in self._slow:
                if record.trace_id == trace_id:
                    out.append(record)
            for record in self._failed:
                if record.trace_id == trace_id and not any(
                    r is record for r in out
                ):
                    out.append(record)
        return out

    @property
    def ring_occupancy(self) -> int:
        with self._lock:
            return len(self._slow)

    def summary(self) -> dict:
        """The /varz flight section: totals, ring occupancy, top-3 slowest
        with their tier breakdowns."""
        with self._lock:
            seen, failed = self.requests_seen, self.requests_failed
            occupancy = len(self._slow)
        return {
            "enabled": self.enabled,
            "requests_seen": seen,
            "requests_failed": failed,
            "ring_occupancy": occupancy,
            "ring_size": self.ring_size,
            "top_slowest": [
                {
                    "name": r.name,
                    "trace_id": r.trace_id,
                    "duration_ms": round(r.duration_ms, 3),
                    "tiers": r.tier_breakdown(),
                }
                for r in self.slowest(3)
            ],
        }

    def dump(
        self,
        *,
        limit: Optional[int] = None,
        trace: Optional[str] = None,
        slowest: Optional[int] = None,
    ) -> dict:
        """The GET /debug/requests payload: slowest-first retained records
        plus the failure ring.

        Filters (ISSUE 17, exclusive of each other by the gateway's
        grammar but composable here): ``trace`` keeps only records carrying
        that trace id (both rings — the fleet stitcher's per-member query);
        ``slowest`` returns just the N slowest completed records with an
        empty failure list (the exemplar-selection query)."""
        if trace is not None:
            matches = [r.to_dict() for r in self.find_all(trace)]
            return {
                "enabled": self.enabled,
                "requests_seen": self.requests_seen,
                "requests_failed": self.requests_failed,
                "trace": trace,
                "slowest": matches,
                "failed": [],
            }
        if slowest is not None:
            return {
                "enabled": self.enabled,
                "requests_seen": self.requests_seen,
                "requests_failed": self.requests_failed,
                "slowest": [r.to_dict() for r in self.slowest(slowest)],
                "failed": [],
            }
        slow = self.slowest(limit)
        failed = self.failures()
        if limit is not None:
            failed = failed[-limit:]
        return {
            "enabled": self.enabled,
            "requests_seen": self.requests_seen,
            "requests_failed": self.requests_failed,
            "slowest": [r.to_dict() for r in slow],
            "failed": [r.to_dict() for r in failed],
        }

    def reset(self) -> None:
        with self._lock:
            self._slow.clear()
            self._failed.clear()
            self.requests_seen = 0
            self.requests_failed = 0


#: Process-wide default recorder; the RSM wires a real one from
#: `flight.enabled` (mirrors NOOP_TRACER).
NOOP_RECORDER = FlightRecorder(enabled=False)
