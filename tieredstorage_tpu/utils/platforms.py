"""Virtual-CPU-mesh platform pinning, shared by tests, bench, and the driver
entry points.

Multi-chip sharding paths are validated on a virtual CPU mesh
(``--xla_force_host_platform_device_count``); the axon site hook pins
``jax_platforms`` to the real single TPU, which can neither provide N devices
nor (in sandboxes) finish backend acquisition at all — so every caller that
wants the virtual mesh must force the platform explicitly *before* the first
JAX backend initialization.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def pin_virtual_cpu(min_devices: int = 8) -> None:
    """Pin JAX to the host platform with at least ``min_devices`` virtual CPU
    devices. Safe to call multiple times; raises if JAX initialized a backend
    with fewer devices before the flag could take effect."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    match = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    count = max(1, min_devices)
    if match is None:
        if count > 1:
            os.environ["XLA_FLAGS"] = f"{flags} {_COUNT_FLAG}={count}".strip()
    elif int(match.group(1)) < count:
        os.environ["XLA_FLAGS"] = flags.replace(
            match.group(0), f"{_COUNT_FLAG}={count}"
        )

    import jax

    jax.config.update("jax_platforms", "cpu")
    cpus = jax.devices("cpu")
    if len(cpus) < min_devices:
        raise RuntimeError(
            f"virtual CPU mesh has {len(cpus)} devices, need {min_devices}; "
            f"a JAX backend was initialized before {_COUNT_FLAG} could be "
            "raised — call pin_virtual_cpu() before any jax.devices()/jit use"
        )
