"""Shared host-side utilities (streams, varints, token bucket)."""
