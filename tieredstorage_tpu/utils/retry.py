"""Unified failure-policy plane, half 1: typed retry + circuit breakers.

Failure handling used to be scattered: the resilient storage decorator had
an inline backoff loop, the peer cache hand-rolled per-owner down-cooldowns,
the gossip agent had bare probe timeouts, and the batcher failed waiters on
the first launch exception. Retries without shared policy *amplify* outages
instead of absorbing them ("Overload Control for Scaling WeChat
Microservices", SOSP 2018; Dean & Barroso, "The Tail at Scale", CACM 2013),
so this module is the single owner of backoff everywhere:

- ``RetryPolicy`` — a typed, frozen policy: attempt cap, exponential backoff
  with *decorrelated jitter* (Brooker, AWS Architecture Blog 2015: each
  sleep is uniform(base, prev*3) capped, which spreads synchronized
  retriers better than plain exp+jitter), and error classification
  (retryable / terminal / healthy-contract-answer / neutral).
- ``call_with_retry`` — the one driver all seams use. It is
  *deadline-aware*: an attempt is never scheduled past the ambient request
  deadline (utils/deadline), so a doomed request sheds instead of sleeping.
  Every attempt and backoff lands in the process ``RetryLedger`` (exported
  by the ``retry-metrics`` group) and on the ambient flight record
  (``retry.attempts``), so amplification is observable, not inferred.
- ``CircuitBreaker`` — closed → open → half-open with single-probe
  admission (moved here from storage/resilient.py, which re-exports it);
  ``BreakerBoard`` keys breakers per target (peer URL, gossip member) so
  one bad replica cannot open the breaker for the healthy rest.

Classification semantics shared by every seam: *healthy* errors are
contract answers from a live target (404, invalid range) — breaker success,
never retried; ``DeadlineExceededException`` is caller impatience — breaker
neutral, never retried; *terminal* errors indict the call, not the target's
availability — breaker failure, never retried; everything retryable is
breaker failure and eligible for another attempt while the cap, the
optional ``retry_gate`` (storage's token-bucket RetryBudget) and the
deadline allow.
"""

from __future__ import annotations

import dataclasses
import enum
import random
import time
from typing import Callable, Dict, Optional, Tuple, Type, TypeVar

from tieredstorage_tpu.storage.core import StorageBackendException
from tieredstorage_tpu.utils import flightrecorder as flight
from tieredstorage_tpu.utils.deadline import DeadlineExceededException, remaining_s
from tieredstorage_tpu.utils.locks import new_lock, note_mutation

_T = TypeVar("_T")

#: Process-default jitter source. Seams that need reproducible schedules
#: (tests, tools/chaos_matrix.py) pass their own seeded ``random.Random``.
_RNG = random.Random()


class BreakerState(enum.Enum):
    CLOSED = 0
    HALF_OPEN = 1
    OPEN = 2


class CircuitOpenException(StorageBackendException):
    """Fast-fail: the breaker is open and the call never reached the target."""


class Outcome(enum.Enum):
    """How a raised exception is treated by policy + breaker accounting."""

    RETRYABLE = "retryable"  # breaker failure; another attempt may follow
    TERMINAL = "terminal"  # breaker failure; re-raised immediately
    HEALTHY = "healthy"  # contract answer from a live target; breaker success
    NEUTRAL = "neutral"  # proves nothing (deadline, interrupt); breaker neutral
    FAST_FAIL = "fast_fail"  # a nested breaker refused; no accounting here


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Typed retry policy: attempt cap + decorrelated-jitter backoff +
    exception classification. Frozen so a policy can be shared across
    threads and seams without defensive copies."""

    max_attempts: int = 3
    base_backoff_s: float = 0.01
    max_backoff_s: float = 1.0
    #: Exception types eligible for another attempt (breaker failures).
    retryable: Tuple[Type[BaseException], ...] = (StorageBackendException,)
    #: Never retried even if also retryable (checked first): the call is
    #: indicted, not the target's availability.
    terminal: Tuple[Type[BaseException], ...] = ()
    #: Contract answers from a healthy target (404, invalid range): breaker
    #: success, re-raised without retry.
    healthy: Tuple[Type[BaseException], ...] = ()
    #: Neither proves nor indicts the target (beyond the always-neutral
    #: DeadlineExceededException): breaker neutral, re-raised.
    neutral: Tuple[Type[BaseException], ...] = ()

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_backoff_s < 0.0:
            raise ValueError("base_backoff_s must be >= 0")
        if self.max_backoff_s < self.base_backoff_s:
            raise ValueError("max_backoff_s must be >= base_backoff_s")

    def single(self) -> "RetryPolicy":
        """This policy with retries disabled (e.g. non-replayable uploads:
        the first attempt consumes the stream)."""
        return dataclasses.replace(self, max_attempts=1)

    def classify(self, exc: BaseException) -> Outcome:
        """Map a raised exception to its policy outcome. Precedence:
        fast-fail > healthy > neutral > terminal > retryable > terminal."""
        if not isinstance(exc, Exception):
            return Outcome.NEUTRAL  # KeyboardInterrupt/SystemExit: hands off
        if isinstance(exc, CircuitOpenException):
            return Outcome.FAST_FAIL
        if self.healthy and isinstance(exc, self.healthy):
            return Outcome.HEALTHY
        if isinstance(exc, DeadlineExceededException) or (
            self.neutral and isinstance(exc, self.neutral)
        ):
            return Outcome.NEUTRAL
        if self.terminal and isinstance(exc, self.terminal):
            return Outcome.TERMINAL
        if self.retryable and isinstance(exc, self.retryable):
            return Outcome.RETRYABLE
        return Outcome.TERMINAL

    def backoff_s(self, prev_s: Optional[float], rng: random.Random) -> float:
        """Next sleep via decorrelated jitter:
        ``min(cap, uniform(base, max(base, prev*3)))``."""
        floor = self.base_backoff_s
        ceil = max(floor, (floor if prev_s is None else prev_s) * 3.0)
        return min(self.max_backoff_s, rng.uniform(floor, ceil))


class RetryLedger:
    """Process-wide per-site retry accounting (the ``retry-metrics`` source).

    Sites are dotted seam names (``storage.fetch``, ``peer.forward``,
    ``gossip.probe``, ``device.launch``). Per site: total attempts, retries
    (attempts beyond a call's first), give-ups (calls that exhausted the
    policy), and cumulative backoff ms. Amplification per site is derivable
    as ``attempts / (attempts - retries)`` — the chaos matrix gates on it.
    """

    def __init__(self) -> None:
        self._lock = new_lock("retry.RetryLedger._lock")
        self._sites: Dict[str, Dict[str, float]] = {}
        #: Optional backoff observer (the retry-metrics histogram); called
        #: OUTSIDE the ledger lock with the delay in ms.
        self.on_backoff: Optional[Callable[[float], None]] = None
        #: Observer calls that raised (swallowed — an observer must not
        #: break a retry — but the failure stays countable).
        self.observer_failures = 0

    def _rec(self, site: str) -> Dict[str, float]:
        rec = self._sites.get(site)
        if rec is None:
            rec = self._sites[site] = {
                "attempts": 0.0,
                "retries": 0.0,
                "giveups": 0.0,
                "backoff_ms": 0.0,
            }
        return rec

    def note_attempt(self, site: str) -> None:
        with self._lock:
            self._rec(site)["attempts"] += 1.0
            note_mutation("retry.RetryLedger._sites")

    def note_retry(self, site: str, delay_s: float) -> None:
        delay_ms = delay_s * 1000.0
        with self._lock:
            rec = self._rec(site)
            rec["retries"] += 1.0
            rec["backoff_ms"] += delay_ms
            note_mutation("retry.RetryLedger._sites")
            hook = self.on_backoff
        if hook is not None:
            try:
                hook(delay_ms)
            except Exception:  # noqa: BLE001 — observers must not break retries
                with self._lock:
                    self.observer_failures += 1
                    note_mutation("retry.RetryLedger.observer_failures")

    def note_giveup(self, site: str) -> None:
        with self._lock:
            self._rec(site)["giveups"] += 1.0
            note_mutation("retry.RetryLedger._sites")

    def value(self, site: str, field: str) -> float:
        with self._lock:
            rec = self._sites.get(site)
            return 0.0 if rec is None else rec.get(field, 0.0)

    def amplification(self, site: str) -> float:
        """attempts per originating call at `site` (1.0 = no retries)."""
        with self._lock:
            rec = self._sites.get(site)
            if rec is None:
                return 1.0
            calls = rec["attempts"] - rec["retries"]
            return rec["attempts"] / calls if calls > 0 else 1.0

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {site: dict(rec) for site, rec in self._sites.items()}


_LEDGER = RetryLedger()


def ledger() -> RetryLedger:
    """The process-wide ledger (one accounting plane across every seam)."""
    return _LEDGER


class CircuitBreaker:
    """Closed → open → half-open breaker with single-probe admission.

    After ``failure_threshold`` consecutive failures the breaker opens and
    ``acquire`` fails fast with CircuitOpenException (no network) until
    ``cooldown_s`` passes; then exactly ONE half-open probe is admitted —
    success closes, failure re-opens. ``on_neutral`` releases a probe slot
    without moving the state machine (caller impatience is not evidence).
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 30.0,
        *,
        time_source: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[BreakerState, BreakerState], None]] = None,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self._threshold = failure_threshold
        self._cooldown_s = cooldown_s
        self._now = time_source
        self._on_transition = on_transition
        self._lock = new_lock("retry.CircuitBreaker._lock")
        self._state = BreakerState.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        #: Cumulative transition/fast-fail counters, exported as gauges.
        self.opens = 0
        self.half_opens = 0
        self.closes = 0
        self.fast_fails = 0
        #: Transition-observer callbacks that raised (swallowed-exception
        #: checker: a failing observer must not break the breaker, but the
        #: failure must still be countable).
        self.observer_failures = 0

    @property
    def state(self) -> BreakerState:
        with self._lock:
            return self._state

    @property
    def state_code(self) -> int:
        return self.state.value

    @property
    def refusing(self) -> bool:
        """True while acquire() would fail fast right now: open inside its
        cooldown, or half-open with the probe slot taken. A non-destructive
        peek for target *selection* (gossip skips refusing peers) — it does
        not admit or consume a probe."""
        with self._lock:
            if self._state is BreakerState.OPEN:
                return self._now() - self._opened_at < self._cooldown_s
            return self._state is BreakerState.HALF_OPEN and self._probe_in_flight

    def _transition_locked(self, new: BreakerState) -> None:
        old, self._state = self._state, new
        if old is new:
            return
        if new is BreakerState.OPEN:
            self.opens += 1
            note_mutation("retry.CircuitBreaker.opens")
        elif new is BreakerState.HALF_OPEN:
            self.half_opens += 1
            note_mutation("retry.CircuitBreaker.half_opens")
        else:
            self.closes += 1
            note_mutation("retry.CircuitBreaker.closes")
        flight.note(f"breaker.state.{new.name.lower()}")
        if self._on_transition is not None:
            try:
                self._on_transition(old, new)
            except Exception:  # noqa: BLE001 — observers must not break the breaker
                self.observer_failures += 1

    def acquire(self) -> None:
        """Gate a call; raises CircuitOpenException while open."""
        with self._lock:
            if self._state is BreakerState.OPEN:
                if self._now() - self._opened_at >= self._cooldown_s:
                    self._transition_locked(BreakerState.HALF_OPEN)
                else:
                    self.fast_fails += 1
                    note_mutation("retry.CircuitBreaker.fast_fails")
                    raise CircuitOpenException(
                        f"Circuit breaker open ({self._consecutive_failures} "
                        "consecutive failures); failing fast"
                    )
            if self._state is BreakerState.HALF_OPEN:
                if self._probe_in_flight:
                    self.fast_fails += 1
                    note_mutation("retry.CircuitBreaker.fast_fails")
                    raise CircuitOpenException(
                        "Circuit breaker half-open; probe already in flight"
                    )
                self._probe_in_flight = True

    def on_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            self._transition_locked(BreakerState.CLOSED)

    def on_neutral(self) -> None:
        """The call neither proves nor indicts the target (e.g. the caller's
        deadline expired client-side): release a half-open probe slot without
        moving the state machine either way."""
        with self._lock:
            self._probe_in_flight = False

    def on_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            was_probe = self._probe_in_flight
            self._probe_in_flight = False
            if was_probe or self._consecutive_failures >= self._threshold:
                self._opened_at = self._now()
                self._transition_locked(BreakerState.OPEN)


class BreakerBoard:
    """Per-target circuit breakers sharing one policy configuration.

    One bad peer must not open the breaker for the healthy rest, so the
    peer cache and gossip agent key a breaker per target (owner URL /
    member id), created lazily here. Transition totals are aggregated
    across targets for the ``retry-metrics`` gauges.

    Lock order: a breaker's transition observer increments the board
    counters, so the only cross-lock edge is CircuitBreaker._lock →
    BreakerBoard._lock; board methods never touch a breaker's lock while
    holding their own (state reads snapshot the breaker list first).
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 30.0,
        *,
        time_source: Callable[[], float] = time.monotonic,
        on_transition: Optional[
            Callable[[str, BreakerState, BreakerState], None]
        ] = None,
    ) -> None:
        self._threshold = failure_threshold
        self._cooldown_s = cooldown_s
        self._now = time_source
        self._observer = on_transition
        self._lock = new_lock("retry.BreakerBoard._lock")
        self._breakers: Dict[str, CircuitBreaker] = {}
        #: Aggregated transition totals across all targets.
        self.opened = 0
        self.half_opened = 0
        self.closed = 0

    def _on_transition(self, target: str, old: BreakerState, new: BreakerState) -> None:
        with self._lock:
            if new is BreakerState.OPEN:
                self.opened += 1
                note_mutation("retry.BreakerBoard.opened")
            elif new is BreakerState.HALF_OPEN:
                self.half_opened += 1
                note_mutation("retry.BreakerBoard.half_opened")
            else:
                self.closed += 1
                note_mutation("retry.BreakerBoard.closed")
        if self._observer is not None:
            self._observer(target, old, new)

    def for_target(self, target: str) -> CircuitBreaker:
        with self._lock:
            breaker = self._breakers.get(target)
            if breaker is None:
                breaker = CircuitBreaker(
                    self._threshold,
                    self._cooldown_s,
                    time_source=self._now,
                    on_transition=lambda old, new, t=target: self._on_transition(
                        t, old, new
                    ),
                )
                self._breakers[target] = breaker
                note_mutation("retry.BreakerBoard._breakers")
            return breaker

    def _snapshot(self) -> Dict[str, CircuitBreaker]:
        with self._lock:
            return dict(self._breakers)

    def targets(self) -> Dict[str, BreakerState]:
        return {t: b.state for t, b in self._snapshot().items()}

    def open_count(self) -> int:
        """Targets currently refusing calls (the ``peers_down`` analogue)."""
        return sum(1 for b in self._snapshot().values() if b.refusing)

    def known_count(self) -> int:
        with self._lock:
            return len(self._breakers)


def call_with_retry(
    fn: Callable[[], _T],
    *,
    policy: RetryPolicy,
    site: str,
    breaker: Optional[CircuitBreaker] = None,
    retry_gate: Optional[Callable[[], bool]] = None,
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    ledger: Optional[RetryLedger] = None,
) -> _T:
    """The one retry driver every I/O seam uses.

    Per attempt: breaker gate → ``fn()`` → classify. Retries happen only
    while the attempt cap, the optional ``retry_gate`` (storage's shared
    RetryBudget: retries are an *earned* resource) and the ambient deadline
    all allow — an attempt is NEVER scheduled past the deadline; the
    original error is re-raised instead of sleeping into certain doom.
    Each retry re-takes the breaker gate, so a retry loop cannot bypass an
    opening breaker. Attempts/backoffs land in the ledger (site-keyed) and
    on the ambient flight record.
    """
    led = ledger if ledger is not None else _LEDGER
    jitter = rng if rng is not None else _RNG
    prev_delay: Optional[float] = None
    attempt = 0
    while True:
        attempt += 1
        if breaker is not None:
            try:
                breaker.acquire()
            except CircuitOpenException:
                flight.note("breaker.fast_fail")
                raise
        led.note_attempt(site)
        flight.note("retry.attempts")
        try:
            result = fn()
        except BaseException as exc:
            outcome = policy.classify(exc)
            if breaker is not None:
                if outcome is Outcome.HEALTHY:
                    breaker.on_success()
                elif outcome in (Outcome.NEUTRAL, Outcome.FAST_FAIL):
                    breaker.on_neutral()
                else:
                    breaker.on_failure()
            if outcome is not Outcome.RETRYABLE:
                raise
            if attempt >= policy.max_attempts:
                led.note_giveup(site)
                raise
            if retry_gate is not None and not retry_gate():
                led.note_giveup(site)
                raise
            delay = policy.backoff_s(prev_delay, jitter)
            prev_delay = delay
            budget = remaining_s()
            if budget is not None and delay >= budget:
                led.note_giveup(site)
                raise  # the deadline can't fit the backoff + another attempt
            led.note_retry(site, delay)
            flight.note("retry.backoff_ms", delay * 1000.0)
            if on_retry is not None:
                on_retry(attempt, delay, exc)
            sleep(delay)
            continue
        if breaker is not None:
            breaker.on_success()
        return result
