"""Named lock factories + the runtime LockWitness/RaceWitness (ISSUES 7, 10).

The static lock-order checker (tieredstorage_tpu/analysis/lockorder.py)
proves, from the AST, that the cross-module lock-acquisition graph is a DAG.
A static proof is only as good as its call-resolution heuristics, so this
module pairs it with a RUNTIME witness: when ``TSTPU_LOCK_WITNESS=1`` every
lock created through these factories is wrapped, each thread's acquisition
stack is tracked, and every observed "held A, then acquired B" pair becomes
an edge in a global order graph. An edge that would close a cycle — the
runtime signature of a potential deadlock (Coffman's circular-wait
condition) — is recorded as a violation (``TSTPU_LOCK_WITNESS=raise`` makes
it throw at the acquisition site). The chaos and fleet-demo suites run with
the witness enabled and assert zero violations, so the statically proven
order is validated against real concurrent executions.

Granularity is the CLASS attribute, not the instance: all instances of
``LoadingCache`` share the node ``caching.LoadingCache._lock``, matching the
static graph (which cannot see instances either). Reentrant acquisition of
the same name (RLock, or two instances of one class) is not an edge.

The same flag arms the **RaceWitness** — the runtime half of the
guarded-by race checker (tieredstorage_tpu/analysis/races.py). Shared
mutable attributes whose mutation sites carry a ``note_mutation(site)``
hook record the witnessed lock actually held (and the mutating thread) at
every sampled write; ``races.runtime_crosscheck`` then validates the
STATICALLY inferred guard of each site against what real executions
observed: an inferred-guarded site mutated with the wrong (or no) lock
held, or a ``# tsa: single-thread`` site mutated from more than one
thread, is a cross-check violation (``new_unguarded`` sites accept torn
updates by declaration and are only checked for being known). ``make
chaos`` and ``make fleet-demo`` fail on any.

When the flag is unset the factories return the raw ``threading``
primitives and ``note_mutation``/``new_unguarded`` are no-ops returning
immediately — zero wrappers, zero overhead, asserted by the unit tests.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, TypeVar

ENV_FLAG = "TSTPU_LOCK_WITNESS"


def witness_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "") not in ("", "0", "false", "no")


def _witness_raises() -> bool:
    return os.environ.get(ENV_FLAG, "").lower() in ("raise", "strict")


class LockOrderViolation(RuntimeError):
    """Acquiring this lock here closes a cycle in the observed lock order."""


class LockWitness:
    """Global acquisition-order graph over named locks, per-thread stacks.

    Thread stacks live in a ``threading.local``; the shared edge graph is
    guarded by one plain (unwitnessed) lock. Edge insertion is O(reachable)
    for the cycle probe but runs at most once per distinct (a, b) pair over
    the process lifetime — steady state adds zero graph work.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()  # guards _succ/_edge_sites/violations
        self._local = threading.local()
        #: adjacency: name -> set of names acquired while holding it
        self._succ: dict[str, set[str]] = {}
        #: first-seen (holder, acquired) pairs, insertion-ordered
        self._edge_sites: dict[tuple[str, str], int] = {}
        #: every witnessed lock name EVER acquired (edges only cover nested
        #: acquisitions; the race cross-check needs outermost locks too).
        #: Mutated via set.add (atomic under the GIL), snapshot-read.
        self._acquired_names: set[str] = set()
        self.violations: list[str] = []

    # ------------------------------------------------------------- thread TLS
    def _held(self) -> list[str]:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        return held

    # ---------------------------------------------------------------- events
    def note_acquire(self, name: str) -> None:
        self._acquired_names.add(name)
        held = self._held()
        for holder in dict.fromkeys(held):  # distinct, preserve order
            if holder != name:  # reentrant / same-class sibling: not an edge
                self._add_edge(holder, name)
        held.append(name)

    def note_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # ----------------------------------------------------------------- graph
    def _add_edge(self, a: str, b: str) -> None:
        with self._mu:
            if (a, b) in self._edge_sites:
                return
            if self._reachable(b, a):
                message = (
                    f"lock-order cycle: thread holds {a!r} while acquiring "
                    f"{b!r}, but the opposite order {b!r} -> ... -> {a!r} "
                    "was already observed"
                )
                self.violations.append(message)
                raise_now = _witness_raises()
            else:
                raise_now = False
            self._edge_sites[(a, b)] = len(self._edge_sites)
            self._succ.setdefault(a, set()).add(b)
        if raise_now:
            raise LockOrderViolation(message)

    def _reachable(self, src: str, dst: str) -> bool:
        seen = set()
        stack = [src]
        while stack:
            node = stack.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            stack.extend(self._succ.get(node, ()))
        return False

    # ------------------------------------------------------------ inspection
    def edges(self) -> list[tuple[str, str]]:
        with self._mu:
            return sorted(self._edge_sites, key=self._edge_sites.get)

    def lock_names(self) -> set[str]:
        with self._mu:
            return {n for edge in self._edge_sites for n in edge}

    def acquired_names(self) -> set[str]:
        """Every witnessed lock name acquired at least once this process."""
        return set(self._acquired_names)

    def held_names(self) -> list[str]:
        """The CURRENT thread's held witnessed-lock stack (outermost first)."""
        return list(self._held())

    def assert_dag(self) -> None:
        with self._mu:
            violations = list(self.violations)
        if violations:
            raise LockOrderViolation(
                f"{len(violations)} lock-order violation(s):\n  "
                + "\n  ".join(violations)
            )

    def reset(self) -> None:
        with self._mu:
            self._succ.clear()
            self._edge_sites.clear()
            self._acquired_names.clear()
            self.violations.clear()


_WITNESS = LockWitness()


def witness() -> LockWitness:
    """The process-wide witness (one graph across every subsystem)."""
    return _WITNESS


class _WitnessedLock:
    """threading.Lock/RLock wrapper reporting acquire/release to the witness.

    Duck-types the lock protocol ``threading.Condition`` relies on
    (acquire/release/context manager; no ``_is_owned`` so Condition falls
    back to its probe), so ``new_condition`` can build a Condition directly
    on top of one and the witness sees the condition's own release/reacquire
    around ``wait()`` for free.
    """

    __slots__ = ("_inner", "name")

    def __init__(self, name: str, inner) -> None:
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                _WITNESS.note_acquire(self.name)
            except LockOrderViolation:  # raise-mode: don't leak the lock
                self._inner.release()
                raise
        return ok

    def release(self) -> None:
        self._inner.release()
        _WITNESS.note_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        # threading.Condition ownership probe. Delegate to the RLock's own
        # notion when available; Condition's acquire(0) fallback is wrong for
        # a reentrant inner lock (the owner's probe would succeed).
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<WitnessedLock {self.name} {self._inner!r}>"


def new_lock(name: str) -> threading.Lock:
    """A ``threading.Lock``, witnessed under TSTPU_LOCK_WITNESS."""
    if witness_enabled():
        return _WitnessedLock(name, threading.Lock())
    return threading.Lock()


def new_rlock(name: str) -> threading.RLock:
    """A ``threading.RLock``, witnessed under TSTPU_LOCK_WITNESS."""
    if witness_enabled():
        return _WitnessedLock(name, threading.RLock())
    return threading.RLock()


def new_condition(name: str, lock: Optional[threading.Lock] = None) -> threading.Condition:
    """A ``threading.Condition``; its lock is witnessed under the flag.

    ``wait()`` releases and reacquires through the witnessed lock's own
    acquire/release (Condition's ``_release_save``/``_acquire_restore``
    fallbacks call them), so the held-stack stays accurate across waits.
    """
    if witness_enabled():
        inner = lock if lock is not None else threading.RLock()
        return threading.Condition(_WitnessedLock(name, inner))
    return threading.Condition(lock)


# --------------------------------------------------------------- RaceWitness
SAMPLE_ENV = "TSTPU_RACE_SAMPLE"


class RaceWitness:
    """Sampling recorder of the lock actually held at attribute mutation
    sites (the runtime half of ``analysis/races.py``).

    A *site* is a ``<module stem>.<Class>.<attr>`` name passed to
    ``note_mutation`` from inside the mutation's critical section (or from
    an annotated lock-free site). Per site the witness keeps the SET of
    innermost witnessed-lock names observed held (``None`` when the
    mutating thread held no witnessed lock) and the set of mutating thread
    idents — enough for the static↔runtime cross-check: an inferred guard
    must be the only lock ever observed, an annotated single-thread site
    must only ever see one thread. Sampling (``TSTPU_RACE_SAMPLE=n``
    records every n-th mutation per site, default 1) bounds the overhead
    on hot sites; set-insertion makes steady state O(1) regardless.
    """

    def __init__(self, witness: Optional[LockWitness] = None) -> None:
        self._witness = witness if witness is not None else _WITNESS
        self._mu = threading.Lock()
        try:
            self._sample_every = max(1, int(os.environ.get(SAMPLE_ENV, "1")))
        except ValueError:
            self._sample_every = 1
        #: site -> set of innermost held witnessed-lock names (None = none)
        self.held_at: dict[str, set[Optional[str]]] = {}
        #: site -> set of mutating thread idents
        self.threads_at: dict[str, set[int]] = {}
        #: site -> raw mutation events seen (pre-sampling)
        self.counts: dict[str, int] = {}
        #: names declared deliberately lock-free via ``new_unguarded``
        self.unguarded_names: set[str] = set()

    def note_mutation(self, site: str) -> None:
        held = self._witness._held()
        innermost = held[-1] if held else None
        ident = threading.get_ident()
        with self._mu:
            count = self.counts.get(site, 0)
            self.counts[site] = count + 1
            if count % self._sample_every:
                return
            self.held_at.setdefault(site, set()).add(innermost)
            self.threads_at.setdefault(site, set()).add(ident)

    def register_unguarded(self, name: str) -> None:
        with self._mu:
            self.unguarded_names.add(name)

    def sites(self) -> list[str]:
        with self._mu:
            return sorted(self.held_at)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "sites": {
                    site: {
                        "held": sorted(
                            "<none>" if h is None else h
                            for h in self.held_at[site]
                        ),
                        "threads": len(self.threads_at.get(site, ())),
                        "mutations": self.counts.get(site, 0),
                    }
                    for site in sorted(self.held_at)
                },
                "unguarded_names": sorted(self.unguarded_names),
            }

    def reset(self) -> None:
        with self._mu:
            self.held_at.clear()
            self.threads_at.clear()
            self.counts.clear()
            self.unguarded_names.clear()


_RACE_WITNESS = RaceWitness()


def race_witness() -> RaceWitness:
    """The process-wide race witness (pairs with ``witness()``)."""
    return _RACE_WITNESS


def note_mutation(site: str) -> None:
    """Record a shared-attribute mutation at ``site`` (no-op unless the
    witness flag is armed). Call INSIDE the guarded section so the held
    witnessed lock is observable; annotated single-thread sites call it
    wherever the mutation happens."""
    if witness_enabled():
        _RACE_WITNESS.note_mutation(site)


_T = TypeVar("_T")


def new_unguarded(name: str, value: _T) -> _T:
    """Declare a DELIBERATELY lock-free shared attribute.

    Returns ``value`` unchanged (zero overhead, no wrapper); the name is the
    same ``<module stem>.<Class>.<attr>`` convention as ``new_lock``. The
    declaration says a torn update is an ACCEPTED cost (best-effort
    counters on hot paths) — distinct from ``# tsa: single-thread``, which
    claims only one thread ever writes. It is load-bearing twice over: the
    static race checker (analysis/races.py) exempts the attribute from
    guarded-by inference but validates the name against the assignment
    target, and under the witness flag the name registers with the
    RaceWitness so runtime observations of the site classify as declared
    rather than unknown.
    """
    if witness_enabled():
        _RACE_WITNESS.register_unguarded(name)
    return value
