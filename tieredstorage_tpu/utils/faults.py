"""Unified failure-policy plane, half 2: deterministic fault injection.

`faults/schedule.py` injects storage-op faults behind one decorator; this
module generalises the idea into a process-wide **FaultPlane** with named
injection points threaded through every I/O seam the retry plane
(utils/retry.py) guards, so `tools/chaos_matrix.py` can sweep fault-kind ×
tier and gate the policy invariants per cell. A rule is

    site ":" kind ["=" arg] ["@" trigger] ["~" match]

- site: ``storage.read`` | ``storage.write`` | ``peer.forward`` |
  ``gossip.probe`` | ``device.launch`` | ``lifecycle.journal`` |
  ``lifecycle.sweep`` | ``*`` (any site)
- kind:
    - ``error`` — raise FaultInjectedError (a StorageBackendException, so
      it propagates — and classifies as retryable — exactly like a real
      backend failure)
    - ``latency`` — sleep ``arg`` milliseconds (default 10) before the
      call; ``latency=10..250`` draws uniformly from [10, 250] ms with the
      plane's seeded RNG
    - ``partial`` — keep only the first ``arg`` bytes of the payload
      (default: half); data-bearing sites only (``storage.read``,
      ``peer.forward``) — the seam applies it via :func:`mutate`, and the
      downstream GCM tag check must refuse to serve the torn bytes
    - ``flaky`` — error on the site's first ``arg`` calls (default 10),
      healthy afterwards: the flaky-then-heal shape breakers must first
      open on and then re-close behind
- trigger (same grammar as faults/schedule.py): ``@N`` (Nth call),
  ``@every=K``, ``@from=N``, ``@p=P`` (seeded RNG), absent = every call
- match: only fire when ``match`` is a substring of the seam's key (object
  key, peer URL, member id, work class)

Arming mirrors the lock witness (utils/locks.py): set ``TSTPU_FAULTS`` to
the rule spec (rules joined with ``;`` or ``,``), optionally
``TSTPU_FAULTS_SEED``; unset means the module-level :func:`fire` helper is
one ``None`` check — zero wrappers, zero locks, zero work, asserted by a
poisoned-lock probe in the unit tests. Tools install a plane
programmatically via :func:`install`. Everything is deterministic for a
given seed and call sequence; every firing is recorded in
``FaultPlane.injections`` so runs can assert on what was actually injected.
"""

from __future__ import annotations

import dataclasses
import os
import random
import re
import time
from collections import Counter
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Union

from tieredstorage_tpu.storage.core import StorageBackendException
from tieredstorage_tpu.utils.locks import new_lock, note_mutation

ENV_FLAG = "TSTPU_FAULTS"
SEED_ENV = "TSTPU_FAULTS_SEED"

SITES = (
    "storage.read",
    "storage.write",
    "peer.forward",
    "gossip.probe",
    "device.launch",
    # Crash-consistent lifecycle plane (ISSUE 20): intent-journal appends
    # and recovery-sweeper passes are first-class failure seams too.
    "lifecycle.journal",
    "lifecycle.sweep",
)
KINDS = ("error", "latency", "partial", "flaky")
#: Sites whose payload bytes a ``partial`` rule may mutate.
DATA_SITES = ("storage.read", "peer.forward")


class FaultInjectedError(StorageBackendException):
    """Raised by an injected ``error``/``flaky`` fault at a named site."""

    def __init__(self, site: str, key: str, rule: str) -> None:
        super().__init__(f"Injected fault at {site} (key={key!r}, rule={rule})")
        self.site = site
        self.key = key
        self.rule = rule


_RULE_RE = re.compile(
    r"(?P<site>\*|[a-z]+\.[a-z]+)\s*:\s*(?P<kind>[a-z]+)"
    r"(?:\s*=\s*(?P<arg>\d+(?:\s*\.\.\s*\d+)?))?"
    r"(?:\s*@\s*(?P<trigger>[a-z0-9.=]+))?"
    r"(?:\s*~\s*(?P<match>[^~]+))?"
)


@dataclasses.dataclass(frozen=True)
class FaultPoint:
    """One parsed injection rule."""

    site: str  # one of SITES or "*"
    kind: str
    arg: Optional[int] = None
    arg_hi: Optional[int] = None  # upper bound of a latency lo..hi range
    nth: Optional[int] = None
    every: Optional[int] = None
    from_nth: Optional[int] = None
    probability: Optional[float] = None
    match: Optional[str] = None

    def __post_init__(self) -> None:
        if self.site != "*" and self.site not in SITES:
            raise ValueError(
                f"Unknown fault site {self.site!r}; must be one of {SITES} or '*'"
            )
        if self.kind not in KINDS:
            raise ValueError(f"Unknown fault kind {self.kind!r}; must be one of {KINDS}")
        if self.kind == "partial" and self.site not in DATA_SITES + ("*",):
            raise ValueError(f"Kind 'partial' only applies to data sites {DATA_SITES}")
        if self.nth is not None and self.nth < 1:
            raise ValueError("nth must be >= 1")
        if self.every is not None and self.every < 1:
            raise ValueError("every must be >= 1")
        if self.from_nth is not None and self.from_nth < 1:
            raise ValueError("from must be >= 1")
        if self.probability is not None and not (0.0 <= self.probability <= 1.0):
            raise ValueError("probability must be in [0, 1]")
        if self.arg_hi is not None:
            if self.kind != "latency":
                raise ValueError("range args (lo..hi) only apply to latency")
            if self.arg is None or self.arg_hi < self.arg:
                raise ValueError(
                    f"latency range must be lo..hi with hi >= lo, "
                    f"got {self.arg}..{self.arg_hi}"
                )

    @staticmethod
    def parse(text: str) -> "FaultPoint":
        m = _RULE_RE.fullmatch(text.strip())
        if m is None:
            raise ValueError(
                f"Invalid fault rule {text!r}; expected "
                "site:kind[=arg][@trigger][~match]"
            )
        nth = every = from_nth = None
        probability = None
        trigger = m.group("trigger")
        if trigger is not None:
            if trigger.isdigit():
                nth = int(trigger)
            elif trigger.startswith("every="):
                every = int(trigger[len("every="):])
            elif trigger.startswith("from="):
                from_nth = int(trigger[len("from="):])
            elif trigger.startswith("p="):
                probability = float(trigger[len("p="):])
            else:
                raise ValueError(
                    f"Invalid fault trigger {trigger!r}; expected N, every=K, "
                    "from=N, or p=P"
                )
        arg = m.group("arg")
        arg_lo = arg_hi = None
        if arg is not None:
            if ".." in arg:
                lo, _, hi = arg.partition("..")
                arg_lo, arg_hi = int(lo), int(hi)
            else:
                arg_lo = int(arg)
        match = m.group("match")
        return FaultPoint(
            site=m.group("site"),
            kind=m.group("kind"),
            arg=arg_lo,
            arg_hi=arg_hi,
            nth=nth,
            every=every,
            from_nth=from_nth,
            probability=probability,
            match=match.strip() if match else None,
        )

    def spec(self) -> str:
        """The rule back in spec form (reports, error messages)."""
        out = f"{self.site}:{self.kind}"
        if self.arg is not None:
            out += f"={self.arg}" + (f"..{self.arg_hi}" if self.arg_hi is not None else "")
        if self.nth is not None:
            out += f"@{self.nth}"
        elif self.every is not None:
            out += f"@every={self.every}"
        elif self.from_nth is not None:
            out += f"@from={self.from_nth}"
        elif self.probability is not None:
            out += f"@p={self.probability}"
        if self.match is not None:
            out += f"~{self.match}"
        return out

    def matches(self, site: str, key: str) -> bool:
        if self.site != "*" and self.site != site:
            return False
        return self.match is None or self.match in key


class FaultPlane:
    """Evaluates fault points against per-site call counters; fully
    deterministic for a given seed and call sequence. Latency sleeps happen
    OUTSIDE the plane lock (blocking-under-lock discipline)."""

    def __init__(
        self,
        rules: Iterable[FaultPoint],
        *,
        seed: int = 0,
        sleeper: Callable[[float], None] = time.sleep,
    ) -> None:
        self._rules = list(rules)
        self._rng = random.Random(seed)
        self._sleep = sleeper
        self._lock = new_lock("faults.FaultPlane._lock")
        self._calls: Counter[str] = Counter()
        #: Every firing as (site, kind, key), in order.
        self.injections: List[tuple] = []
        #: Firings per (site, kind) — the chaos-matrix evidence counters.
        self.fired: Counter = Counter()

    @classmethod
    def parse(
        cls,
        spec: Union[str, Sequence[str], None],
        *,
        seed: int = 0,
        sleeper: Callable[[float], None] = time.sleep,
    ) -> "FaultPlane":
        if spec is None:
            spec = []
        elif isinstance(spec, str):
            spec = [spec]
        parts = [q for p in spec for q in re.split(r"[;,]", str(p)) if q.strip()]
        return cls([FaultPoint.parse(q) for q in parts], seed=seed, sleeper=sleeper)

    @property
    def rules(self) -> List[FaultPoint]:
        return list(self._rules)

    def calls(self, site: str) -> int:
        with self._lock:
            return self._calls[site]

    def _fires_locked(self, rule: FaultPoint, call_no: int) -> bool:
        if rule.kind == "flaky":
            heal_after = rule.arg if rule.arg is not None else 10
            if call_no > heal_after:
                return False
            # fall through: an explicit trigger still gates the flaky window
        if rule.nth is not None:
            return call_no == rule.nth
        if rule.every is not None:
            return call_no % rule.every == 0
        if rule.from_nth is not None:
            return call_no >= rule.from_nth
        if rule.probability is not None:
            return self._rng.random() < rule.probability
        return True

    def fire(self, site: str, key: str = "") -> List[FaultPoint]:
        """Count one `site` call; sleep any fired latency, raise any fired
        error, and return fired data rules for the seam to apply via
        :func:`mutate`."""
        delays: List[float] = []
        error: Optional[FaultPoint] = None
        data_rules: List[FaultPoint] = []
        with self._lock:
            self._calls[site] += 1
            call_no = self._calls[site]
            note_mutation("faults.FaultPlane._calls")
            for rule in self._rules:
                if not rule.matches(site, key) or not self._fires_locked(rule, call_no):
                    continue
                self.injections.append((site, rule.kind, key))
                self.fired[(site, rule.kind)] += 1
                note_mutation("faults.FaultPlane.fired")
                if rule.kind == "latency":
                    if rule.arg is None:
                        delays.append(10.0)
                    elif rule.arg_hi is None:
                        delays.append(float(rule.arg))
                    else:
                        delays.append(self._rng.uniform(rule.arg, rule.arg_hi))
                elif rule.kind in ("error", "flaky"):
                    error = error if error is not None else rule
                else:  # partial
                    data_rules.append(rule)
        for delay_ms in delays:
            self._sleep(delay_ms / 1000.0)
        if error is not None:
            raise FaultInjectedError(site, key, error.spec())
        return data_rules

    @staticmethod
    def mutate(data: bytes, rules: Sequence[FaultPoint]) -> bytes:
        """Apply fired data rules (``partial``) to a fetched payload."""
        for rule in rules:
            keep = rule.arg if rule.arg is not None else len(data) // 2
            data = data[: max(0, min(len(data), keep))]
        return data

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "rules": [r.spec() for r in self._rules],
                "calls": dict(self._calls),
                "injections": len(self.injections),
                "fired": {f"{site}:{kind}": n for (site, kind), n in self.fired.items()},
            }


#: The installed plane. ``None`` (the default) means every seam's
#: ``fire()`` is a single attribute read — the zero-work disabled mode.
_PLANE: Optional[FaultPlane] = None


def plane() -> Optional[FaultPlane]:
    return _PLANE


def install(new_plane: Optional[FaultPlane]) -> Optional[FaultPlane]:
    """Install (or with None, remove) the process fault plane; returns the
    previous one so tools can restore it."""
    global _PLANE
    prior, _PLANE = _PLANE, new_plane
    return prior


def enabled() -> bool:
    return _PLANE is not None


def fire(site: str, key: str = "") -> Optional[List[FaultPoint]]:
    """The seam hook: no-op returning None unless a plane is installed."""
    p = _PLANE
    if p is None:
        return None
    return p.fire(site, key)


def mutate(data: bytes, rules: Optional[Sequence[FaultPoint]]) -> bytes:
    """Apply ``fire``'s returned data rules to a payload (no-op on None)."""
    if not rules:
        return data
    return FaultPlane.mutate(data, rules)


def _arm_from_env() -> None:
    spec = os.environ.get(ENV_FLAG, "")
    if spec in ("", "0", "false", "no"):
        return
    try:
        seed = int(os.environ.get(SEED_ENV, "0") or "0")
    except ValueError:
        seed = 0
    install(FaultPlane.parse(spec, seed=seed))


_arm_from_env()
