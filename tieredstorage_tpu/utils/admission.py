"""Admission control: bounded concurrency + bounded queue at the entry point.

The sidecar previously accepted unlimited concurrent work: the HTTP gateway
is a ThreadingHTTPServer (a thread per connection) and the gRPC server has a
worker pool but an unbounded accept queue, so overload manifested as
ever-growing queues, memory growth, and every request timing out together —
the classic congestion-collapse shape. DAGOR ("Overload Control for Scaling
WeChat Microservices", SOSP 2018) is explicit that shedding must happen at
the *entry* of the service, before any real work (here: before the request
body is even read), and that rejected callers must be told to back off.

``AdmissionController`` is that gate: at most ``max_concurrent`` requests
execute, at most ``max_queue`` more wait (bounded, with a wait deadline),
and everything beyond that is shed immediately with
``AdmissionRejectedException`` carrying a Retry-After hint — the boundaries
translate it to HTTP 429 + ``Retry-After`` and gRPC ``RESOURCE_EXHAUSTED``.
Counters are plain ints exported as resilience gauges; ``on_wait`` feeds the
admission-wait-time histogram.

Per-tenant fair share (ISSUE 6, fleet mode): callers that identify a tenant
(the gateway forwards the ``x-tenant`` header) are additionally subject to a
fair-share rule AT SATURATION — while no slot is free, a tenant already
holding at least ``ceil(max_concurrent / active_tenants)`` slots is shed
immediately instead of queuing, so one greedy tenant flooding the gate
cannot starve polite ones out of the bounded queue (DAGOR's user-fairness
property). Under light load the rule is inert: any tenant may use every
slot while nobody else wants them. Requests without a tenant behave exactly
as before.
"""

from __future__ import annotations

import math
import time
from collections import Counter
from typing import Callable, Optional
from tieredstorage_tpu.utils.locks import new_condition


class AdmissionRejectedException(Exception):
    """The request was shed at the entry gate; retry after `retry_after_s`."""

    def __init__(self, message: str, retry_after_s: float) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s


class AdmissionController:
    def __init__(
        self,
        max_concurrent: int,
        max_queue: int,
        *,
        queue_timeout_s: float = 1.0,
        retry_after_s: float = 1.0,
        on_wait: Optional[Callable[[float], None]] = None,
    ) -> None:
        if max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        if max_queue < 0:
            raise ValueError("max_queue must be >= 0")
        self._max_concurrent = max_concurrent
        self._max_queue = max_queue
        self._queue_timeout_s = queue_timeout_s
        self.retry_after_s = retry_after_s
        self.on_wait = on_wait
        self._cond = new_condition("admission.AdmissionController._cond")
        #: Requests currently executing / currently queued (gauges).
        self.active = 0
        self.queued = 0
        #: Cumulative admissions and sheds (gauges).
        self.admitted_total = 0
        self.shed_total = 0
        #: Per-tenant slot occupancy and fair-share sheds (fleet mode).
        self._tenant_active: Counter = Counter()
        self.tenant_sheds: Counter = Counter()

    def _fair_share(self) -> int:
        """Slots one tenant may hold while the gate is saturated: an equal
        split of the concurrency limit across tenants currently holding
        slots (at least 1 so a lone tenant is never zeroed)."""
        tenants = max(1, len(self._tenant_active))
        return max(1, math.ceil(self._max_concurrent / tenants))

    def tenant_active(self, tenant: str) -> int:
        with self._cond:
            return self._tenant_active.get(tenant, 0)

    def acquire(self, what: str = "", tenant: Optional[str] = None) -> None:
        """Admit or shed. Blocks at most `queue_timeout_s` in the bounded
        queue; raises AdmissionRejectedException when the queue is full,
        the wait times out, or — with a `tenant` — the tenant is over its
        fair share while the gate is saturated. Pair with release(tenant=)
        in a finally block."""
        start = time.monotonic()
        with self._cond:
            if self.active < self._max_concurrent:
                self._admit(tenant)
                return
            if tenant is not None and self._tenant_active[tenant] >= self._fair_share():
                # Saturated AND this tenant already holds its share: shed
                # without queuing so the bounded queue stays available to
                # tenants under their share.
                self.shed_total += 1
                self.tenant_sheds[tenant] += 1
                raise AdmissionRejectedException(
                    f"tenant {tenant!r} over fair share "
                    f"({self._tenant_active[tenant]}/{self._fair_share()} slots, "
                    f"{self.active} active): {what or 'request'} shed",
                    self.retry_after_s,
                )
            if self.queued >= self._max_queue:
                self.shed_total += 1
                if tenant is not None:
                    self.tenant_sheds[tenant] += 1
                raise AdmissionRejectedException(
                    f"admission queue full ({self.active} active, "
                    f"{self.queued} queued): {what or 'request'} shed",
                    self.retry_after_s,
                )
            self.queued += 1
            try:
                deadline = start + self._queue_timeout_s
                while self.active >= self._max_concurrent:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.shed_total += 1
                        if tenant is not None:
                            self.tenant_sheds[tenant] += 1
                        raise AdmissionRejectedException(
                            f"queued {self._queue_timeout_s * 1000:.0f} ms without "
                            f"a slot: {what or 'request'} shed",
                            self.retry_after_s,
                        )
                    self._cond.wait(remaining)
                self._admit(tenant)
            finally:
                self.queued -= 1
        if self.on_wait is not None:
            self.on_wait((time.monotonic() - start) * 1000.0)

    def _admit(self, tenant: Optional[str]) -> None:
        self.active += 1
        self.admitted_total += 1
        if tenant is not None:
            self._tenant_active[tenant] += 1

    def release(self, tenant: Optional[str] = None) -> None:
        with self._cond:
            self.active -= 1
            if tenant is not None:
                self._tenant_active[tenant] -= 1
                if self._tenant_active[tenant] <= 0:
                    del self._tenant_active[tenant]
            self._cond.notify()
