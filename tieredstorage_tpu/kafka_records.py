"""Minimal Kafka log-segment record-batch inspection for the compression heuristic.

Reference: core/.../SegmentCompressionChecker.java:30-38 — open the segment,
inspect only the FIRST record batch; if its compression type != NONE the whole
segment is treated as already compressed. The reference delegates to Kafka's
FileRecords; here the batch header is parsed directly: the magic byte sits at
offset 16 for both modern (v2) batches and legacy (v0/v1) message sets, and
the compression codec is the low 3 bits of the attributes field (offset 21,
int16, for v2; offset 17, int8, for v0/v1).
"""

from __future__ import annotations

import struct
from pathlib import Path


class InvalidRecordBatchException(Exception):
    """First batch is unreadable/truncated (reference:
    core/.../InvalidRecordBatchException.java; caught by the RSM to fall back
    to uploading uncompressed, RemoteStorageManager.java:389-392)."""


_V2_HEADER_LEN = 23  # through the attributes field
_LEGACY_HEADER_LEN = 18

COMPRESSION_NONE = 0


def first_batch_compression_codec(segment_path: str | Path) -> int:
    """Returns the compression codec id (0 = NONE) of the first record batch."""
    try:
        with open(segment_path, "rb") as f:
            header = f.read(_V2_HEADER_LEN)
    except OSError as e:
        raise InvalidRecordBatchException(f"Cannot read segment: {e}") from e

    if len(header) < _LEGACY_HEADER_LEN:
        raise InvalidRecordBatchException(
            f"Segment too short for a record batch header: {len(header)} bytes"
        )
    magic = header[16]
    if magic == 2:
        if len(header) < _V2_HEADER_LEN:
            raise InvalidRecordBatchException("Truncated v2 record batch header")
        (attributes,) = struct.unpack_from(">h", header, 21)
    elif magic in (0, 1):
        attributes = header[17]
    else:
        raise InvalidRecordBatchException(f"Unknown record batch magic: {magic}")
    return attributes & 0x07


def segment_looks_compressed(segment_path: str | Path) -> bool:
    return first_batch_compression_codec(segment_path) != COMPRESSION_NONE
