"""KIP-405 custom segment metadata: tagged fields stored by the broker.

Reference: core/.../metadata/{SegmentCustomMetadataField.java (fields
REMOTE_SIZE(0, varlong), OBJECT_PREFIX(1, compact string),
OBJECT_KEY(2, compact string) — indexes are wire compatibility-critical),
SegmentCustomMetadataBuilder.java:30-64, SegmentCustomMetadataSerde.java:28-58}.

Wire format is Kafka's tagged-fields section: uvarint field count, then per
field in ascending tag order: uvarint tag, uvarint payload size, payload.
VARLONG payloads are zigzag varlongs; COMPACT_STRING payloads are
uvarint(len+1) + UTF-8 bytes.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Mapping

from tieredstorage_tpu.metadata import RemoteLogSegmentMetadata
from tieredstorage_tpu.object_key import Suffix, main_path
from tieredstorage_tpu.utils.varint import (
    read_unsigned_varint,
    read_varlong,
    write_unsigned_varint,
    write_varlong,
)


class _FieldType(enum.Enum):
    VARLONG = "varlong"
    COMPACT_STRING = "compact_string"


class SegmentCustomMetadataField(enum.Enum):
    REMOTE_SIZE = (0, _FieldType.VARLONG)
    OBJECT_PREFIX = (1, _FieldType.COMPACT_STRING)
    OBJECT_KEY = (2, _FieldType.COMPACT_STRING)

    def __init__(self, index: int, field_type: _FieldType):
        self.index = index
        self.field_type = field_type

    @staticmethod
    def by_index(index: int) -> "SegmentCustomMetadataField":
        for f in SegmentCustomMetadataField:
            if f.index == index:
                return f
        raise ValueError(f"Unknown custom metadata field index {index}")

    @staticmethod
    def names() -> list[str]:
        return [f.name for f in SegmentCustomMetadataField]


def _encode_payload(field: SegmentCustomMetadataField, value: object) -> bytes:
    out = bytearray()
    if field.field_type is _FieldType.VARLONG:
        write_varlong(int(value), out)
    else:
        data = str(value).encode("utf-8")
        write_unsigned_varint(len(data) + 1, out)
        out += data
    return bytes(out)


def _decode_payload(field: SegmentCustomMetadataField, data: bytes) -> object:
    if field.field_type is _FieldType.VARLONG:
        value, _ = read_varlong(data, 0)
        return value
    length_plus_one, pos = read_unsigned_varint(data, 0)
    return data[pos : pos + length_plus_one - 1].decode("utf-8")


def serialize_custom_metadata(fields: Mapping[int, object]) -> bytes:
    if not fields:
        return b""
    out = bytearray()
    write_unsigned_varint(len(fields), out)
    for tag in sorted(fields):
        payload = _encode_payload(SegmentCustomMetadataField.by_index(tag), fields[tag])
        write_unsigned_varint(tag, out)
        write_unsigned_varint(len(payload), out)
        out += payload
    return bytes(out)


def deserialize_custom_metadata(data: bytes | None) -> dict[int, object]:
    if not data:
        return {}
    count, pos = read_unsigned_varint(data, 0)
    fields: dict[int, object] = {}
    for _ in range(count):
        tag, pos = read_unsigned_varint(data, pos)
        size, pos = read_unsigned_varint(data, pos)
        fields[tag] = _decode_payload(
            SegmentCustomMetadataField.by_index(tag), data[pos : pos + size]
        )
        pos += size
    return fields


class SegmentCustomMetadataBuilder:
    """Accumulates per-suffix upload byte counts; emits the configured field subset."""

    def __init__(
        self,
        include_fields: list[SegmentCustomMetadataField],
        object_key_prefix: str,
        segment_metadata: RemoteLogSegmentMetadata,
    ):
        self._include = include_fields
        self._prefix = object_key_prefix
        self._metadata = segment_metadata
        self._sizes: dict[Suffix, int] = {}

    def add_upload_result(self, suffix: Suffix, bytes_uploaded: int) -> "SegmentCustomMetadataBuilder":
        if suffix in self._sizes:
            raise ValueError(f"Upload result for {suffix} already added")
        self._sizes[suffix] = bytes_uploaded
        return self

    def total_size(self) -> int:
        return sum(self._sizes.values())

    def build(self) -> dict[int, object]:
        providers: dict[SegmentCustomMetadataField, Callable[[], object]] = {
            SegmentCustomMetadataField.REMOTE_SIZE: self.total_size,
            SegmentCustomMetadataField.OBJECT_PREFIX: lambda: self._prefix,
            SegmentCustomMetadataField.OBJECT_KEY: lambda: main_path(self._metadata),
        }
        return {f.index: providers[f]() for f in self._include}
