"""Fleet mode: sharded gateway instances behind consistent-hash routing.

The scale-out tier (ROADMAP item 3): N sidecar instances form a fleet —
``fleet/ring.py`` maps every segment object key to one owner instance on a
consistent-hash ring (virtual nodes, bounded key movement under membership
change), ``fleet/peer_cache.py`` resolves non-owner misses with one hop to
the owner's chunk cache over the shim-wire gateway (``GET /chunk``), and
``fleet/singleflight.py`` collapses concurrent duplicate fetches — local or
forwarded — to exactly one backend read. Each key has R replica owners
(``fleet.replication.factor`` ring successors, tried in order) so an
instance death loses no cache tier, and ``fleet/gossip.py`` runs SWIM-style
gossip membership (probe → suspect → dead, epoch-numbered views) so the
fleet self-organizes through joins, failures, and rolling restarts.
``fleet/metrics.py`` exports the ``fleet-metrics`` group, and
``fleet/telemetry.py`` aggregates every member's metric samples into one
fleet-wide scrape (sum/max/histogram-merge semantics per stat) over the
gateway's ``GET /fleet/telemetry`` route. See docs/fleet.rst.
"""

from tieredstorage_tpu.fleet.gossip import GossipAgent
from tieredstorage_tpu.fleet.metrics import (
    FLEET_METRIC_GROUP,
    FleetMetrics,
    register_fleet_metrics,
)
from tieredstorage_tpu.fleet.peer_cache import (
    PeerChunkCache,
    decode_chunk_frames,
    encode_chunk_frames,
)
from tieredstorage_tpu.fleet.ring import FleetRouter, HashRing, parse_instances
from tieredstorage_tpu.fleet.singleflight import SingleFlight
from tieredstorage_tpu.fleet.telemetry import (
    FleetTelemetry,
    export_samples,
    merge_samples,
)

__all__ = [
    "FLEET_METRIC_GROUP",
    "FleetMetrics",
    "FleetRouter",
    "FleetTelemetry",
    "GossipAgent",
    "HashRing",
    "PeerChunkCache",
    "SingleFlight",
    "export_samples",
    "merge_samples",
    "decode_chunk_frames",
    "encode_chunk_frames",
    "parse_instances",
    "register_fleet_metrics",
]
