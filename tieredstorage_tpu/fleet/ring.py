"""Consistent-hash segment routing for the gateway fleet.

Karger et al. ("Consistent Hashing and Random Trees", STOC '97): instances
own arcs of a fixed hash circle, keys map to the first instance point at or
after their own hash, and membership changes move only the keys on the arcs
adjacent to the joining/leaving instance — every other key keeps its owner.
Virtual nodes (``fleet.vnodes`` points per instance) smooth the arc-length
variance so ownership fractions concentrate near 1/N.

The hash is MD5 over stable text labels (``<instance>#<vnode>`` for ring
points, the raw object key for lookups), so the mapping is deterministic
across processes, restarts, and Python versions — every fleet member computes
the identical ring from the identical membership list, with no coordination
service in the loop. (MD5 here is a mixing function, not a security
boundary; routing does not authenticate anything.)

Routing granularity is the segment OBJECT KEY, not the chunk: all chunks of
one hot segment land in exactly one instance's cache, which is what makes
the peer tier (fleet/peer_cache.py) a single hop.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Mapping, Optional

from tieredstorage_tpu.utils.tracing import NOOP_TRACER
from tieredstorage_tpu.utils.locks import new_lock

#: Full circle size: MD5-derived points are taken mod 2^64.
_RING_BITS = 64
_RING_SIZE = 1 << _RING_BITS


def _point(label: str) -> int:
    return int.from_bytes(
        hashlib.md5(label.encode("utf-8")).digest()[:8], "big"
    )


class HashRing:
    """Immutable consistent-hash circle over a set of instance names."""

    def __init__(self, instances, vnodes: int = 64) -> None:
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        listed = list(instances)
        if not listed:
            raise ValueError("a hash ring needs at least one instance")
        names = sorted(set(listed))
        if len(names) != len(listed):
            # A duplicated name would silently halve the fleet's real
            # capacity (two "members" sharing one arc set) and desync rings
            # across members that happened to dedupe differently.
            dupes = sorted({n for n in names if listed.count(n) > 1})
            raise ValueError(f"duplicate ring instances: {', '.join(dupes)}")
        self.vnodes = vnodes
        self.instances = tuple(names)
        points: list[tuple[int, str]] = []
        for name in names:
            for v in range(vnodes):
                points.append((_point(f"{name}#{v}"), name))
        # Ties (astronomically unlikely) break by instance name so every
        # member sorts the identical ring.
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [o for _, o in points]

    def owner(self, key: str) -> str:
        """The instance owning `key`: first ring point at or after its hash,
        wrapping at the top of the circle."""
        idx = bisect.bisect_left(self._points, _point(key))
        if idx == len(self._points):
            idx = 0
        return self._owners[idx]

    def owners(self, key: str, n: int) -> list[str]:
        """The first `n` DISTINCT instances walking the circle from `key` —
        the failover preference order (owner first, then successors)."""
        start = bisect.bisect_left(self._points, _point(key))
        out: list[str] = []
        for i in range(len(self._points)):
            candidate = self._owners[(start + i) % len(self._points)]
            if candidate not in out:
                out.append(candidate)
                if len(out) == n:
                    break
        return out

    def ownership_fraction(self, instance: str) -> float:
        """Fraction of the hash circle whose keys map to `instance` (the
        ring-ownership gauge; ~1/N with enough vnodes)."""
        if instance not in self.instances:
            return 0.0
        owned = 0
        for i, owner in enumerate(self._owners):
            prev = self._points[i - 1] if i > 0 else self._points[-1] - _RING_SIZE
            if owner == instance:
                owned += self._points[i] - prev
        return owned / _RING_SIZE


def parse_instances(entries) -> dict[str, Optional[str]]:
    """``fleet.instances`` entries to {name: base_url|None}.

    Each entry is ``name=http://host:port`` (a routable peer) or a bare
    ``name`` (address unknown to this member — typically itself; the router
    never forwards to an address-less member, it serves locally)."""
    out: dict[str, Optional[str]] = {}
    for entry in entries:
        text = str(entry).strip()
        if not text:
            continue
        name, sep, url = text.partition("=")
        name = name.strip()
        if not name:
            raise ValueError(f"fleet instance entry {entry!r} has no name")
        if name in out:
            raise ValueError(f"duplicate fleet instance {name!r}")
        out[name] = url.strip() or None if sep else None
    return out


class FleetRouter:
    """Maps object keys to owner instances over a swappable HashRing.

    Membership is replaceable at runtime (``set_membership``) because
    addresses are often only known after gateways bind their ports, and
    because the fleet shrinks when an instance is declared dead; the ring is
    rebuilt atomically and the consistent-hash property bounds the keys that
    change owner to the arcs of the joining/leaving instances."""

    def __init__(
        self,
        instance_id: str,
        *,
        vnodes: int = 64,
        tracer=NOOP_TRACER,
    ) -> None:
        if not instance_id:
            raise ValueError("fleet.instance.id must be non-empty")
        self.instance_id = instance_id
        self.vnodes = vnodes
        self.tracer = tracer
        self._lock = new_lock("ring.FleetRouter._lock")
        self._peers: dict[str, Optional[str]] = {instance_id: None}
        self._ring = HashRing([instance_id], vnodes)
        #: Membership generations applied (starts at 1 for the solo ring).
        self.generation = 1
        #: Epoch of the last agreed view applied (0 = static membership
        #: only). Gossip (fleet/gossip.py) numbers its views so a delayed
        #: delivery can never roll the ring back to an older membership.
        self.view_epoch = 0

    def set_membership(
        self, peers: Mapping[str, Optional[str]], *, epoch: Optional[int] = None
    ) -> bool:
        """Replace the fleet membership with {name: base_url|None}. The
        local instance is always a member (added if absent).

        `epoch` numbers gossip-agreed views: an epoch at or below the last
        applied one is stale (a reordered delivery) and is ignored, so
        routing stays a pure function of the NEWEST agreed view. Un-numbered
        calls (bootstrap / tests / --fleet-peers) always apply. Returns
        whether the view was applied."""
        members = dict(peers)
        members.setdefault(self.instance_id, None)
        ring = HashRing(members, self.vnodes)
        with self._lock:
            if epoch is not None:
                if epoch <= self.view_epoch:
                    return False
                self.view_epoch = epoch
            self._peers = members
            self._ring = ring
            self.generation += 1
        self.tracer.event(
            "fleet.membership", instances=len(members),
            generation=self.generation, epoch=epoch if epoch is not None else 0,
        )
        return True

    def remove_instance(self, name: str) -> None:
        """Drop a dead member; its arcs redistribute to the ring successors
        (every other key keeps its owner). Removing the local instance or
        the last member is refused."""
        with self._lock:
            peers = dict(self._peers)
        if name == self.instance_id or name not in peers:
            return
        del peers[name]
        self.set_membership(peers)

    @property
    def ring(self) -> HashRing:
        with self._lock:
            return self._ring

    @property
    def peers(self) -> dict[str, Optional[str]]:
        with self._lock:
            return dict(self._peers)

    @property
    def instances(self) -> tuple[str, ...]:
        return self.ring.instances

    def owner(self, key: str) -> str:
        return self.ring.owner(key)

    def is_local(self, key: str) -> bool:
        return self.owner(key) == self.instance_id

    def route(self, key: str) -> tuple[str, Optional[str]]:
        """(owner, base_url): base_url is None when the key is locally owned
        or the owner's address is unknown (both mean: serve locally)."""
        with self._lock:
            owner = self._ring.owner(key)
            if owner == self.instance_id:
                return owner, None
            return owner, self._peers.get(owner)

    def route_owners(self, key: str, r: int) -> list[tuple[str, Optional[str]]]:
        """The R replica owners of `key` as ordered (owner, base_url) pairs —
        ring-successor preference order, one consistent (ring, peers)
        snapshot. base_url is None for the local instance and for members
        whose address is unknown (both mean: serve locally when reached)."""
        with self._lock:
            owners = self._ring.owners(key, r)
            return [
                (o, None if o == self.instance_id else self._peers.get(o))
                for o in owners
            ]

    def peer_url(self, name: str) -> Optional[str]:
        with self._lock:
            return self._peers.get(name)

    def local_ownership_fraction(self) -> float:
        return self.ring.ownership_fraction(self.instance_id)
