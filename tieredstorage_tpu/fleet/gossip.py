"""SWIM-style gossip membership: probe → suspect → dead, epoch-numbered views.

Das et al. ("SWIM: Scalable Weakly-consistent Infection-style Process Group
Membership Protocol", DSN '02) split membership into a *failure detector*
(periodic probes, bounded detection time) and a *disseminator* (membership
deltas piggybacked on the probe traffic). This module follows that shape
over the existing shim-wire gateway — ``POST /fleet/gossip`` is both the
probe and the delta exchange, ``GET /fleet/ping`` a cheap liveness/status
read — with the van Renesse heartbeat refinement: every member bumps a
local heartbeat counter each protocol period, and heartbeats spread
epidemically with the views, so second-hand freshness keeps a member ALIVE
even between direct contacts (one probe per period stays O(1) per member).

State machine per member (all timers counted in protocol periods,
``fleet.gossip.interval.ms``):

  ALIVE    --no heartbeat advance for suspect.periods-->  SUSPECT
  SUSPECT  --no refutation for dead.periods-->            DEAD
  SUSPECT/DEAD  --incarnation bump by the member-->       ALIVE

Suspicion is REFUTABLE: a member that hears itself called suspect/dead
re-announces itself with a higher *incarnation* number, which takes
precedence over any lower-incarnation state (the rejoin path after a
``kill -9`` + restart works the same way), and a relayed heartbeat advance
at the same incarnation recovers a false suspicion without the round trip.
Precedence is total and deterministic — ``(incarnation, heartbeat, status
rank)`` with DEAD > SUSPECT > ALIVE at an equal pair — so every member
converges to the same view from any delivery order.

The ring only changes when the agreed *routing view* (non-DEAD members)
changes: each change is numbered with a local, monotonically increasing
**view epoch** and applied through ``FleetRouter.set_membership(epoch=)``,
which refuses stale epochs. SUSPECT members stay in the ring — suspicion
must not thrash keys — so key movement stays bounded to the arcs of members
actually declared dead (or newly joined), exactly the consistent-hashing
guarantee, now under dynamic membership. ``fleet.instances`` becomes the
SEED set only: it bootstraps who to probe first, after which the fleet is
self-organizing.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import random
import threading
import time
import zlib
from typing import Callable, Mapping, Optional

from tieredstorage_tpu.fleet.ring import FleetRouter
from tieredstorage_tpu.utils import faults
from tieredstorage_tpu.utils.locks import new_lock, note_mutation
from tieredstorage_tpu.utils.retry import BreakerBoard, RetryPolicy, call_with_retry
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

log = logging.getLogger(__name__)

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"

#: Precedence rank at EQUAL (incarnation, heartbeat): dead overrides
#: suspect overrides alive (SWIM §4.2). A higher incarnation overrides any
#: lower-incarnation state (that is what makes suspicion refutable and
#: rejoin possible), and at equal incarnation a heartbeat advance overrides
#: any staler state (the van Renesse refinement: relayed liveness evidence
#: recovers a false suspicion — or even a false obituary — without an
#: incarnation round trip). The triple is a TOTAL order, so every member
#: reaches the same fixed point from any delivery order.
_STATUS_RANK = {ALIVE: 0, SUSPECT: 1, DEAD: 2}


@dataclasses.dataclass
class Member:
    """One fleet member as this agent currently believes it to be."""

    name: str
    url: Optional[str]
    incarnation: int = 0
    status: str = ALIVE
    #: Member-local period counter, bumped by the member itself each period
    #: and spread epidemically; an advance is liveness evidence no matter
    #: how many hops it travelled.
    heartbeat: int = 0
    #: Monotonic local time of the last heartbeat advance / direct contact.
    last_heard: float = 0.0
    #: Monotonic local time the member entered SUSPECT (0 otherwise).
    suspected_at: float = 0.0

    def entry(self) -> dict:
        """The wire form of this member for a gossip payload."""
        return {
            "name": self.name,
            "url": self.url,
            "incarnation": self.incarnation,
            "status": self.status,
            "heartbeat": self.heartbeat,
        }


def _fresher(
    inc_a: int, hb_a: int, status_a: str,
    inc_b: int, hb_b: int, status_b: str,
) -> bool:
    """Does state A take precedence over state B? Total order on
    (incarnation, heartbeat, status rank) — deterministic merge from any
    delivery order, the property the convergence tests pin."""
    return (inc_a, hb_a, _STATUS_RANK[status_a]) > (
        inc_b, hb_b, _STATUS_RANK[status_b]
    )


class GossipAgent:
    """The per-instance membership daemon.

    One protocol period (`run_period`, also steppable synchronously by
    tests and drills): bump own heartbeat, age peers through
    alive→suspect→dead, apply the resulting routing view to the ring if it
    changed (epoch-numbered), then probe the next non-dead peer round-robin
    with the full view piggybacked; the probe response view is merged back.
    Inbound exchanges (`on_gossip`, wired to POST /fleet/gossip) merge the
    sender's view and answer with ours — every exchange disseminates in
    both directions.
    """

    def __init__(
        self,
        router: FleetRouter,
        *,
        interval_s: float = 1.0,
        probe_timeout_s: float = 0.75,
        suspect_periods: int = 3,
        dead_periods: int = 3,
        probe_retries: int = 1,
        breaker_threshold: int = 2,
        tracer=NOOP_TRACER,
        transport: Optional[Callable[[str, dict], dict]] = None,
        time_source=time.monotonic,
        sleeper: Callable[[float], None] = time.sleep,
    ) -> None:
        if interval_s <= 0:
            raise ValueError(f"gossip interval must be > 0, got {interval_s}")
        self._router = router
        self.instance_id = router.instance_id
        self.interval_s = interval_s
        self.probe_timeout_s = probe_timeout_s
        self.suspect_after_s = suspect_periods * interval_s
        self.dead_after_s = dead_periods * interval_s
        self.tracer = tracer
        self._now = time_source
        self._transport = transport
        self._sleep = sleeper
        # Unified failure policy (ISSUE 19): a failed probe round trip gets
        # `probe_retries` extra attempts through the shared driver with
        # decorrelated jitter — seeded per instance id so a partitioned
        # fleet does NOT retry its probes in lockstep — and each member gets
        # a breaker (per-target board) that deprioritizes it in probe-target
        # selection after `breaker_threshold` consecutive failed rounds.
        self._probe_policy = RetryPolicy(
            max_attempts=1 + max(0, probe_retries),
            base_backoff_s=interval_s / 100.0,
            max_backoff_s=interval_s / 10.0,
            retryable=(Exception,),
        )
        self._jitter = random.Random(zlib.crc32(self.instance_id.encode("utf-8")))
        self.breakers = BreakerBoard(
            failure_threshold=max(1, breaker_threshold),
            cooldown_s=self.suspect_after_s,
            time_source=time_source,
        )
        self._lock = new_lock("gossip.GossipAgent._lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._clients: dict[str, object] = {}
        self._members: dict[str, Member] = {}
        self._probe_order: list[str] = []
        self._probe_idx = 0
        #: The routing view (non-DEAD members) last applied to the ring.
        self._applied_view: dict[str, Optional[str]] = {}
        #: Local view-epoch counter; bumped once per applied view change.
        self.epoch = 0
        # Counters (exported as fleet-metrics gauges).
        self.periods = 0
        self.probes_sent = 0
        self.acks = 0
        self.probe_failures = 0
        self.refutations = 0
        self.deltas_applied = 0
        self.period_errors = 0
        #: Probe candidates skipped because their breaker was refusing.
        self.probe_skips = 0
        #: Probe round trips that needed at least one retry attempt.
        self.retried_probes = 0
        self.seed(router.peers)

    # ------------------------------------------------------------- lifecycle
    def seed(self, peers: Mapping[str, Optional[str]]) -> None:
        """(Re)seed membership from {name: url|None} — the static
        ``fleet.instances`` list or ``--fleet-peers``. Known members keep
        their state (a reseed must not resurrect the dead); new ones start
        ALIVE with a fresh grace period."""
        now = self._now()
        with self._lock:
            for name, url in dict(peers).items():
                known = self._members.get(name)
                if known is None:
                    self._members[name] = Member(
                        name=name, url=url, last_heard=now
                    )
                elif url is not None:
                    known.url = url
            if self.instance_id not in self._members:
                self._members[self.instance_id] = Member(
                    name=self.instance_id, url=None, last_heard=now
                )
            self._applied_view = self._routing_view_locked()
            note_mutation("gossip.GossipAgent._members")

    @property
    def self_url(self) -> Optional[str]:
        """This instance's advertised gateway URL (from the seed set; the
        address peers will gossip onward for us)."""
        with self._lock:
            me = self._members.get(self.instance_id)
            return me.url if me is not None else None

    def set_self_url(self, url: str) -> None:
        """Advertise `url` as this instance's gateway (deployments that only
        know their port after bind)."""
        with self._lock:
            self._members[self.instance_id].url = url
            note_mutation("gossip.GossipAgent._members")

    def start(self) -> "GossipAgent":
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="fleet-gossip", daemon=True
                )
                self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            thread = self._thread
            self._thread = None
            clients = list(self._clients.values())
            self._clients.clear()
        if thread is not None:
            thread.join(timeout=5)
        for client in clients:
            client.close()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_period()
            except Exception:
                # The daemon must survive any single bad period (a peer
                # speaking garbage, a transport bug): count it loudly and
                # keep the failure detector running.
                with self._lock:
                    self.period_errors += 1
                    note_mutation("gossip.GossipAgent.period_errors")
                log.warning("gossip period failed", exc_info=True)
            self._stop.wait(self.interval_s)

    # ------------------------------------------------------------ the period
    def run_period(self) -> None:
        """One protocol period: heartbeat, age, re-ring, probe."""
        now = self._now()
        with self._lock:
            self.periods += 1
            me = self._members[self.instance_id]
            me.heartbeat += 1
            me.last_heard = now
            note_mutation("gossip.GossipAgent._members")
            transitions = self._age_members_locked(now)
            target = self._next_probe_target_locked()
            payload = self._view_payload_locked()
        for name, status in transitions:
            self.tracer.event("fleet.gossip.transition", member=name, status=status)
        self._apply_view_if_changed()
        if target is not None:
            self._probe(target, payload)

    def _age_members_locked(self, now: float) -> list[tuple[str, str]]:
        transitions: list[tuple[str, str]] = []
        for member in self._members.values():
            if member.name == self.instance_id or member.status == DEAD:
                continue
            if (
                member.status == ALIVE
                and now - member.last_heard > self.suspect_after_s
            ):
                member.status = SUSPECT
                member.suspected_at = now
                transitions.append((member.name, SUSPECT))
            elif (
                member.status == SUSPECT
                and now - member.suspected_at > self.dead_after_s
            ):
                member.status = DEAD
                transitions.append((member.name, DEAD))
        if transitions:
            note_mutation("gossip.GossipAgent._members")
        return transitions

    def _next_probe_target_locked(self) -> Optional[Member]:
        candidates = sorted(
            m.name for m in self._members.values()
            if m.name != self.instance_id and m.status != DEAD and m.url
        )
        if not candidates:
            return None
        if candidates != self._probe_order:
            self._probe_order = candidates
        # Breaker-aware selection: members whose breaker is refusing (opened
        # by consecutive failed probe rounds, still cooling down) are
        # DEPRIORITIZED, not silenced — skip them round-robin, but if every
        # candidate is refusing fall back to plain round-robin so the
        # failure detector keeps probing (breakers must never blind it).
        # `refusing` is a non-destructive read: the half-open probe slot is
        # only consumed by on_failure/on_success after the round completes.
        for _ in range(len(self._probe_order)):
            self._probe_idx = (self._probe_idx + 1) % len(self._probe_order)
            name = self._probe_order[self._probe_idx]
            if not self.breakers.for_target(name).refusing:
                return self._members[name]
            self.probe_skips += 1
            note_mutation("gossip.GossipAgent.probe_skips")
        self._probe_idx = (self._probe_idx + 1) % len(self._probe_order)
        return self._members[self._probe_order[self._probe_idx]]

    def _on_probe_retry(
        self, attempt: int, delay_s: float, exc: BaseException
    ) -> None:
        with self._lock:
            self.retried_probes += 1
            note_mutation("gossip.GossipAgent.retried_probes")

    def _probe(self, target: Member, payload: dict) -> None:
        with self._lock:
            self.probes_sent += 1
            note_mutation("gossip.GossipAgent.probes_sent")
        breaker = self.breakers.for_target(target.name)
        try:
            # The shared retry driver owns the in-round retry (decorrelated
            # jitter, instance-seeded so partitioned members desynchronize);
            # the breaker is accounted per probe ROUND, not per attempt —
            # one flaky round trip that recovers on retry is a success.
            response = call_with_retry(
                lambda: self._exchange(target.url, payload),
                policy=self._probe_policy,
                site="gossip.probe",
                on_retry=self._on_probe_retry,
                rng=self._jitter,
                sleep=self._sleep,
            )
        except Exception as e:
            # A failed probe is merely a missed heartbeat refresh: the
            # age-out state machine does the declaring, never one miss.
            breaker.on_failure()
            with self._lock:
                self.probe_failures += 1
                note_mutation("gossip.GossipAgent.probe_failures")
            self.tracer.event(
                "fleet.gossip.probe_failed", member=target.name,
                reason=type(e).__name__,
            )
            return
        breaker.on_success()
        with self._lock:
            self.acks += 1
            note_mutation("gossip.GossipAgent.acks")
        self.merge(response, heard_from=target.name)

    def _exchange(self, url: str, payload: dict) -> dict:
        """One gossip round trip; the injectable seam for tests."""
        faults.fire("gossip.probe", url or "")
        if self._transport is not None:
            return self._transport(url, payload)
        client = self._client(url)
        resp = client.request(
            "POST", "/fleet/gossip",
            body=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            idempotent=False,
        )
        if resp.status != 200:
            raise GossipExchangeError(f"gossip peer answered {resp.status}")
        return json.loads(resp.body)

    def _client(self, url: str):
        from tieredstorage_tpu.storage.httpclient import NO_RETRY, HttpClient

        with self._lock:
            client = self._clients.get(url)
            if client is None:
                client = HttpClient(url, timeout=self.probe_timeout_s, retry=NO_RETRY)
                self._clients[url] = client
        return client

    # ------------------------------------------------------------- the views
    def _view_payload_locked(self) -> dict:
        return {
            "from": self.instance_id,
            "epoch": self.epoch,
            "members": [m.entry() for m in self._members.values()],
        }

    def view_payload(self) -> dict:
        """This agent's full view in wire form (also the ping status body)."""
        with self._lock:
            return self._view_payload_locked()

    def _routing_view_locked(self) -> dict[str, Optional[str]]:
        return {
            m.name: m.url for m in self._members.values() if m.status != DEAD
        }

    def routing_view(self) -> dict[str, Optional[str]]:
        with self._lock:
            return self._routing_view_locked()

    def members(self) -> dict[str, Member]:
        with self._lock:
            return {m.name: dataclasses.replace(m) for m in self._members.values()}

    def count_status(self, status: str) -> int:
        with self._lock:
            return sum(1 for m in self._members.values() if m.status == status)

    def _apply_view_if_changed(self) -> None:
        with self._lock:
            view = self._routing_view_locked()
            if view == self._applied_view:
                return
            self._applied_view = view
            self.epoch += 1
            epoch = self.epoch
        # The router takes its own lock; called outside ours so the lock
        # order stays gossip -> ring with no blocking work under either.
        self._router.set_membership(view, epoch=epoch)
        self.tracer.event(
            "fleet.gossip.view", epoch=epoch, members=len(view),
        )

    # ---------------------------------------------------------------- merges
    def on_gossip(self, payload: Mapping) -> dict:
        """Handle one inbound exchange (POST /fleet/gossip): merge the
        sender's view, treat the contact itself as first-hand liveness
        evidence for the sender, and answer with our full view."""
        if self._stop.is_set():
            # A stopped agent is a member that LEFT: answering here would
            # count as first-hand liveness and keep this instance in every
            # ring forever (keep-alive handler threads outlive a gateway
            # stop, so "closed but still answering" is a real state).
            raise GossipStoppedError("gossip agent is stopped")
        members = payload.get("members")
        if not isinstance(members, list):
            raise ValueError("gossip payload has no members list")
        self.merge(payload, heard_from=payload.get("from"))
        return self.view_payload()

    def merge(self, payload: Mapping, *, heard_from: Optional[str] = None) -> int:
        """Fold a received view into ours by (incarnation, status, heartbeat)
        precedence; returns the number of entries that changed anything.

        `heard_from` names the member we are talking to directly: that is
        first-hand evidence it is alive RIGHT NOW, which revives even a
        locally-DEAD entry (with an incarnation above the dead one, so the
        revival wins the gossip race against the stale obituary)."""
        now = self._now()
        changed = 0
        refuted = False
        with self._lock:
            for entry in payload.get("members", ()):
                try:
                    name = str(entry["name"])
                    inc = int(entry["incarnation"])
                    status = str(entry["status"])
                    heartbeat = int(entry.get("heartbeat", 0))
                except (KeyError, TypeError, ValueError):
                    continue  # one malformed entry must not poison the view
                if status not in _STATUS_RANK:
                    continue
                url = entry.get("url") or None
                if name == self.instance_id:
                    me = self._members[self.instance_id]
                    if status != ALIVE and inc >= me.incarnation:
                        # Someone is spreading my obituary: refute it with a
                        # higher incarnation (SWIM §4.2); the next exchanges
                        # spread alive@inc+1 which beats suspect/dead@inc.
                        me.incarnation = inc + 1
                        self.refutations += 1
                        note_mutation("gossip.GossipAgent.refutations")
                        refuted = True
                        changed += 1
                    continue
                known = self._members.get(name)
                if known is None:
                    self._members[name] = Member(
                        name=name, url=url, incarnation=inc, status=status,
                        heartbeat=heartbeat,
                        last_heard=now,
                        suspected_at=now if status == SUSPECT else 0.0,
                    )
                    changed += 1
                    continue
                if url is not None and known.url != url:
                    known.url = url
                    changed += 1
                if _fresher(
                    inc, heartbeat, status,
                    known.incarnation, known.heartbeat, known.status,
                ):
                    # An incarnation advance restarts the member's heartbeat
                    # sequence (a rejoin after kill -9 starts from 0), so
                    # the winning entry's heartbeat replaces — never maxes
                    # with — the old one. A winning ALIVE that advanced
                    # (incarnation, heartbeat) is liveness evidence no
                    # matter how many hops it travelled.
                    if status == ALIVE and (inc, heartbeat) > (
                        known.incarnation, known.heartbeat
                    ):
                        known.last_heard = now
                        known.suspected_at = 0.0
                    elif status == SUSPECT and known.status != SUSPECT:
                        known.suspected_at = now
                    known.incarnation = inc
                    known.heartbeat = heartbeat
                    known.status = status
                    changed += 1
            if heard_from and heard_from != self.instance_id:
                direct = self._members.get(heard_from)
                if direct is not None:
                    direct.last_heard = now
                    if direct.status == DEAD:
                        # First-hand contact with a "dead" member: it is
                        # back (kill -9 + restart); give it an incarnation
                        # that outranks its obituary.
                        direct.incarnation = direct.incarnation + 1
                        changed += 1
                    if direct.status != ALIVE:
                        direct.status = ALIVE
                        direct.suspected_at = 0.0
                        changed += 1
            if changed:
                self.deltas_applied += changed
                note_mutation("gossip.GossipAgent._members")
        if refuted:
            self.tracer.event("fleet.gossip.refuted", member=self.instance_id)
        if changed:
            self._apply_view_if_changed()
        return changed


class GossipExchangeError(RuntimeError):
    """A gossip probe round trip failed at the HTTP layer."""


class GossipStoppedError(RuntimeError):
    """An inbound exchange reached an agent that has already stopped."""
