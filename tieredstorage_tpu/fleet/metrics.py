"""Fleet-mode observability: ring ownership, peer tier, coalescing, forwards.

Group ``fleet-metrics``, same conventions as the resilience gauges
(metrics/rsm_metrics.py): components keep plain counters, this module
publishes them as supplier gauges, plus one latency family —
``fleet-forward-time`` avg/max with the log-scale ``fleet-forward-time-ms``
histogram — fed through ``FleetMetrics.record_forward`` (wired as
``PeerChunkCache.on_forward`` by the RSM).
"""

from __future__ import annotations

from tieredstorage_tpu.metrics.core import (
    Avg,
    Histogram,
    Max,
    MetricName,
    MetricsRegistry,
)

FLEET_METRIC_GROUP = "fleet-metrics"


class FleetMetrics:
    """Recorder for the forward-latency family (the only non-gauge)."""

    def __init__(self, registry: MetricsRegistry) -> None:
        self.registry = registry

    def record_forward(self, ms: float) -> None:
        """One completed peer forward (request out -> framed chunks in)."""
        self.registry.sensor("fleet-forward-time").ensure_stats(lambda: [
            (MetricName.of("fleet-forward-time-avg", FLEET_METRIC_GROUP), Avg()),
            (MetricName.of("fleet-forward-time-max", FLEET_METRIC_GROUP), Max()),
            (
                MetricName.of(
                    "fleet-forward-time-ms", FLEET_METRIC_GROUP,
                    "peer forward latency histogram (ms, log-scale buckets)",
                ),
                Histogram(),
            ),
        ]).record(ms)


def register_fleet_metrics(
    registry: MetricsRegistry,
    *,
    router=None,
    peer_cache=None,
    gossip=None,
) -> None:
    """Publish fleet counters as gauges (group ``fleet-metrics``)."""

    def gauge(name: str, supplier, description: str = "", tags=None) -> None:
        registry.add_gauge(
            MetricName.of(name, FLEET_METRIC_GROUP, description, tags=tags or {}),
            supplier,
        )

    if router is not None:
        gauge("fleet-instances", lambda: float(len(router.instances)),
              "Fleet members in the current consistent-hash ring")
        gauge("fleet-vnodes", lambda: float(router.vnodes),
              "Virtual nodes per instance on the ring")
        gauge("fleet-membership-generation", lambda: float(router.generation),
              "Membership changes applied (starts at 1)")
        gauge("fleet-view-epoch", lambda: float(router.view_epoch),
              "Epoch of the last gossip-agreed membership view applied "
              "(0 = static membership only)")
        gauge(
            "fleet-local-ownership",
            lambda: float(router.local_ownership_fraction()),
            "Fraction of the hash circle owned by this instance (~1/N)",
        )
    if gossip is not None:
        from tieredstorage_tpu.fleet.gossip import ALIVE, DEAD, SUSPECT

        gauge("fleet-members-alive", lambda: float(gossip.count_status(ALIVE)),
              "Members the gossip view currently believes alive")
        gauge("fleet-members-suspect", lambda: float(gossip.count_status(SUSPECT)),
              "Members under unrefuted suspicion (still in the ring)")
        gauge("fleet-members-dead", lambda: float(gossip.count_status(DEAD)),
              "Members declared dead and removed from the ring")
        gauge("fleet-gossip-periods-total", lambda: float(gossip.periods),
              "Gossip protocol periods run")
        gauge("fleet-gossip-probes-total", lambda: float(gossip.probes_sent),
              "Gossip probes sent (one per period with a live target)")
        gauge("fleet-gossip-acks-total", lambda: float(gossip.acks),
              "Gossip probes answered (response view merged)")
        gauge(
            "fleet-gossip-probe-failures-total",
            lambda: float(gossip.probe_failures),
            "Gossip probes that failed in transport (missed heartbeat)",
        )
        gauge("fleet-gossip-refutations-total", lambda: float(gossip.refutations),
              "Times this member refuted its own suspicion/obituary with "
              "an incarnation bump")
        gauge("fleet-gossip-deltas-total", lambda: float(gossip.deltas_applied),
              "Membership delta entries merged from received views")
        gauge(
            "fleet-gossip-probe-skips-total",
            lambda: float(gossip.probe_skips),
            "Probe candidates skipped because their breaker was refusing "
            "(deprioritized, not silenced)",
        )
        gauge(
            "fleet-gossip-retried-probes-total",
            lambda: float(gossip.retried_probes),
            "Probe round trips that needed at least one jittered retry",
        )
    if peer_cache is not None:
        gauge("fleet-replication-factor", lambda: float(peer_cache.replication),
              "Replica owners per segment key (ring successors tried in "
              "order on a non-owner miss)")
        gauge("fleet-forwards-total", lambda: float(peer_cache.forwards),
              "Chunk windows forwarded to their owner instance")
        gauge("fleet-peer-hits-total", lambda: float(peer_cache.peer_hits),
              "Forwards answered by the owner's chunk tier")
        gauge("fleet-failover-hits-total", lambda: float(peer_cache.failover_hits),
              "Forwards answered by a non-first replica owner (failover)")
        gauge("fleet-peer-misses-total", lambda: float(peer_cache.peer_misses),
              "Forwards the owner could not serve (local fallback)")
        gauge(
            "fleet-forward-failures-total",
            lambda: float(peer_cache.forward_failures),
            "Forwards that failed in transport (peer marked down)",
        )
        gauge("fleet-peers-down", lambda: float(peer_cache.peers_down),
              "Peers currently in the down cooldown")
        flight = peer_cache.singleflight
        gauge(
            "fleet-singleflight-leaders-total",
            lambda: float(flight.leaders),
            "Chunk windows actually resolved (one forward or backend read)",
        )
        gauge(
            "fleet-coalesced-fetches-total",
            lambda: float(flight.coalesced),
            "Concurrent duplicate fetches served by a leader's single "
            "resolve",
        )
        gauge(
            "fleet-singleflight-failures-total",
            lambda: float(flight.failures),
            "Flights whose leader failed (error shared by all joiners)",
        )
