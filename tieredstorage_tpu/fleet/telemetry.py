"""Fleet-wide telemetry: one scrape over the whole gossip membership view.

A 3-instance fleet (fleet/ring.py + fleet/gossip.py) exports three separate
metric registries; operators (and the load harness) need the FLEET answer:
total backend fetches, total peer hits, the worst breaker state anywhere.
This module aggregates every member's metric samples — fetched over the
shim-wire gateway's ``GET /fleet/telemetry`` route, membership taken from
the live routing view — into one fleet-wide scrape with explicit per-stat
merge semantics, and stitches ONE request's records from every member that
touched it into a causally-ordered fleet timeline (``assemble_trace``,
ISSUE 17: origin record, peer ``/chunk`` serves, failover hops, and the
merged device launches that served them, joined on ``gcm.batch:<id>``
stage markers). Per-stat merge semantics:

- **histogram-merge**: per-bound cumulative bucket counts, ``sum`` and
  ``count`` are summed across members (all histograms share the log-scale
  ladder of metrics/core.py, so bounds line up by construction; a member
  with a foreign ladder contributes its buckets under their own bounds);
- **max**: names ending ``-state``/``-max`` (worst breaker state anywhere
  IS the fleet's breaker state; the fleet max latency is the max of maxes);
- **min**: names ending ``-min``;
- **sum** (default): totals, rates, gauges of countable things — sharded
  instances partition the work, so the fleet value is the sum of parts.

The local member never scrapes itself over HTTP (its registries are read
in-process), unreachable members are reported as such rather than failing
the scrape (telemetry must degrade, not gate availability), and the
gossip/ping counters every member already serves are folded in as the
``fleet-ping`` pseudo-group so failover and forward totals appear in the
same view.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Iterable, Mapping, Optional

from tieredstorage_tpu.metrics.core import Histogram, MetricsRegistry
from tieredstorage_tpu.utils.locks import new_lock, note_mutation

#: Merge rules by metric-name suffix; first match wins, default is "sum".
_SUFFIX_AGGREGATIONS: tuple[tuple[str, str], ...] = (
    ("-state", "max"),
    ("-max", "max"),
    ("-min", "min"),
)


def aggregation_of(name: str) -> str:
    """The merge semantic for a (non-histogram) stat name."""
    for suffix, agg in _SUFFIX_AGGREGATIONS:
        if name.endswith(suffix):
            return agg
    return "sum"


def _le_repr(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else f"{bound:g}"


def export_samples(registries: Iterable[MetricsRegistry]) -> list[dict]:
    """One member's registries as JSON-safe samples (the
    ``GET /fleet/telemetry`` payload body). A failing supplier gauge must
    not fail the scrape; skipped gauges are counted VISIBLY as the
    ``telemetry-skipped-gauges-total`` sample (swallowed-exception
    checker: the failure has a metric, not silence)."""
    samples: list[dict] = []
    seen: set[str] = set()
    skipped_gauges = 0
    for registry in registries:
        for metric_name in registry.metric_names:
            try:
                stat = registry.stat(metric_name)
            except KeyError:
                continue  # unregistered between listing and read
            key = str(metric_name)
            if key in seen:
                continue  # identical series in another registry
            seen.add(key)
            base = {
                "group": metric_name.group,
                "name": metric_name.name,
                "tags": dict(metric_name.tags),
            }
            if isinstance(stat, Histogram):
                samples.append({
                    **base,
                    "kind": "histogram",
                    "buckets": [
                        [_le_repr(bound), count]
                        for bound, count in stat.buckets()
                    ],
                    "sum": stat.sum,
                    "count": stat.count,
                })
                continue
            try:
                value = float(registry.value(metric_name))
            except Exception:
                skipped_gauges += 1
                continue
            samples.append({**base, "kind": "value", "value": value})
    if skipped_gauges:
        samples.append({
            "group": "fleet-telemetry", "name": "telemetry-skipped-gauges-total",
            "tags": {}, "kind": "value", "value": float(skipped_gauges),
        })
    return samples


def _series_key(sample: Mapping) -> str:
    tags = ",".join(f"{k}={v}" for k, v in sorted(sample["tags"].items()))
    return f"{sample['group']}:{sample['name']}" + (f"{{{tags}}}" if tags else "")


def merge_samples(member_samples: Mapping[str, list[dict]]) -> dict[str, dict]:
    """Merge ``{member: [samples]}`` into ``{series key: merged stat}``.

    Each merged entry records its ``aggregation`` and the ``members`` that
    contributed, so a dashboard (or a test) can audit which semantic
    produced every number."""
    merged: dict[str, dict] = {}
    for member in sorted(member_samples):
        for sample in member_samples[member]:
            key = _series_key(sample)
            if sample["kind"] == "histogram":
                entry = merged.setdefault(key, {
                    "kind": "histogram",
                    "aggregation": "histogram-merge",
                    "buckets": {},
                    "sum": 0.0,
                    "count": 0,
                    "members": [],
                })
                if entry["kind"] != "histogram":
                    continue  # kind clash: first kind wins, audit via members
                buckets = entry["buckets"]
                for le, count in sample["buckets"]:
                    buckets[le] = buckets.get(le, 0) + count
                entry["sum"] += sample["sum"]
                entry["count"] += sample["count"]
                entry["members"].append(member)
                continue
            agg = aggregation_of(sample["name"])
            entry = merged.setdefault(key, {
                "kind": "value",
                "aggregation": agg,
                "value": None,
                "members": [],
            })
            if entry["kind"] != "value":
                continue
            value = sample["value"]
            if entry["value"] is None:
                entry["value"] = value
            elif agg == "max":
                entry["value"] = max(entry["value"], value)
            elif agg == "min":
                entry["value"] = min(entry["value"], value)
            else:
                entry["value"] += value
            entry["members"].append(member)
    return merged


class FleetTelemetry:
    """Aggregates the membership view's telemetry into one fleet scrape.

    ``router`` supplies the live membership (name -> gateway base URL;
    None = this instance / address unknown). ``transport(url)`` fetches a
    peer's ``GET /fleet/telemetry`` payload and exists as a seam for tests;
    the default uses the bounded-pool HTTP client with a single attempt —
    telemetry is an observer, a struggling peer must not absorb retries."""

    def __init__(
        self,
        registries: Iterable[MetricsRegistry],
        *,
        instance_id: str = "local",
        router=None,
        ping: Optional[Callable[[], dict]] = None,
        transport: Optional[Callable[[str], dict]] = None,
        timeout_s: float = 2.0,
        time_source: Callable[[], float] = time.monotonic,
        flight_recorder=None,
        timeline=None,
        fetch_json: Optional[Callable[[str, str], Optional[dict]]] = None,
    ) -> None:
        self._registries = list(registries)
        self.instance_id = instance_id
        self._router = router
        self._ping = ping
        self._transport = transport
        self.timeout_s = timeout_s
        self._now = time_source
        #: Local evidence sources for assemble_trace (ISSUE 17): this
        #: member's flight ring and device-scheduler timeline are read
        #: in-process, peers over their debug routes.
        self._flight_recorder = flight_recorder
        self._timeline = timeline
        #: Seam for tests: ``fetch_json(url, path)`` returns the decoded
        #: JSON payload, None on 404 (absence, not failure), raises
        #: otherwise. Default uses the cached bounded HTTP clients.
        self._fetch_json = fetch_json
        self._lock = new_lock("telemetry.FleetTelemetry._lock")
        self._clients: dict[str, object] = {}
        #: Fleet scrapes served (exported in the scrape payload itself).
        self.scrapes = 0
        self.peer_scrape_failures = 0

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for client in clients:
            client.close()

    # ---------------------------------------------------------------- local
    def local_payload(self) -> dict:
        """This member's contribution (served on GET /fleet/telemetry)."""
        samples = export_samples(self._registries)
        if self._ping is not None:
            try:
                ping = self._ping()
            except Exception:
                ping = {}
            samples.extend(self._ping_samples(ping))
        return {"instance": self.instance_id, "samples": samples}

    @staticmethod
    def _ping_samples(ping: Mapping) -> list[dict]:
        """Flatten the numeric /fleet/ping counters (peer-cache forwards,
        failover hits, gossip periods) into the ``fleet-ping`` pseudo-group
        so they merge like any other stat."""
        out: list[dict] = []

        def emit(name: str, value) -> None:
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                return
            out.append({
                "group": "fleet-ping", "name": name, "tags": {},
                "kind": "value", "value": float(value),
            })

        for name, value in ping.items():
            if isinstance(value, Mapping):
                if name in ("peer_cache",):
                    for sub, sub_value in value.items():
                        emit(f"{name}-{sub.replace('_', '-')}-total", sub_value)
            else:
                emit(name.replace("_", "-"), value)
        return out

    # ---------------------------------------------------------------- fleet
    def _members(self) -> dict[str, Optional[str]]:
        if self._router is None:
            return {self.instance_id: None}
        return dict(self._router.peers)

    def _fetch_peer(self, url: str) -> dict:
        if self._transport is not None:
            return self._transport(url)
        import json

        from tieredstorage_tpu.storage.httpclient import NO_RETRY, HttpClient

        with self._lock:
            client = self._clients.get(url)
            if client is None:
                client = HttpClient(url, timeout=self.timeout_s, retry=NO_RETRY)
                self._clients[url] = client
        resp = client.request("GET", "/fleet/telemetry")
        if resp.status != 200:
            raise RuntimeError(f"peer telemetry returned {resp.status}")
        payload = json.loads(resp.body)
        if not isinstance(payload, dict) or "samples" not in payload:
            raise RuntimeError("peer telemetry payload malformed")
        return payload

    def scrape(self) -> dict:
        """One fleet-wide scrape: local registries in-process, every other
        member over its gateway, merged with the per-stat semantics above.
        Unreachable members degrade to ``reachable: false`` entries AND
        are listed as explicit ``(member, reason)`` pairs in
        ``unreachable`` — a dead gateway must be diagnosable from the
        scrape artifact alone (ISSUE 17), not by diffing the member map
        against an expected roster."""
        members = self._members()
        per_member: dict[str, list[dict]] = {}
        status: dict[str, dict] = {}
        unreachable: list[list[str]] = []
        for name, url in sorted(members.items()):
            if name == self.instance_id or url is None:
                payload = self.local_payload()
                per_member[name] = payload["samples"]
                status[name] = {
                    "reachable": True, "local": True,
                    "samples": len(payload["samples"]),
                }
                continue
            try:
                payload = self._fetch_peer(url)
            except Exception as e:  # noqa: BLE001 — degrade, never gate
                with self._lock:
                    self.peer_scrape_failures += 1
                    note_mutation(
                        "telemetry.FleetTelemetry.peer_scrape_failures"
                    )
                reason = f"{type(e).__name__}: {e}"
                status[name] = {
                    "reachable": False, "local": False, "error": reason,
                }
                unreachable.append([name, reason])
                continue
            per_member[name] = payload.get("samples", [])
            status[name] = {
                "reachable": True, "local": False,
                "samples": len(per_member[name]),
            }
        with self._lock:
            self.scrapes += 1
            note_mutation("telemetry.FleetTelemetry.scrapes")
            scrapes = self.scrapes
        return {
            "instance": self.instance_id,
            "scrapes": scrapes,
            "members": status,
            "unreachable": unreachable,
            "fleet": merge_samples(per_member),
        }

    # ------------------------------------------------------------- stitching
    def _get_json(self, url: str, path: str) -> Optional[dict]:
        """GET a peer debug route: decoded JSON on 200, None on 404 (the
        route is disabled or holds nothing — absence, not failure), raises
        on anything else. Reuses the cached single-attempt clients."""
        if self._fetch_json is not None:
            return self._fetch_json(url, path)
        import json

        from tieredstorage_tpu.storage.httpclient import NO_RETRY, HttpClient

        with self._lock:
            client = self._clients.get(url)
            if client is None:
                client = HttpClient(url, timeout=self.timeout_s, retry=NO_RETRY)
                self._clients[url] = client
        resp = client.request("GET", path)
        if resp.status == 404:
            return None
        if resp.status != 200:
            raise RuntimeError(f"peer {path} returned {resp.status}")
        return json.loads(resp.body)

    def assemble_trace(self, trace_id: str) -> dict:
        """One request's FLEET-WIDE timeline (ISSUE 17): query every live
        member's flight ring for records carrying ``trace_id`` (they share
        it via the W3C traceparent the forward/failover hops propagate),
        pull the scheduler timeline of every member that served a leg, and
        stitch origin, peer ``/chunk`` serves, and device launches into one
        causally-ordered, Perfetto-exportable trace.

        Clock-skew tolerance: the ``ordered`` list is derived from hop
        EDGES (an origin's forward created each peer serve, so the origin
        precedes every serve), never from comparing wall clocks across
        members; raw timestamps are used only to RENDER each member's own
        slices on its own clock (pinned to the wall axis by that member's
        exported epoch). Unreachable members degrade to ``(member,
        reason)`` pairs, like ``scrape``."""
        if not trace_id:
            raise ValueError("trace_id must be non-empty")
        from urllib.parse import quote

        members = self._members()
        instances: dict[str, dict] = {}
        unreachable: list[list[str]] = []
        trace_path = "/debug/requests?trace=" + quote(trace_id, safe="")
        for name, url in sorted(members.items()):
            if name == self.instance_id or url is None:
                records: list[dict] = []
                recorder = self._flight_recorder
                if recorder is not None and recorder.enabled:
                    records = [
                        r.to_dict() for r in recorder.find_all(trace_id)
                    ]
                launches: list[dict] = []
                epoch = None
                timeline = self._timeline
                if timeline is not None and timeline.enabled:
                    launches = timeline.events()
                    epoch = timeline.epoch()
                instances[name] = {
                    "local": True, "records": records,
                    "launches": launches, "epoch": epoch,
                }
                continue
            try:
                payload = self._get_json(url, trace_path)
            except Exception as e:  # noqa: BLE001 — degrade, never gate
                unreachable.append([name, f"{type(e).__name__}: {e}"])
                continue
            records = (payload or {}).get("slowest", [])
            launches, epoch = [], None
            if records:
                try:
                    tl_payload = self._get_json(url, "/debug/timeline")
                except Exception:  # noqa: BLE001 — launches are enrichment
                    tl_payload = None
                if tl_payload:
                    launches = tl_payload.get("events", [])
                    epoch = tl_payload.get("epoch")
            instances[name] = {
                "local": False, "records": records,
                "launches": launches, "epoch": epoch,
            }
        return stitch_trace(trace_id, instances, unreachable)


def stitch_trace(
    trace_id: str,
    instances: Mapping[str, Mapping],
    unreachable: Iterable[Iterable[str]] = (),
) -> dict:
    """Pure stitcher over per-member evidence (``{name: {records,
    launches, epoch, local}}`` — record dicts in ``RequestRecord.to_dict``
    shape, launches in the timeline ring's event shape).

    - ``ordered``: the causal record order — origin records (anything that
      is not a peer ``gateway.chunk`` serve) strictly before the serves
      they fanned out to, serves deterministic by (instance, duration);
      hop edges are listed explicitly so the order is auditable.
    - ``flow_edges``: every ``gcm.batch:<id>`` stage marker resolved
      against the SAME member's retained launches — a request joined to
      the merged device launch that served it.
    - ``chrome_trace``: one Perfetto-loadable event list, one pid per
      member (process_name metadata), flows scoped per member."""
    from tieredstorage_tpu.metrics import timeline as timeline_mod

    ordered: list[dict] = []
    hop_edges: list[dict] = []
    flow_edges: list[dict] = []
    events: list[dict] = []
    origins: list[dict] = []
    serves: list[dict] = []
    span_instances: list[str] = []

    for idx, name in enumerate(sorted(instances)):
        member = instances[name]
        records = list(member.get("records", ()))
        launches = list(member.get("launches", ()))
        if records:
            span_instances.append(name)
        launch_by_id = {
            ev["batch_id"]: ev for ev in launches if ev.get("kind") == "flush"
        }
        for rec in records:
            batches = timeline_mod.batch_ids_of(rec)
            entry = {
                "instance": name,
                "name": rec.get("name", "request"),
                "trace_id": rec.get("trace_id", trace_id),
                "duration_ms": rec.get("duration_ms", 0.0),
                "error": rec.get("error"),
                "batches": batches,
            }
            if rec.get("name") == "gateway.chunk":
                entry["role"] = "peer-serve"
                serves.append(entry)
            else:
                entry["role"] = "origin"
                origins.append(entry)
            for batch_id in batches:
                launch = launch_by_id.get(batch_id)
                if launch is not None:
                    flow_edges.append({
                        "instance": name,
                        "batch_id": batch_id,
                        "work_class": launch.get("work_class"),
                        "occupancy": launch.get("occupancy"),
                        "record": entry["name"],
                    })
        epoch = member.get("epoch") or {"wall_s": 0.0, "mono_s": 0.0}
        events.extend(timeline_mod.chrome_trace_events(
            launches, records, pid=idx + 1, epoch=epoch, instance=name,
        ))

    # Causal order from hop edges, never raw cross-member clocks: the
    # origin's forward CREATED each peer serve, so origin precedes all.
    origins.sort(key=lambda e: e["instance"])
    serves.sort(key=lambda e: (e["instance"], -float(e["duration_ms"])))
    ordered = origins + serves
    for origin in origins:
        for serve in serves:
            hop_edges.append({
                "from": origin["instance"], "to": serve["instance"],
                "kind": "peer-chunk-serve",
            })

    return {
        "trace_id": trace_id,
        "instances": {
            name: {
                "local": bool(member.get("local")),
                "records": list(member.get("records", ())),
                "launches_retained": len(list(member.get("launches", ()))),
            }
            for name, member in instances.items()
        },
        "span_instances": span_instances,
        "ordered": ordered,
        "hop_edges": hop_edges,
        "flow_edges": flow_edges,
        "unreachable": [list(pair) for pair in unreachable],
        "chrome_trace": {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace_id},
        },
    }
