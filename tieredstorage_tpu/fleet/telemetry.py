"""Fleet-wide telemetry: one scrape over the whole gossip membership view.

A 3-instance fleet (fleet/ring.py + fleet/gossip.py) exports three separate
metric registries; operators (and the load harness) need the FLEET answer:
total backend fetches, total peer hits, the worst breaker state anywhere.
This module aggregates every member's metric samples — fetched over the
shim-wire gateway's ``GET /fleet/telemetry`` route, membership taken from
the live routing view — into one fleet-wide scrape with explicit per-stat
merge semantics:

- **histogram-merge**: per-bound cumulative bucket counts, ``sum`` and
  ``count`` are summed across members (all histograms share the log-scale
  ladder of metrics/core.py, so bounds line up by construction; a member
  with a foreign ladder contributes its buckets under their own bounds);
- **max**: names ending ``-state``/``-max`` (worst breaker state anywhere
  IS the fleet's breaker state; the fleet max latency is the max of maxes);
- **min**: names ending ``-min``;
- **sum** (default): totals, rates, gauges of countable things — sharded
  instances partition the work, so the fleet value is the sum of parts.

The local member never scrapes itself over HTTP (its registries are read
in-process), unreachable members are reported as such rather than failing
the scrape (telemetry must degrade, not gate availability), and the
gossip/ping counters every member already serves are folded in as the
``fleet-ping`` pseudo-group so failover and forward totals appear in the
same view.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Iterable, Mapping, Optional

from tieredstorage_tpu.metrics.core import Histogram, MetricsRegistry
from tieredstorage_tpu.utils.locks import new_lock, note_mutation

#: Merge rules by metric-name suffix; first match wins, default is "sum".
_SUFFIX_AGGREGATIONS: tuple[tuple[str, str], ...] = (
    ("-state", "max"),
    ("-max", "max"),
    ("-min", "min"),
)


def aggregation_of(name: str) -> str:
    """The merge semantic for a (non-histogram) stat name."""
    for suffix, agg in _SUFFIX_AGGREGATIONS:
        if name.endswith(suffix):
            return agg
    return "sum"


def _le_repr(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else f"{bound:g}"


def export_samples(registries: Iterable[MetricsRegistry]) -> list[dict]:
    """One member's registries as JSON-safe samples (the
    ``GET /fleet/telemetry`` payload body). A failing supplier gauge must
    not fail the scrape; skipped gauges are counted VISIBLY as the
    ``telemetry-skipped-gauges-total`` sample (swallowed-exception
    checker: the failure has a metric, not silence)."""
    samples: list[dict] = []
    seen: set[str] = set()
    skipped_gauges = 0
    for registry in registries:
        for metric_name in registry.metric_names:
            try:
                stat = registry.stat(metric_name)
            except KeyError:
                continue  # unregistered between listing and read
            key = str(metric_name)
            if key in seen:
                continue  # identical series in another registry
            seen.add(key)
            base = {
                "group": metric_name.group,
                "name": metric_name.name,
                "tags": dict(metric_name.tags),
            }
            if isinstance(stat, Histogram):
                samples.append({
                    **base,
                    "kind": "histogram",
                    "buckets": [
                        [_le_repr(bound), count]
                        for bound, count in stat.buckets()
                    ],
                    "sum": stat.sum,
                    "count": stat.count,
                })
                continue
            try:
                value = float(registry.value(metric_name))
            except Exception:
                skipped_gauges += 1
                continue
            samples.append({**base, "kind": "value", "value": value})
    if skipped_gauges:
        samples.append({
            "group": "fleet-telemetry", "name": "telemetry-skipped-gauges-total",
            "tags": {}, "kind": "value", "value": float(skipped_gauges),
        })
    return samples


def _series_key(sample: Mapping) -> str:
    tags = ",".join(f"{k}={v}" for k, v in sorted(sample["tags"].items()))
    return f"{sample['group']}:{sample['name']}" + (f"{{{tags}}}" if tags else "")


def merge_samples(member_samples: Mapping[str, list[dict]]) -> dict[str, dict]:
    """Merge ``{member: [samples]}`` into ``{series key: merged stat}``.

    Each merged entry records its ``aggregation`` and the ``members`` that
    contributed, so a dashboard (or a test) can audit which semantic
    produced every number."""
    merged: dict[str, dict] = {}
    for member in sorted(member_samples):
        for sample in member_samples[member]:
            key = _series_key(sample)
            if sample["kind"] == "histogram":
                entry = merged.setdefault(key, {
                    "kind": "histogram",
                    "aggregation": "histogram-merge",
                    "buckets": {},
                    "sum": 0.0,
                    "count": 0,
                    "members": [],
                })
                if entry["kind"] != "histogram":
                    continue  # kind clash: first kind wins, audit via members
                buckets = entry["buckets"]
                for le, count in sample["buckets"]:
                    buckets[le] = buckets.get(le, 0) + count
                entry["sum"] += sample["sum"]
                entry["count"] += sample["count"]
                entry["members"].append(member)
                continue
            agg = aggregation_of(sample["name"])
            entry = merged.setdefault(key, {
                "kind": "value",
                "aggregation": agg,
                "value": None,
                "members": [],
            })
            if entry["kind"] != "value":
                continue
            value = sample["value"]
            if entry["value"] is None:
                entry["value"] = value
            elif agg == "max":
                entry["value"] = max(entry["value"], value)
            elif agg == "min":
                entry["value"] = min(entry["value"], value)
            else:
                entry["value"] += value
            entry["members"].append(member)
    return merged


class FleetTelemetry:
    """Aggregates the membership view's telemetry into one fleet scrape.

    ``router`` supplies the live membership (name -> gateway base URL;
    None = this instance / address unknown). ``transport(url)`` fetches a
    peer's ``GET /fleet/telemetry`` payload and exists as a seam for tests;
    the default uses the bounded-pool HTTP client with a single attempt —
    telemetry is an observer, a struggling peer must not absorb retries."""

    def __init__(
        self,
        registries: Iterable[MetricsRegistry],
        *,
        instance_id: str = "local",
        router=None,
        ping: Optional[Callable[[], dict]] = None,
        transport: Optional[Callable[[str], dict]] = None,
        timeout_s: float = 2.0,
        time_source: Callable[[], float] = time.monotonic,
    ) -> None:
        self._registries = list(registries)
        self.instance_id = instance_id
        self._router = router
        self._ping = ping
        self._transport = transport
        self.timeout_s = timeout_s
        self._now = time_source
        self._lock = new_lock("telemetry.FleetTelemetry._lock")
        self._clients: dict[str, object] = {}
        #: Fleet scrapes served (exported in the scrape payload itself).
        self.scrapes = 0
        self.peer_scrape_failures = 0

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for client in clients:
            client.close()

    # ---------------------------------------------------------------- local
    def local_payload(self) -> dict:
        """This member's contribution (served on GET /fleet/telemetry)."""
        samples = export_samples(self._registries)
        if self._ping is not None:
            try:
                ping = self._ping()
            except Exception:
                ping = {}
            samples.extend(self._ping_samples(ping))
        return {"instance": self.instance_id, "samples": samples}

    @staticmethod
    def _ping_samples(ping: Mapping) -> list[dict]:
        """Flatten the numeric /fleet/ping counters (peer-cache forwards,
        failover hits, gossip periods) into the ``fleet-ping`` pseudo-group
        so they merge like any other stat."""
        out: list[dict] = []

        def emit(name: str, value) -> None:
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                return
            out.append({
                "group": "fleet-ping", "name": name, "tags": {},
                "kind": "value", "value": float(value),
            })

        for name, value in ping.items():
            if isinstance(value, Mapping):
                if name in ("peer_cache",):
                    for sub, sub_value in value.items():
                        emit(f"{name}-{sub.replace('_', '-')}-total", sub_value)
            else:
                emit(name.replace("_", "-"), value)
        return out

    # ---------------------------------------------------------------- fleet
    def _members(self) -> dict[str, Optional[str]]:
        if self._router is None:
            return {self.instance_id: None}
        return dict(self._router.peers)

    def _fetch_peer(self, url: str) -> dict:
        if self._transport is not None:
            return self._transport(url)
        import json

        from tieredstorage_tpu.storage.httpclient import NO_RETRY, HttpClient

        with self._lock:
            client = self._clients.get(url)
            if client is None:
                client = HttpClient(url, timeout=self.timeout_s, retry=NO_RETRY)
                self._clients[url] = client
        resp = client.request("GET", "/fleet/telemetry")
        if resp.status != 200:
            raise RuntimeError(f"peer telemetry returned {resp.status}")
        payload = json.loads(resp.body)
        if not isinstance(payload, dict) or "samples" not in payload:
            raise RuntimeError("peer telemetry payload malformed")
        return payload

    def scrape(self) -> dict:
        """One fleet-wide scrape: local registries in-process, every other
        member over its gateway, merged with the per-stat semantics above.
        Unreachable members degrade to ``reachable: false`` entries."""
        members = self._members()
        per_member: dict[str, list[dict]] = {}
        status: dict[str, dict] = {}
        for name, url in sorted(members.items()):
            if name == self.instance_id or url is None:
                payload = self.local_payload()
                per_member[name] = payload["samples"]
                status[name] = {
                    "reachable": True, "local": True,
                    "samples": len(payload["samples"]),
                }
                continue
            try:
                payload = self._fetch_peer(url)
            except Exception as e:  # noqa: BLE001 — degrade, never gate
                with self._lock:
                    self.peer_scrape_failures += 1
                    note_mutation(
                        "telemetry.FleetTelemetry.peer_scrape_failures"
                    )
                status[name] = {
                    "reachable": False, "local": False,
                    "error": f"{type(e).__name__}: {e}",
                }
                continue
            per_member[name] = payload.get("samples", [])
            status[name] = {
                "reachable": True, "local": False,
                "samples": len(per_member[name]),
            }
        with self._lock:
            self.scrapes += 1
            note_mutation("telemetry.FleetTelemetry.scrapes")
            scrapes = self.scrapes
        return {
            "instance": self.instance_id,
            "scrapes": scrapes,
            "members": status,
            "fleet": merge_samples(per_member),
        }
