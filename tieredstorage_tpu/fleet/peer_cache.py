"""Peer chunk cache tier: ask the owning sibling before remote storage.

The memcache-at-Facebook shape (Nishtala et al., NSDI '13): consistent-hash
routing (fleet/ring.py) concentrates each segment's chunks in exactly one
instance's chunk cache, so a non-owner resolves a miss with ONE cheap hop to
the owner instead of a remote-storage ranged GET + detransform. The owner
serves the forwarded window through its own full chunk path (local cache,
then single-flight backend fetch), so a fleet-wide thundering herd on a hot
chunk still causes exactly one backend read — the owner's.

Layering (owner and non-owner identical):

    ChunkCache (local, per-instance)
      -> PeerChunkCache (this module: route -> forward | local)
        -> SingleFlight -> DefaultChunkManager -> remote storage

Failure semantics: forwarding is an OPTIMIZATION, never a dependency. Peer
health is a per-owner circuit breaker (utils/retry.BreakerBoard, ISSUE 19 —
this replaced a bespoke down-cooldown dict): a forward that fails
(connect/timeout/5xx/torn frame) counts a breaker failure, and after
``breaker.peer.failure.threshold`` consecutive failures (default 1) the
owner's breaker opens for ``fleet.peer.down.cooldown.ms`` — reads skip it
and fall back to the next owner / the local backend path, byte-identical
result, no error. After the cooldown the breaker goes half-open and admits
exactly ONE probing forward (concurrent readers keep falling back instead
of stampeding a recovering peer); success closes it. A 404 from the owner
(object unknown there) is a contract answer from a healthy peer — breaker
success — and falls back so the authoritative error comes from this
instance's own storage stack. Forwards propagate the ambient Deadline
(``x-deadline-ms``) and trace context (``traceparent``), and the wire is
the existing shim-wire gateway (``GET /chunk``) — no new listener, no new
protocol. The ``peer.forward`` fault-injection seam (utils/faults.py)
fires per forward attempt, before the wire.
"""

from __future__ import annotations

import contextlib
import io
import struct
import time
from typing import BinaryIO, Optional, Sequence
from urllib.parse import quote

from tieredstorage_tpu.fetch.chunk_manager import ChunkManager
from tieredstorage_tpu.fleet.ring import FleetRouter
from tieredstorage_tpu.fleet.singleflight import SingleFlight
from tieredstorage_tpu.manifest.segment_manifest import SegmentManifestV1
from tieredstorage_tpu.storage.core import ObjectKey
from tieredstorage_tpu.storage.httpclient import HttpClient, HttpError, NO_RETRY
from tieredstorage_tpu.utils import faults, flightrecorder as flight
from tieredstorage_tpu.utils.retry import (
    BreakerBoard,
    BreakerState,
    CircuitOpenException,
)
from tieredstorage_tpu.utils.deadline import DEADLINE_HEADER, current_deadline
from tieredstorage_tpu.utils.tracing import TRACEPARENT_HEADER, NOOP_TRACER
from tieredstorage_tpu.utils.locks import new_lock, note_mutation


def encode_chunk_frames(chunks: Sequence[bytes]) -> bytes:
    """Peer-wire framing of a chunk window: u32 count, then per chunk
    u32 length | bytes (big-endian, shim-wire style). Plaintext chunks are
    variable-length (compression), so the frame carries explicit sizes."""
    out = io.BytesIO()
    out.write(struct.pack(">I", len(chunks)))
    for chunk in chunks:
        out.write(struct.pack(">I", len(chunk)))
        out.write(chunk)
    return out.getvalue()


def decode_chunk_frames(blob: bytes, *, expected: int) -> list[bytes]:
    """Inverse of encode_chunk_frames; raises ValueError on any mismatch
    (a torn/truncated peer response must fall back, not serve short bytes)."""
    view = memoryview(blob)
    if len(view) < 4:
        raise ValueError("peer chunk response truncated (no count)")
    (count,) = struct.unpack_from(">I", view, 0)
    if count != expected:
        raise ValueError(f"peer returned {count} chunks, wanted {expected}")
    offset = 4
    chunks: list[bytes] = []
    for _ in range(count):
        if len(view) - offset < 4:
            raise ValueError("peer chunk response truncated (length)")
        (length,) = struct.unpack_from(">I", view, offset)
        offset += 4
        if len(view) - offset < length:
            raise ValueError("peer chunk response truncated (body)")
        chunks.append(bytes(view[offset : offset + length]))
        offset += length
    if offset != len(view):
        raise ValueError("peer chunk response has trailing bytes")
    return chunks


class PeerChunkCache(ChunkManager):
    """ChunkManager tier that routes misses to the owning fleet sibling."""

    def __init__(
        self,
        delegate: ChunkManager,
        router: FleetRouter,
        *,
        replication: int = 2,
        forward_timeout_s: float = 2.0,
        down_cooldown_s: float = 5.0,
        breaker_threshold: int = 1,
        tracer=NOOP_TRACER,
        on_forward=None,
        time_source=time.monotonic,
    ) -> None:
        if replication < 1:
            raise ValueError(f"replication factor must be >= 1, got {replication}")
        self._delegate = delegate
        self._router = router
        #: R replica owners per key (`fleet.replication.factor`): misses try
        #: them in ring order, so the death of the first owner fails over to
        #: the second with one forward hop instead of losing the cache arc.
        self.replication = replication
        self._flight = SingleFlight(tracer=tracer)
        self.tracer = tracer
        #: Optional `(elapsed_ms)` hook per completed forward; the RSM wires
        #: it to the fleet-forward-time histogram.
        self.on_forward = on_forward
        self.forward_timeout_s = forward_timeout_s
        self.down_cooldown_s = down_cooldown_s
        self._now = time_source
        #: Per-owner breakers (the unified failure-policy plane, ISSUE 19):
        #: threshold failures open an owner for `down_cooldown_s`, then one
        #: half-open probe re-admits it. Opening emits the same
        #: `fleet.peer_down` tracing event the old cooldown dict did.
        self.breakers = BreakerBoard(
            failure_threshold=max(1, breaker_threshold),
            cooldown_s=down_cooldown_s,
            time_source=time_source,
            on_transition=self._on_breaker_transition,
        )
        self._lock = new_lock("peer_cache.PeerChunkCache._lock")
        self._clients: dict[str, HttpClient] = {}
        #: Keys this instance is currently serving AS the owner (forwarded
        #: requests pin their key so the serving path can never re-forward,
        #: even across the chunk cache's loader pool threads).
        self._pinned: dict[str, int] = {}
        # Counters (exported as fleet-metrics gauges).
        self.forwards = 0
        self.peer_hits = 0
        self.peer_misses = 0
        self.forward_failures = 0
        #: Forwards answered by a non-first owner (the replication win:
        #: requests that would have been backend reads pre-R>1).
        self.failover_hits = 0

    @property
    def delegate(self) -> ChunkManager:
        return self._delegate

    @property
    def singleflight(self) -> SingleFlight:
        return self._flight

    @property
    def router(self) -> FleetRouter:
        return self._router

    @property
    def peers_down(self) -> int:
        return self.breakers.open_count()

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for client in clients:
            client.close()
        if hasattr(self._delegate, "close"):
            self._delegate.close()

    # -------------------------------------------------------------- pinning
    @contextlib.contextmanager
    def serving_locally(self, key_value: str):
        """Pin `key_value` to the local path for the duration of the block —
        the loop guard for forwarded requests. Keyed (not thread-local) so it
        holds across the chunk cache's loader pool threads."""
        with self._lock:
            self._pinned[key_value] = self._pinned.get(key_value, 0) + 1
        try:
            yield
        finally:
            with self._lock:
                count = self._pinned.get(key_value, 1) - 1
                if count <= 0:
                    self._pinned.pop(key_value, None)
                else:
                    self._pinned[key_value] = count

    def _is_pinned(self, key_value: str) -> bool:
        with self._lock:
            return key_value in self._pinned

    # ---------------------------------------------------------- peer health
    def _on_breaker_transition(
        self, peer: str, old: BreakerState, new: BreakerState
    ) -> None:
        if new is BreakerState.OPEN:
            self.tracer.event("fleet.peer_down", peer=peer, reason="breaker_open")

    def _client(self, peer: str, url: str) -> HttpClient:
        stale: Optional[HttpClient] = None
        with self._lock:
            client = self._clients.get(peer)
            if client is None or client.base_url != url:
                # Single attempt: the local backend path IS the retry, and a
                # struggling peer must not absorb backoff sleeps. The stale
                # client (peer re-ringed to a new URL) is closed OUTSIDE the
                # lock - socket teardown must not stall every other forward
                # (lock-order checker: no blocking calls under _lock).
                stale = client
                client = HttpClient(
                    url, timeout=self.forward_timeout_s, retry=NO_RETRY
                )
                self._clients[peer] = client
        if stale is not None:
            stale.close()
        return client

    # ----------------------------------------------------------------- reads
    def get_chunk(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_id: int
    ) -> BinaryIO:
        return io.BytesIO(self.get_chunks(objects_key, manifest, [chunk_id])[0])

    def get_chunks(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_ids: Sequence[int]
    ) -> list[bytes]:
        if not chunk_ids:
            return []
        # The flight wraps the WHOLE resolve (forward or backend): N
        # concurrent identical windows produce at most one forward on a
        # non-owner and exactly one backend read on the owner. Keyed by the
        # exact id list, not endpoints: windows [0,2] and [0,1,2] must not
        # share a flight (their results have different shapes).
        flight_key = f"{objects_key.value}#{','.join(map(str, chunk_ids))}"
        return self._flight.do(
            flight_key,
            lambda: self._resolve(objects_key, manifest, chunk_ids),
            what=objects_key.value,
        )

    def _resolve(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_ids: Sequence[int]
    ) -> list[bytes]:
        """Try the key's R replica owners in ring order; serve locally when
        this instance is the highest-priority reachable owner (or every
        owner is down/unreachable — forwarding is never a dependency)."""
        if not self._is_pinned(objects_key.value):
            owners = self._router.route_owners(objects_key.value, self.replication)
            for rank, (owner, url) in enumerate(owners):
                if url is None:
                    # This instance (or an address-less member) is the first
                    # live owner: the local chunk path IS the replica serve,
                    # and it warms this instance's arc copy.
                    break
                breaker = self.breakers.for_target(owner)
                try:
                    # Open = skip to the next owner; half-open admits ONE
                    # probing forward while concurrent readers fall back.
                    breaker.acquire()
                except CircuitOpenException:
                    flight.note("breaker.skipped_owners")
                    continue
                try:
                    forwarded = self._try_forward(
                        owner, url, objects_key, chunk_ids, rank=rank,
                        breaker=breaker,
                    )
                except BaseException:
                    breaker.on_neutral()  # never leak a half-open probe slot
                    raise
                if forwarded is not None:
                    return forwarded
        return self._delegate.get_chunks(objects_key, manifest, list(chunk_ids))

    def _try_forward(
        self, owner: str, url: str, objects_key: ObjectKey,
        chunk_ids: Sequence[int], *, rank: int = 0, breaker=None,
    ) -> Optional[list[bytes]]:
        """One GET /chunk against the owner; None means 'try the next owner,
        then serve locally' (miss, peer down, torn frame) — never an error.
        Every outcome settles `breaker`: failure/torn frame/5xx are breaker
        failures, a served window or a 404 (healthy contract answer) is a
        breaker success."""

        def settle_failure() -> None:
            if breaker is not None:
                breaker.on_failure()

        with self._lock:
            self.forwards += 1
            note_mutation("peer_cache.PeerChunkCache.forwards")
        self.tracer.event(
            "fleet.forward", peer=owner, key=objects_key.value,
            chunks=len(chunk_ids), rank=rank,
        )
        # The wire carries a contiguous lo-hi window; a sparse id list (the
        # cache's missing-subset can have gaps) over-fetches the covering
        # range and subselects — one round trip beats per-gap requests.
        lo, hi = chunk_ids[0], chunk_ids[-1]
        path = (
            f"/chunk?key={quote(objects_key.value, safe='')}"
            f"&chunks={lo}-{hi}"
        )
        headers: dict[str, str] = {}
        traceparent = self.tracer.current_traceparent()
        if traceparent:
            headers[TRACEPARENT_HEADER] = traceparent
        deadline = current_deadline()
        if deadline is not None:
            headers[DEADLINE_HEADER] = deadline.header_value()
        start = time.monotonic()
        try:
            # ISSUE 19 injection seam: an `error` fault fails this hop like a
            # dead transport; `partial` tears the response body below so the
            # frame decoder must refuse it.
            torn = faults.fire("peer.forward", f"{owner}|{objects_key.value}")
            resp = self._client(owner, url).request("GET", path, headers=headers)
        except (HttpError, faults.FaultInjectedError) as e:
            with self._lock:
                self.forward_failures += 1
                note_mutation("peer_cache.PeerChunkCache.forward_failures")
            settle_failure()
            self.tracer.event(
                "fleet.forward_failed", peer=owner, reason=f"{type(e).__name__}"
            )
            return None
        elapsed_ms = (time.monotonic() - start) * 1000.0
        if resp.status == 200:
            try:
                body = faults.mutate(resp.body, torn)
                window = decode_chunk_frames(body, expected=hi - lo + 1)
            except ValueError as e:
                with self._lock:
                    self.forward_failures += 1
                    note_mutation("peer_cache.PeerChunkCache.forward_failures")
                settle_failure()
                self.tracer.event(
                    "fleet.forward_failed", peer=owner, reason=str(e)
                )
                return None
            chunks = [window[cid - lo] for cid in chunk_ids]
            if breaker is not None:
                breaker.on_success()
            with self._lock:
                self.peer_hits += 1
                note_mutation("peer_cache.PeerChunkCache.peer_hits")
                if rank > 0:
                    self.failover_hits += 1
                    note_mutation("peer_cache.PeerChunkCache.failover_hits")
            # Flight-record the peer serve (and how many owner hops it took).
            flight.note("tier.peer", len(chunks))
            if rank > 0:
                flight.note("peer.failover_hops", rank)
            if self.on_forward is not None:
                self.on_forward(elapsed_ms)
            self.tracer.event(
                "fleet.peer_hit", peer=owner, key=objects_key.value,
                chunks=len(chunks),
            )
            return chunks
        if resp.status == 404:
            # The owner cannot serve this key (not uploaded / already
            # deleted there): a contract answer from a HEALTHY peer — the
            # authoritative answer comes from the local storage stack.
            if breaker is not None:
                breaker.on_success()
            with self._lock:
                self.peer_misses += 1
                note_mutation("peer_cache.PeerChunkCache.peer_misses")
            return None
        with self._lock:
            self.forward_failures += 1
            note_mutation("peer_cache.PeerChunkCache.forward_failures")
        settle_failure()
        self.tracer.event(
            "fleet.forward_failed", peer=owner, reason=f"http {resp.status}"
        )
        return None
