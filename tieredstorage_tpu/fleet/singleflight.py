"""Single-flight request coalescing: N identical in-flight calls, one execution.

Nishtala et al. ("Scaling Memcache at Facebook", NSDI '13 §3.2.1, "leases"):
under a thundering herd on a hot key, every concurrent miss issuing its own
backend read multiplies load exactly when the backend is least able to absorb
it. The cure is to elect one LEADER per key — the first caller executes the
fetch; every concurrent duplicate (local threads, or requests forwarded from
sibling instances, which land on the owner and take this same gate) blocks as
a FOLLOWER and receives the leader's result. N concurrent fetches of one hot
chunk collapse to exactly one backend read.

Failure semantics: the leader's exception propagates to every follower of
that flight (they asked the same question; they get the same answer), and the
flight slot is removed before followers wake — the NEXT caller starts a fresh
flight, so a transient failure is retryable and a slot can never leak.
Followers clamp their wait to the ambient end-to-end Deadline; a follower
timing out does not disturb the flight (the leader still completes and
populates the cache for later readers).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, TypeVar

from tieredstorage_tpu.utils.deadline import (
    DeadlineExceededException,
    remaining_s,
)
from tieredstorage_tpu.utils.locks import new_lock
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

T = TypeVar("T")


class _Flight:
    __slots__ = ("done", "result", "error")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class SingleFlight:
    """Per-key in-flight call registry; thread-safe, allocation-light."""

    def __init__(self, tracer=NOOP_TRACER) -> None:
        self.tracer = tracer
        self._lock = new_lock("singleflight.SingleFlight._lock")
        self._flights: dict[str, _Flight] = {}
        #: Calls that executed the work (one per flight).
        self.leaders = 0
        #: Calls that joined an existing flight instead of executing.
        self.coalesced = 0
        #: Flights that completed with an error (propagated to all joiners).
        self.failures = 0

    @property
    def pending(self) -> int:
        """In-flight keys right now (0 when idle — leaked slots would show
        here, which is what the hedge-interaction tests pin)."""
        with self._lock:
            return len(self._flights)

    def do(self, key: str, fn: Callable[[], T], *, what: str = "") -> T:
        """Run `fn` once per concurrently-requested `key`.

        The first caller for a key executes `fn` on ITS OWN thread (so the
        ambient deadline/trace context apply unchanged); concurrent callers
        with the same key wait and share the leader's result or exception."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                leader = True
                self.leaders += 1
            else:
                leader = False
                self.coalesced += 1
        if not leader:
            self.tracer.event("fleet.coalesced", key=what or key)
            return self._await(key, flight)
        try:
            flight.result = fn()
        except BaseException as e:
            flight.error = e
            with self._lock:
                self.failures += 1
            raise
        finally:
            # Unregister BEFORE waking followers: a caller arriving after
            # completion must start a fresh flight, never read a stale one.
            with self._lock:
                self._flights.pop(key, None)
            flight.done.set()
        return flight.result

    def _await(self, key: str, flight: _Flight) -> T:
        budget = remaining_s()
        if not flight.done.wait(timeout=budget):
            raise DeadlineExceededException(
                f"Deadline exceeded waiting on coalesced fetch of {key}"
            )
        if flight.error is not None:
            raise flight.error
        return flight.result
