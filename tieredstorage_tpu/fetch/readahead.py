"""Predictive sequential readahead: speculate FUTURE windows, pre-admit them.

Kafka consumers replay log segments front to back, so the fetch stream of a
replaying consumer is near-perfectly predictable — yet without this tier a
cold massed replay is served as a reactive cache-miss storm: every window
pays storage latency + a latency-class decrypt right on the consumer's
critical path. The informed-prefetching line of work (Patterson et al.,
TIP, SOSP '95) says the fix is to turn disclosed/detected sequentiality
into *scheduled* background work; the continuous-batching lever (Orca,
OSDI '22 — already the shape of our ``WindowBatcher``) says speculated
windows should keep the device queue full between foreground arrivals.

``ReadaheadManager`` is the outermost fetch tier (above the chunk cache,
inserted by ``fetch/factory.py`` when ``readahead.enabled``)::

    ReadaheadManager -> ChunkCache -> DeviceHotCache -> [PeerChunkCache]
                     -> DefaultChunkManager -> storage

Per segment stream it runs a small detector state machine:

- ``IDLE`` — every stream starts here. Consecutive *sequential* window
  reads (window N+1 starts exactly where window N ended) accumulate a
  run; ``promote_after`` consecutive sequential reads promote the stream
  (hysteresis: one sequential read is not a pattern).
- ``READAHEAD`` — the manager speculates ``readahead.window.chunks``
  chunks past the stream's frontier on every foreground read, issuing
  them through the *delegate chain* on its own small pool under
  ``work_class_scope(BACKGROUND)`` + ``speculative_scope()`` so the
  decrypts join the batcher's background admission class and can never
  out-rank a latency-class fetch. The loads populate the chunk cache /
  hot tier exactly like foreground loads do — pre-admission IS a cache
  population — and the chunk cache's per-chunk single-flight guarantees
  a foreground read that arrives mid-speculation JOINS the in-flight
  decode instead of double-decrypting.
- Mispredictions (a non-sequential jump while promoted) are strikes;
  ``max_strikes`` strikes demote the stream back to ``IDLE`` and charge
  every unused speculated byte to ``wasted_bytes`` (strike-based
  demotion, not single-miss: one seek in an otherwise sequential replay
  must not kill the pipeline).

Speculation is bounded by a HARD in-flight byte budget
(``readahead.budget.bytes``) and self-throttles when the observed
wasted-decrypt-bytes ratio exceeds ``readahead.misprediction.max.ratio``,
so a wrong prediction model degrades to the reactive baseline instead of
burning the device.

Cross-segment continuation: a segment's chunk index ends, but the replay
does not — when the frontier crosses the segment end and a
``next_segment_resolver`` is wired (harness/broker-side knowledge of
segment ordering; the resolver typically rides the RSM's keyed
single-flight ``ManifestLookahead`` so N streams crossing one boundary
resolve the next manifest once), the first window of the NEXT segment is
speculated and its stream is pre-promoted, so the consumer crosses the
boundary into an already-warm cache.

Every counter here is guarded by ``_lock`` and inventoried by the race
checker (``analysis/races.py`` ``SHARED_CLASSES``) with ``note_mutation``
at each write site — zero suppressions. Speculative launches carry
synthetic flight records (``readahead.window``) stamped with the
originating stream's trace id, so ``/debug/timeline`` shows them as
attributable background flows.
"""

from __future__ import annotations

import dataclasses
import io
import logging
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import BinaryIO, Callable, Optional, Sequence

from tieredstorage_tpu.config.configdef import ConfigDef, ConfigKey, in_range
from tieredstorage_tpu.fetch.cache.chunk_cache import ChunkKey
from tieredstorage_tpu.fetch.chunk_manager import ChunkManager
from tieredstorage_tpu.manifest.segment_manifest import SegmentManifestV1
from tieredstorage_tpu.storage.core import ObjectKey
from tieredstorage_tpu.transform.scheduler import BACKGROUND, work_class_scope
from tieredstorage_tpu.transform.scheduler import speculative_scope
from tieredstorage_tpu.utils import flightrecorder as flight
from tieredstorage_tpu.utils.locks import new_lock, note_mutation
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

log = logging.getLogger(__name__)

#: Consecutive sequential window reads before a stream is promoted to
#: READAHEAD (hysteresis: one sequential pair is coincidence, two are a
#: pattern — TIP's "sequential detection" default).
DEFAULT_PROMOTE_AFTER = 2
#: Mispredictions while promoted before the stream is demoted back to
#: IDLE (strike-based: a single seek must not kill the pipeline).
DEFAULT_MAX_STRIKES = 2

IDLE = "idle"
READAHEAD = "readahead"

#: A resolver maps the CURRENT segment's object key to the next segment in
#: replay order: ``(next_object_key, manifest_loader)`` or None at the log
#: head. Segment ordering is broker-side knowledge (base offsets), so the
#: RSM/harness wires this seam; the loader should ride the manifest
#: lookahead (fetch/manifest_cache.py) for keyed single-flight resolution.
NextSegmentResolver = Callable[
    [ObjectKey], Optional[tuple[ObjectKey, Callable[[], SegmentManifestV1]]]
]


def _definition() -> ConfigDef:
    """Top-level ``readahead.*`` keys, read by the ChunkManagerFactory and
    rendered into docs/configs.rst by the docs generator."""
    d = ConfigDef()
    d.define(ConfigKey(
        "readahead.enabled", "bool", default=False, importance="medium",
        doc="Insert the predictive sequential-readahead tier above the "
            "chunk cache: streams detected as sequential get future "
            "windows speculated as background-class work and pre-admitted "
            "into the chunk cache / device hot tier before the consumer "
            "asks. Disabled is zero-work (the tier is not built).",
    ))
    d.define(ConfigKey(
        "readahead.window.chunks", "int", default=8,
        validator=in_range(1, 4096), importance="medium",
        doc="Chunks speculated per readahead launch: each launch covers "
            "this many chunks past the stream's frontier with ONE delegate "
            "window read (one ranged GET + one batched detransform).",
    ))
    d.define(ConfigKey(
        "readahead.streams.max", "int", default=64,
        validator=in_range(1, None), importance="low",
        doc="Per-segment streams tracked by the sequential detector; the "
            "least-recently-observed stream is evicted beyond this (its "
            "unused speculated bytes are charged as wasted).",
    ))
    d.define(ConfigKey(
        "readahead.budget.bytes", "long", default=16 * 1024 * 1024,
        validator=in_range(0, None), importance="medium",
        doc="HARD in-flight speculation budget in original (plaintext) "
            "bytes across all streams: a launch that would exceed it is "
            "deferred to the next foreground read, so speculation can "
            "never starve latency-class fetches or run away on the "
            "device. 0 disables speculation while keeping the detector.",
    ))
    d.define(ConfigKey(
        "readahead.misprediction.max.ratio", "double", default=0.2,
        validator=in_range(0.0, 1.0), importance="medium",
        doc="Bound on wasted speculative decrypt bytes as a fraction of "
            "all speculated bytes: the readahead-misprediction SLO spec "
            "objectives against it, and the manager self-throttles (stops "
            "launching) while the observed ratio exceeds it.",
    ))
    return d


@dataclasses.dataclass
class _Speculated:
    """One speculated chunk, from launch until used/wasted/failed."""

    stream: str
    n_bytes: int
    completed_at: Optional[float] = None
    #: Stream was demoted/evicted while this chunk's load was in flight:
    #: charge it as wasted when the load completes.
    doomed: bool = False


class _Stream:
    """Per-segment detector state (guarded by the manager's ``_lock``)."""

    __slots__ = (
        "state", "expected_next", "runs", "strikes", "frontier",
        "outstanding", "continued",
    )

    def __init__(self, expected_next: int) -> None:
        self.state = IDLE
        #: Chunk id a sequential continuation would start at.
        self.expected_next = expected_next
        self.runs = 0
        self.strikes = 0
        #: Next chunk id to speculate (never behind the foreground read).
        self.frontier = expected_next
        #: ChunkKeys speculated for this stream and not yet used/wasted.
        self.outstanding: set[ChunkKey] = set()
        #: Cross-segment continuation already planned for this segment.
        self.continued = False


class ReadaheadManager(ChunkManager):
    """Outermost fetch tier: detect sequential streams, speculate ahead."""

    #: Span recorder; the RSM swaps in its configured tracer.
    tracer = NOOP_TRACER
    #: Synthetic-record source for speculative launches; the RSM wires its
    #: configured FlightRecorder so readahead windows appear on
    #: /debug/requests and as timeline flows.
    flight_recorder = flight.NOOP_RECORDER
    #: Cross-segment continuation seam (see NextSegmentResolver).
    next_segment_resolver: Optional[NextSegmentResolver] = None

    def __init__(
        self,
        delegate: ChunkManager,
        *,
        window_chunks: int = 8,
        streams_max: int = 64,
        budget_bytes: int = 16 * 1024 * 1024,
        misprediction_max_ratio: float = 0.2,
        promote_after: int = DEFAULT_PROMOTE_AFTER,
        max_strikes: int = DEFAULT_MAX_STRIKES,
        time_source: Callable[[], float] = time.monotonic,
        max_workers: int = 2,
    ) -> None:
        if window_chunks < 1:
            raise ValueError(f"window_chunks must be >= 1, got {window_chunks}")
        if streams_max < 1:
            raise ValueError(f"streams_max must be >= 1, got {streams_max}")
        if budget_bytes < 0:
            raise ValueError(f"budget_bytes must be >= 0, got {budget_bytes}")
        if not 0.0 <= misprediction_max_ratio <= 1.0:
            raise ValueError(
                "misprediction_max_ratio must be in [0, 1], "
                f"got {misprediction_max_ratio}"
            )
        self._delegate = delegate
        self.window_chunks = int(window_chunks)
        self.streams_max = int(streams_max)
        self.budget_bytes = int(budget_bytes)
        self.misprediction_max_ratio = float(misprediction_max_ratio)
        self.promote_after = int(promote_after)
        self.max_strikes = int(max_strikes)
        self._now = time_source
        self._executor = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="readahead"
        )
        self._lock = new_lock("readahead.ReadaheadManager._lock")
        #: LRU of per-segment detector states (segment file name -> _Stream).
        self._streams: "OrderedDict[str, _Stream]" = OrderedDict()
        #: Every speculated chunk not yet used/wasted/failed.
        self._speculated: dict[ChunkKey, _Speculated] = {}
        # --- counters (all guarded by _lock; race-checker inventoried) ---
        self.promotions = 0
        self.demotions = 0
        self.strikes = 0
        self.stream_evictions = 0
        self.windows_launched = 0
        self.chunks_speculated = 0
        self.bytes_speculated = 0
        self.inflight_bytes = 0
        self.used_chunks = 0
        self.used_bytes = 0
        self.wasted_bytes = 0
        self.budget_deferrals = 0
        self.ratio_throttles = 0
        self.cross_segment_continuations = 0
        self.speculation_failures = 0
        #: Pre-admit-to-use age accounting (completed speculation -> first
        #: foreground use), for the freshness gauge.
        self.pre_admit_age_ms_sum = 0.0
        self.pre_admit_age_samples = 0

    # ---------------------------------------------------------- observability
    @property
    def tracked_streams(self) -> int:
        with self._lock:
            return len(self._streams)

    @property
    def outstanding_chunks(self) -> int:
        with self._lock:
            return len(self._speculated)

    @property
    def hit_rate(self) -> float:
        """Speculated chunks later consumed by a foreground read."""
        with self._lock:
            if self.chunks_speculated == 0:
                return 0.0
            return self.used_chunks / self.chunks_speculated

    @property
    def misprediction_ratio(self) -> float:
        """Wasted speculative decrypt bytes / all speculated bytes."""
        with self._lock:
            return self._misprediction_ratio_locked()

    def _misprediction_ratio_locked(self) -> float:
        if self.bytes_speculated == 0:
            return 0.0
        return self.wasted_bytes / self.bytes_speculated

    @property
    def mean_pre_admit_age_ms(self) -> float:
        with self._lock:
            if self.pre_admit_age_samples == 0:
                return 0.0
            return self.pre_admit_age_ms_sum / self.pre_admit_age_samples

    # ----------------------------------------------------------------- reads
    def get_chunk(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_id: int
    ) -> BinaryIO:
        return io.BytesIO(self.get_chunks(objects_key, manifest, [chunk_id])[0])

    def get_chunks(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1,
        chunk_ids: Sequence[int],
    ) -> list[bytes]:
        if not chunk_ids:
            return []
        launches = self._observe(objects_key, manifest, chunk_ids)
        # Launch speculation BEFORE the foreground read so the speculative
        # window's fetch+decrypt overlaps with it (the windows are disjoint;
        # shared chunks would coalesce in the cache's single-flight anyway).
        for launch in launches:
            self._executor.submit(self._speculate, *launch)
        return self._delegate.get_chunks(objects_key, manifest, chunk_ids)

    # -------------------------------------------------------------- detector
    def _observe(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1,
        chunk_ids: Sequence[int],
    ) -> list[tuple]:
        """Advance the stream's detector state for one foreground window
        read and return the speculation launches to submit (possibly
        empty). Runs entirely under ``_lock``; launches run the storage
        and device work OUTSIDE it."""
        first, last = chunk_ids[0], chunk_ids[-1]
        stream_key = ChunkKey.of(objects_key, first).segment_file_name
        trace_id = flight.current_trace_id() or ""
        with self._lock:
            stream = self._streams.get(stream_key)
            if stream is None:
                stream = _Stream(expected_next=last + 1)
                self._streams[stream_key] = stream
                self._evict_streams_locked()
            else:
                self._streams.move_to_end(stream_key)
                self._advance_locked(stream, first, last)
            # Consume pre-admitted chunks covered by this read (their use
            # is what the whole subsystem exists for).
            self._consume_locked(stream, objects_key, chunk_ids)
            if stream.state != READAHEAD:
                return []
            flight.note("readahead.stream_hits" if stream.outstanding else
                        "readahead.stream", 1)
            return self._plan_locked(stream, objects_key, manifest, last, trace_id)

    def _advance_locked(self, stream: _Stream, first: int, last: int) -> None:
        if first == stream.expected_next:
            stream.runs += 1
            if stream.state == IDLE and stream.runs >= self.promote_after:
                stream.state = READAHEAD
                stream.strikes = 0
                stream.frontier = max(stream.frontier, last + 1)
                self.promotions += 1
                note_mutation("readahead.ReadaheadManager.promotions")
        elif last + 1 == stream.expected_next:
            # Re-read ending at the current frontier (broker retry of the
            # previous window): neither a run nor a strike — idempotent
            # retries are not seeks.
            pass
        else:
            stream.runs = 0
            if stream.state == READAHEAD:
                stream.strikes += 1
                self.strikes += 1
                note_mutation("readahead.ReadaheadManager.strikes")
                if stream.strikes >= self.max_strikes:
                    self._demote_locked(stream)
        stream.expected_next = last + 1
        if stream.state == READAHEAD:
            stream.frontier = max(stream.frontier, last + 1)

    def _demote_locked(self, stream: _Stream) -> None:
        stream.state = IDLE
        stream.runs = 0
        stream.strikes = 0
        self.demotions += 1
        note_mutation("readahead.ReadaheadManager.demotions")
        self._discard_outstanding_locked(stream)

    def _discard_outstanding_locked(self, stream: _Stream) -> None:
        """Charge a stream's unused predictions as wasted; in-flight loads
        are doomed in place (charged on completion)."""
        for key in stream.outstanding:
            entry = self._speculated.get(key)
            if entry is None:
                continue
            if entry.completed_at is not None:
                del self._speculated[key]
                self.wasted_bytes += entry.n_bytes
                note_mutation("readahead.ReadaheadManager.wasted_bytes")
            else:
                entry.doomed = True
        stream.outstanding.clear()

    def _evict_streams_locked(self) -> None:
        while len(self._streams) > self.streams_max:
            _, evicted = self._streams.popitem(last=False)
            self.stream_evictions += 1
            note_mutation("readahead.ReadaheadManager.stream_evictions")
            self._discard_outstanding_locked(evicted)

    def _consume_locked(
        self, stream: _Stream, objects_key: ObjectKey,
        chunk_ids: Sequence[int],
    ) -> None:
        used = 0
        now = self._now()
        for cid in chunk_ids:
            key = ChunkKey.of(objects_key, cid)
            entry = self._speculated.pop(key, None)
            if entry is None:
                continue
            stream.outstanding.discard(key)
            used += 1
            self.used_chunks += 1
            note_mutation("readahead.ReadaheadManager.used_chunks")
            self.used_bytes += entry.n_bytes
            note_mutation("readahead.ReadaheadManager.used_bytes")
            if entry.completed_at is not None:
                self.pre_admit_age_ms_sum += (now - entry.completed_at) * 1000.0
                note_mutation("readahead.ReadaheadManager.pre_admit_age_ms_sum")
                self.pre_admit_age_samples += 1
                note_mutation("readahead.ReadaheadManager.pre_admit_age_samples")
        # Predictions the stream ran PAST without using are mispredicted
        # bytes even without a demotion (the consumer skipped them).
        superseded = [
            key for key in stream.outstanding if key.chunk_id < chunk_ids[0]
        ]
        for key in superseded:
            entry = self._speculated.get(key)
            stream.outstanding.discard(key)
            if entry is None:
                continue
            if entry.completed_at is not None:
                del self._speculated[key]
                self.wasted_bytes += entry.n_bytes
                note_mutation("readahead.ReadaheadManager.wasted_bytes")
            else:
                entry.doomed = True
        if used:
            flight.note("tier.readahead", used)

    # -------------------------------------------------------------- planning
    def _plan_locked(
        self, stream: _Stream, objects_key: ObjectKey,
        manifest: SegmentManifestV1, last: int, trace_id: str,
    ) -> list[tuple]:
        launches: list[tuple] = []
        if self.budget_bytes <= 0:
            return launches
        if self._misprediction_ratio_locked() > self.misprediction_max_ratio:
            # Self-throttle: the prediction model is provably wrong right
            # now — stop speculating until used bytes pull the ratio back
            # under the bound (degrades to the reactive baseline).
            self.ratio_throttles += 1
            note_mutation("readahead.ReadaheadManager.ratio_throttles")
            return launches
        index = manifest.chunk_index
        stream_key = ChunkKey.of(objects_key, last).segment_file_name
        start = max(stream.frontier, last + 1)
        if start < index.chunk_count:
            ids = list(range(start, min(start + self.window_chunks,
                                        index.chunk_count)))
            planned = self._admit_locked(stream, objects_key, index, ids)
            if planned:
                stream.frontier = ids[-1] + 1
                launches.append(
                    (objects_key, manifest, ids, planned, trace_id, stream_key)
                )
        if (
            stream.frontier >= index.chunk_count
            and not stream.continued
            and self.next_segment_resolver is not None
        ):
            # The frontier crossed the segment end: continue into the next
            # segment (resolved + planned on the pool — the resolver may
            # fetch a manifest and must not run under this lock).
            stream.continued = True
            launches.append((objects_key, None, None, None, trace_id, stream_key))
        return launches

    def _admit_locked(
        self, stream: _Stream, objects_key: ObjectKey, index, ids: list[int]
    ) -> Optional[int]:
        """Budget admission for one speculative window: returns its byte
        cost and registers its chunks, or None when deferred."""
        ids[:] = [
            cid for cid in ids
            if ChunkKey.of(objects_key, cid) not in self._speculated
        ]
        if not ids:
            return None
        n_bytes = sum(index._chunk_at(cid).original_size for cid in ids)
        if self.inflight_bytes + n_bytes > self.budget_bytes:
            self.budget_deferrals += 1
            note_mutation("readahead.ReadaheadManager.budget_deferrals")
            return None
        stream_key = ChunkKey.of(objects_key, ids[0]).segment_file_name
        for cid in ids:
            key = ChunkKey.of(objects_key, cid)
            self._speculated[key] = _Speculated(
                stream=stream_key,
                n_bytes=index._chunk_at(cid).original_size,
            )
            stream.outstanding.add(key)
        self.inflight_bytes += n_bytes
        note_mutation("readahead.ReadaheadManager.inflight_bytes")
        self.bytes_speculated += n_bytes
        note_mutation("readahead.ReadaheadManager.bytes_speculated")
        self.windows_launched += 1
        note_mutation("readahead.ReadaheadManager.windows_launched")
        self.chunks_speculated += len(ids)
        note_mutation("readahead.ReadaheadManager.chunks_speculated")
        return n_bytes

    # ------------------------------------------------------------ speculation
    def _speculate(
        self, objects_key: ObjectKey, manifest, ids, n_bytes,
        trace_id: str, stream_key: str,
    ) -> None:
        """Pool entry point for one speculative launch. ``manifest is
        None`` marks a cross-segment continuation: resolve the next
        segment first, then plan + load its first window."""
        try:
            if manifest is None:
                resolved = self._continue_next_segment(objects_key, trace_id)
                if resolved is None:
                    return
                objects_key, manifest, ids, n_bytes, stream_key = resolved
            self._load_window(objects_key, manifest, ids, n_bytes, trace_id,
                              stream_key)
        except Exception:
            # Isolation boundary: speculation must never propagate into (or
            # wedge) anything — it is a bet, and a failed bet just means
            # the foreground read pays the reactive price later.
            log.debug("Readahead speculation failed for %s", objects_key,
                      exc_info=True)

    def _continue_next_segment(self, objects_key: ObjectKey, trace_id: str):
        resolved = self.next_segment_resolver(objects_key)
        if resolved is None:
            return None
        next_key, manifest_loader = resolved
        with self.tracer.span("readahead.next_segment", key=next_key.value):
            manifest = manifest_loader()
        index = manifest.chunk_index
        ids = list(range(0, min(self.window_chunks, index.chunk_count)))
        if not ids:
            return None
        next_stream_key = ChunkKey.of(next_key, 0).segment_file_name
        with self._lock:
            stream = self._streams.get(next_stream_key)
            if stream is None:
                # Pre-promote the continuation stream: the consumer will
                # start the next segment at chunk 0, already sequential.
                stream = _Stream(expected_next=0)
                self._streams[next_stream_key] = stream
                self._evict_streams_locked()
            stream.state = READAHEAD
            stream.runs = self.promote_after
            planned = self._admit_locked(stream, next_key, index, ids)
            if planned is None:
                return None
            stream.frontier = ids[-1] + 1
            self.cross_segment_continuations += 1
            note_mutation(
                "readahead.ReadaheadManager.cross_segment_continuations"
            )
        return next_key, manifest, ids, planned, next_stream_key

    def _load_window(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1,
        ids: list[int], n_bytes: int, trace_id: str, stream_key: str,
    ) -> None:
        """Load one speculative window through the delegate chain under a
        synthetic flight record + background work class. The delegate IS
        the chunk cache, so the verified plaintext lands in the cache (and
        offers itself to the hot tier) exactly like a foreground load —
        and any concurrent foreground read single-flight-joins it."""
        keys = [ChunkKey.of(objects_key, cid) for cid in ids]
        try:
            # Pool workers carry no ambient record, so this opens a REAL
            # synthetic record (request() is reentrant) attributed to the
            # originating stream's trace id — readahead flows are visible
            # work, not anonymous background load.
            with self.flight_recorder.request("readahead.window",
                                              trace_id=trace_id):
                flight.note("readahead.chunks", len(ids))
                flight.stage(f"readahead.segment:{stream_key}")
                with work_class_scope(BACKGROUND), speculative_scope():
                    with self.tracer.span(
                        "readahead.window", key=objects_key.value,
                        chunks=len(ids),
                    ):
                        self._delegate.get_chunks(objects_key, manifest, ids)
        except Exception:
            self._resolve_failed(keys, n_bytes)
            raise
        self._resolve_completed(keys, n_bytes)

    def _resolve_completed(self, keys: list[ChunkKey], n_bytes: int) -> None:
        now = self._now()
        with self._lock:
            self.inflight_bytes -= n_bytes
            note_mutation("readahead.ReadaheadManager.inflight_bytes")
            for key in keys:
                entry = self._speculated.get(key)
                if entry is None:
                    continue  # consumed (single-flight join) mid-load
                if entry.doomed:
                    del self._speculated[key]
                    self.wasted_bytes += entry.n_bytes
                    note_mutation("readahead.ReadaheadManager.wasted_bytes")
                else:
                    entry.completed_at = now

    def _resolve_failed(self, keys: list[ChunkKey], n_bytes: int) -> None:
        with self._lock:
            self.inflight_bytes -= n_bytes
            note_mutation("readahead.ReadaheadManager.inflight_bytes")
            self.speculation_failures += 1
            note_mutation("readahead.ReadaheadManager.speculation_failures")
            for key in keys:
                entry = self._speculated.pop(key, None)
                if entry is None:
                    continue
                # Never decrypted: not wasted decrypt bytes — back the
                # failed window out of the speculated total entirely.
                self.bytes_speculated -= entry.n_bytes
                note_mutation("readahead.ReadaheadManager.bytes_speculated")
                stream = self._streams.get(entry.stream)
                if stream is not None:
                    stream.outstanding.discard(key)

    # ----------------------------------------------------------------- close
    def close(self) -> None:
        # Drain speculation before the tiers below close: an in-flight
        # speculative decode must not reach a closed transform backend.
        self._executor.shutdown(wait=True, cancel_futures=True)
        if hasattr(self._delegate, "close"):
            self._delegate.close()
