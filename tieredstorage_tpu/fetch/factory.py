"""ChunkManagerFactory: optionally wrap the default manager in a chunk cache.

Reference: core/.../fetch/ChunkManagerFactory.java:36-52 (reflective wrap of
DefaultChunkManager in the configured ChunkCache subclass) and
config/ChunkManagerFactoryConfig.java:29-55 (`fetch.chunk.cache.class`,
subclass-of-ChunkCache validated, no cache when unset).
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

from tieredstorage_tpu.config.configdef import (
    ConfigDef,
    ConfigKey,
    subclass_of,
    subset_with_prefix,
)
from tieredstorage_tpu.config.rsm_config import FETCH_CHUNK_CACHE_PREFIX
from tieredstorage_tpu.fetch.cache.chunk_cache import ChunkCache
from tieredstorage_tpu.fetch.chunk_manager import ChunkManager, DefaultChunkManager
from tieredstorage_tpu.storage.core import ObjectFetcher
from tieredstorage_tpu.transform.api import TransformBackend


class ChunkManagerFactoryConfig:
    def __init__(self, props: Mapping[str, Any]):
        d = ConfigDef()
        d.define(ConfigKey(
            "fetch.chunk.cache.class", "class", default=None,
            validator=subclass_of(ChunkCache), importance="medium",
            doc="Chunk cache implementation. There are 2 implementations "
                "included: MemoryChunkCache and DiskChunkCache. Unset means "
                "no chunk caching.",
        ))
        self._values = d.parse(props)
        self._props = dict(props)

    @property
    def chunk_cache_class(self) -> Optional[type]:
        return self._values["fetch.chunk.cache.class"]

    def chunk_cache_configs(self) -> dict[str, Any]:
        # The stray "class" key the strip produces is ignored by the cache's
        # ConfigDef (undefined keys are skipped by parse).
        return subset_with_prefix(self._props, FETCH_CHUNK_CACHE_PREFIX)


class ChunkManagerFactory:
    def __init__(self) -> None:
        self._config: Optional[ChunkManagerFactoryConfig] = None

    def configure(self, configs: Mapping[str, Any]) -> None:
        self._config = ChunkManagerFactoryConfig(configs)

    def init_chunk_manager(
        self, fetcher: ObjectFetcher, transform_backend: TransformBackend,
        inner_wrapper=None,
    ) -> ChunkManager:
        """`inner_wrapper`, when given, wraps the DefaultChunkManager BELOW
        the cache (fleet mode inserts the PeerChunkCache tier there: local
        cache first, then route-to-owner, then backend)."""
        default = DefaultChunkManager(fetcher, transform_backend)
        inner: ChunkManager = (
            inner_wrapper(default) if inner_wrapper is not None else default
        )
        cache_class = self._config.chunk_cache_class
        if cache_class is None:
            return inner
        cache: ChunkCache = cache_class(inner)
        cache.configure(self._config.chunk_cache_configs())
        return cache
