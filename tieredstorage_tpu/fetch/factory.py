"""ChunkManagerFactory: optionally wrap the default manager in cache tiers.

Reference: core/.../fetch/ChunkManagerFactory.java:36-52 (reflective wrap of
DefaultChunkManager in the configured ChunkCache subclass) and
config/ChunkManagerFactoryConfig.java:29-55 (`fetch.chunk.cache.class`,
subclass-of-ChunkCache validated, no cache when unset).

Extended TPU-first with the device hot-window tier (ISSUE 12): when
``cache.device.bytes`` > 0 a `DeviceHotCache` is inserted between the chunk
cache and the fleet peer tier; with ``readahead.enabled`` (ISSUE 18) the
predictive readahead tier wraps OUTERMOST — it must see the raw foreground
read stream (cache hits included) to detect sequentiality, and its
speculative loads go through the full tier stack below so pre-admission IS
a cache population. The full chain reads::

    [ReadaheadManager] -> ChunkCache -> DeviceHotCache -> [PeerChunkCache]
                       -> DefaultChunkManager
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

from tieredstorage_tpu.config.configdef import (
    ConfigDef,
    ConfigKey,
    subclass_of,
    subset_with_prefix,
)
from tieredstorage_tpu.config.rsm_config import FETCH_CHUNK_CACHE_PREFIX
from tieredstorage_tpu.fetch import readahead as readahead_mod
from tieredstorage_tpu.fetch.cache import device_hot
from tieredstorage_tpu.fetch.cache.chunk_cache import ChunkCache
from tieredstorage_tpu.fetch.cache.device_hot import DeviceHotCache
from tieredstorage_tpu.fetch.chunk_manager import ChunkManager, DefaultChunkManager
from tieredstorage_tpu.fetch.readahead import ReadaheadManager
from tieredstorage_tpu.storage.core import ObjectFetcher
from tieredstorage_tpu.transform.api import TransformBackend


class ChunkManagerFactoryConfig:
    def __init__(self, props: Mapping[str, Any]):
        d = ConfigDef()
        d.define(ConfigKey(
            "fetch.chunk.cache.class", "class", default=None,
            validator=subclass_of(ChunkCache), importance="medium",
            doc="Chunk cache implementation. There are 2 implementations "
                "included: MemoryChunkCache and DiskChunkCache. Unset means "
                "no chunk caching.",
        ))
        for key in device_hot._definition().keys.values():
            d.define(key)
        for key in readahead_mod._definition().keys.values():
            d.define(key)
        self._values = d.parse(props)
        self._props = dict(props)

    @property
    def chunk_cache_class(self) -> Optional[type]:
        return self._values["fetch.chunk.cache.class"]

    @property
    def device_cache_bytes(self) -> int:
        """HBM budget of the hot-window tier; 0 disables it."""
        return self._values["cache.device.bytes"]

    @property
    def device_admission_hits(self) -> int:
        return self._values["cache.device.admission.hits"]

    @property
    def device_sketch_width(self) -> int:
        return self._values["cache.device.sketch.width"]

    @property
    def readahead_enabled(self) -> bool:
        return self._values["readahead.enabled"]

    @property
    def readahead_window_chunks(self) -> int:
        return self._values["readahead.window.chunks"]

    @property
    def readahead_streams_max(self) -> int:
        return self._values["readahead.streams.max"]

    @property
    def readahead_budget_bytes(self) -> int:
        return self._values["readahead.budget.bytes"]

    @property
    def readahead_misprediction_max_ratio(self) -> float:
        return self._values["readahead.misprediction.max.ratio"]

    def chunk_cache_configs(self) -> dict[str, Any]:
        # The stray "class" key the strip produces is ignored by the cache's
        # ConfigDef (undefined keys are skipped by parse).
        return subset_with_prefix(self._props, FETCH_CHUNK_CACHE_PREFIX)


class ChunkManagerFactory:
    def __init__(self) -> None:
        self._config: Optional[ChunkManagerFactoryConfig] = None
        #: The hot tier built by the last `init_chunk_manager` call (None
        #: when `cache.device.bytes` is 0) — the RSM wires its tracer and
        #: hot-cache-metrics gauges through this handle.
        self.device_hot_cache: Optional[DeviceHotCache] = None
        #: The readahead tier built by the last `init_chunk_manager` call
        #: (None unless ``readahead.enabled``) — the RSM wires its tracer,
        #: flight recorder, next-segment resolver, metrics gauges and the
        #: misprediction SLO spec through this handle.
        self.readahead_manager: Optional[ReadaheadManager] = None

    def configure(self, configs: Mapping[str, Any]) -> None:
        self._config = ChunkManagerFactoryConfig(configs)

    def init_chunk_manager(
        self, fetcher: ObjectFetcher, transform_backend: TransformBackend,
        inner_wrapper=None,
    ) -> ChunkManager:
        """`inner_wrapper`, when given, wraps the DefaultChunkManager BELOW
        the cache tiers (fleet mode inserts the PeerChunkCache tier there:
        local cache first, then the hot tier, then route-to-owner, then
        backend)."""
        default = DefaultChunkManager(fetcher, transform_backend)
        inner: ChunkManager = (
            inner_wrapper(default) if inner_wrapper is not None else default
        )
        self.device_hot_cache = None
        if self._config.device_cache_bytes > 0:
            # Between ChunkCache and PeerChunkCache: a local chunk-cache
            # miss tries the resident decrypted window BEFORE paying a peer
            # forward or a storage fetch + detransform.
            self.device_hot_cache = DeviceHotCache(
                inner,
                transform_backend,
                innermost=default,
                budget_bytes=self._config.device_cache_bytes,
                admission_hits=self._config.device_admission_hits,
                sketch_width=self._config.device_sketch_width,
            )
            inner = self.device_hot_cache
        cache_class = self._config.chunk_cache_class
        if cache_class is not None:
            cache: ChunkCache = cache_class(inner)
            cache.configure(self._config.chunk_cache_configs())
            inner = cache
        self.readahead_manager = None
        if self._config.readahead_enabled:
            # Outermost: the detector must observe every foreground read
            # (including the ones the cache below will serve as hits), and
            # its speculation goes through the whole chain so verified
            # plaintext lands in the cache tiers before the consumer asks.
            self.readahead_manager = ReadaheadManager(
                inner,
                window_chunks=self._config.readahead_window_chunks,
                streams_max=self._config.readahead_streams_max,
                budget_bytes=self._config.readahead_budget_bytes,
                misprediction_max_ratio=(
                    self._config.readahead_misprediction_max_ratio
                ),
            )
            inner = self.readahead_manager
        return inner
