"""Segment indexes cache: (indexes object key, index type) -> raw index bytes.

Reference: core/.../fetch/index/SegmentIndexesCache.java:28-34 (interface),
SegmentIndexKey.java (key pair), MemorySegmentIndexesCache.java (Caffeine
byte-weighed cache, 10 MiB default cap :55, single-flight `get` through the
ranged-fetch+decrypt supplier :93-120).
"""

from __future__ import annotations

import abc
import concurrent.futures
import dataclasses
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Mapping, Optional

from tieredstorage_tpu.config.cache_config import CacheConfig
from tieredstorage_tpu.manifest.segment_indexes import IndexType
from tieredstorage_tpu.storage.core import ObjectKey
from tieredstorage_tpu.utils.caching import LoadingCache


@dataclasses.dataclass(frozen=True)
class SegmentIndexKey:
    indexes_key: str
    index_type: IndexType


class SegmentIndexesCache(abc.ABC):
    @abc.abstractmethod
    def get(
        self, key: ObjectKey, index_type: IndexType, loader: Callable[[], bytes]
    ) -> bytes:
        """Cached raw index bytes; loads through `loader` at most once."""


class MemorySegmentIndexesCache(SegmentIndexesCache):
    DEFAULT_MAX_SIZE_BYTES = 10 * 1024 * 1024

    def __init__(self) -> None:
        self._cache: Optional[LoadingCache[SegmentIndexKey, bytes]] = None
        self._config: Optional[CacheConfig] = None
        self._executor: Optional[ThreadPoolExecutor] = None

    def configure(self, configs: Mapping[str, Any]) -> None:
        self._config = CacheConfig(
            configs, size_default=self.DEFAULT_MAX_SIZE_BYTES
        )
        self._executor = ThreadPoolExecutor(
            max_workers=self._config.thread_pool_size or None,
            thread_name_prefix="indexes-cache",
        )
        self._cache = LoadingCache(
            executor=self._executor,
            max_weight=self._config.cache_size,
            weigher=len,
            expire_after_access_s=self._config.retention_s,
        )

    @property
    def stats(self):
        return self._cache.stats

    @property
    def size(self) -> int:
        return len(self._cache)

    @property
    def total_weight(self) -> int:
        return self._cache.total_weight

    def get(
        self, key: ObjectKey, index_type: IndexType, loader: Callable[[], bytes]
    ) -> bytes:
        cache_key = SegmentIndexKey(key.value, index_type)
        try:
            return self._cache.get(cache_key, loader, timeout=self._config.get_timeout_s)
        except concurrent.futures.TimeoutError:
            raise TimeoutError(f"Loading index {cache_key} timed out") from None

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True, cancel_futures=True)
